"""Framework-wide metrics registry: counters, gauges, histograms.

The Prometheus data model, host-side and dependency-free: a registry
holds metric *families* (one per dotted name, e.g. ``serving.ttft_ms``),
each family holds labelled *children* (``engine="0"``), and every child
is O(1) to update under one registry lock — cheap enough for the serving
decode hot path (one lock + one float add per event, no device work).

Two export surfaces:

  * :meth:`MetricsRegistry.snapshot` — a JSON-able dict (what
    ``bench.py`` embeds into BENCH_DECODE.json and tests assert on);
  * :meth:`MetricsRegistry.prometheus_text` — the text exposition format
    (``paddle_tpu_serving_ttft_ms_bucket{engine="0",le="5"} 3``), so a
    serving host can answer a scrape endpoint with one function call.

Histograms are fixed-bucket (Prometheus-style cumulative ``le`` bounds)
with percentile readout by linear interpolation inside the bucket — the
same estimate ``histogram_quantile`` computes server-side, available
locally so TTFT/TPOT p50/p99 land in bench artifacts without a scraper.

Naming conventions (README "Observability"): dotted lowercase names,
``_ms`` suffix for millisecond histograms; exposition mangles dots to
underscores and prefixes ``paddle_tpu_``; counters gain the
``_total`` suffix Prometheus expects.
"""

from __future__ import annotations

import json
import math
import re
import threading
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "LATENCY_BUCKETS_MS", "SNAPSHOT_SCHEMA_VERSION",
           "default_registry", "snapshot", "prometheus_text", "reset"]

# bump when the snapshot() row shape changes; consumers (bench rows, CI
# diffs) key on it the same way static_analysis --json carries its
# schema version, so artifact diffs are attributable
SNAPSHOT_SCHEMA_VERSION = 1

# decade-ish spread covering sub-ms kernel dispatch through multi-second
# CPU-interpret prefills; +Inf is implicit
LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0)

_PERCENTILES = (0.5, 0.9, 0.99)


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Child:
    """One labelled time series.  Shares its family's registry lock."""

    __slots__ = ("_family", "labels")

    def __init__(self, family: "_Family", labels: Dict[str, str]):
        self._family = family
        self.labels = labels

    @property
    def _lock(self):
        return self._family._lock


class Counter(_Child):
    __slots__ = ("_value",)

    def __init__(self, family, labels):
        super().__init__(family, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> float:
        """Add ``n`` (must be >= 0); returns the new value."""
        if n < 0:
            raise ValueError(f"counters only go up (inc by {n})")
        with self._lock:
            self._value += n
            return self._value

    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Child):
    __slots__ = ("_value",)

    def __init__(self, family, labels):
        super().__init__(family, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Child):
    __slots__ = ("_counts", "_sum", "_count")

    def __init__(self, family, labels):
        super().__init__(family, labels)
        # one slot per finite bound + the +Inf overflow slot
        self._counts = [0] * (len(family.buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        bounds = self._family.buckets
        i = 0
        while i < len(bounds) and v > bounds[i]:
            i += 1
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> Optional[float]:
        """Quantile estimate (``q`` in [0, 1]) by linear interpolation
        inside the owning bucket — ``histogram_quantile`` semantics.
        ``None`` on an empty histogram; values in the +Inf bucket clamp
        to the largest finite bound."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        bounds = self._family.buckets
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return None
        rank = min(max(q * total, 1e-9), float(total))
        cum = 0
        lower = 0.0
        for i, c in enumerate(counts):
            prev = cum
            cum += c
            if prev < rank <= cum:
                if i >= len(bounds):          # +Inf bucket: clamp
                    return float(lower)
                upper = bounds[i]
                return lower + (upper - lower) * (rank - prev) / c
            if i < len(bounds):
                lower = bounds[i]
        return float(lower)

    def bucket_counts(self) -> Dict[str, int]:
        """CUMULATIVE counts keyed by the bucket's ``le`` bound."""
        bounds = self._family.buckets
        with self._lock:
            counts = list(self._counts)
        out: Dict[str, int] = {}
        cum = 0
        for b, c in zip(bounds, counts):
            cum += c
            out[_fmt_float(b)] = cum
        out["+Inf"] = cum + counts[-1]
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """All children of one metric name (shared kind/help/buckets)."""

    __slots__ = ("name", "kind", "help", "buckets", "_children", "_lock",
                 "coalesced", "_overflow_warned")

    def __init__(self, name: str, kind: str, help: str,
                 buckets: Optional[Sequence[float]], lock):
        self.name = name
        self.kind = kind
        self.help = help
        self.buckets = tuple(buckets) if buckets is not None else None
        self._children: Dict[Tuple, _Child] = {}
        self._lock = lock
        self.coalesced = 0             # label sets routed to overflow
        self._overflow_warned = False

    def labels(self, **labels: Any) -> _Child:
        key = _label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                # label-cardinality guard: a family past
                # FLAGS_metrics_max_children distinct label sets warns
                # once and coalesces every further NEW label set into a
                # single {overflow="true"} child, so per-uid/per-shape
                # labels can never grow the registry unboundedly.
                # Existing children keep resolving normally.
                from .. import flags as _flags
                cap = int(_flags.flag("metrics_max_children"))
                if cap > 0 and len(self._children) >= cap \
                        and labels.get("overflow") != "true":
                    self.coalesced += 1
                    if not self._overflow_warned:
                        self._overflow_warned = True
                        warnings.warn(
                            f"metric family {self.name!r} hit the "
                            f"label-cardinality cap ({cap} children); "
                            f"coalescing new label sets into "
                            f"{{overflow='true'}} "
                            f"(FLAGS_metrics_max_children)",
                            RuntimeWarning, stacklevel=3)
                    okey = _label_key({"overflow": "true"})
                    child = self._children.get(okey)
                    if child is None:
                        child = _KINDS[self.kind](self, dict(okey))
                        self._children[okey] = child
                    return child
                child = _KINDS[self.kind](self, dict(key))
                self._children[key] = child
            return child

    # the family itself proxies to its unlabelled child, so call sites
    # without label needs stay one-liners
    def inc(self, n: float = 1.0):
        return self.labels().inc(n)

    def set(self, v: float):
        return self.labels().set(v)

    def dec(self, n: float = 1.0):
        return self.labels().dec(n)

    def observe(self, v: float):
        return self.labels().observe(v)

    def value(self, **labels: Any) -> float:
        return self.labels(**labels).value()

    def children(self) -> List[_Child]:
        with self._lock:
            return list(self._children.values())


class MetricsRegistry:
    """Thread-safe registry of metric families.

    ``counter``/``gauge``/``histogram`` are idempotent declarations: the
    first call creates the family, later calls return it (and re-declare
    with a conflicting kind or bucket layout raise, so two subsystems
    cannot silently share a name with different meanings).
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}

    def _family(self, name: str, kind: str, help: str,
                buckets: Optional[Sequence[float]] = None) -> _Family:
        if not name or not re.match(r"^[a-zA-Z_][a-zA-Z0-9_.]*$", name):
            raise ValueError(f"bad metric name {name!r} (use dotted "
                             f"lowercase identifiers)")
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help, buckets, self._lock)
                self._families[name] = fam
            else:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, not {kind}")
                if (kind == "histogram" and buckets is not None
                        and fam.buckets != tuple(buckets)):
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"different buckets")
            return fam

    def counter(self, name: str, help: str = "") -> _Family:
        return self._family(name, "counter", help)

    def gauge(self, name: str, help: str = "") -> _Family:
        return self._family(name, "gauge", help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS_MS) -> _Family:
        return self._family(name, "histogram", help, buckets)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def reset(self) -> None:
        """Drop every family (test isolation; children held by live
        objects keep working but stop being exported)."""
        with self._lock:
            self._families.clear()

    # -- export ------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able dump of every series: counters/gauges as values,
        histograms with count/sum/percentiles/cumulative buckets.

        Deterministically ordered (families sorted by name, series by
        label items, ``schema_version`` first) so two snapshots of the
        same state serialize byte-identically — the static_analysis
        ``--json`` convention, which keeps bench artifacts and CI diffs
        stable across reruns."""
        with self._lock:
            families = list(self._families.values())
        out: Dict[str, Any] = {"schema_version": SNAPSHOT_SCHEMA_VERSION}
        for fam in sorted(families, key=lambda f: f.name):
            series = []
            for child in fam.children():
                row: Dict[str, Any] = {"labels": dict(child.labels)}
                if fam.kind == "histogram":
                    row["count"] = child.count
                    row["sum"] = round(child.sum, 6)
                    for q in _PERCENTILES:
                        p = child.percentile(q)
                        if p is not None:
                            row[f"p{int(q * 100)}"] = round(p, 6)
                    row["buckets"] = child.bucket_counts()
                else:
                    row["value"] = child.value()
                series.append(row)
            series.sort(key=lambda r: sorted(r["labels"].items()))
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "series": series}
        json.dumps(out)  # guarantee the contract (catches NaN/Inf early)
        return out

    def prometheus_text(self, prefix: str = "paddle_tpu") -> str:
        """Prometheus/OpenMetrics text exposition of every series."""
        with self._lock:
            families = list(self._families.values())
        lines: List[str] = []
        for fam in sorted(families, key=lambda f: f.name):
            base = _expo_name(fam.name, prefix)
            if fam.kind == "counter":
                base += "_total"
            if fam.help:
                lines.append(f"# HELP {base} {_expo_help(fam.help)}")
            lines.append(f"# TYPE {base} {fam.kind}")
            for child in fam.children():
                if fam.kind == "histogram":
                    for le, c in child.bucket_counts().items():
                        lines.append(f"{base}_bucket"
                                     f"{_expo_labels(child.labels, le=le)}"
                                     f" {c}")
                    lab = _expo_labels(child.labels)
                    lines.append(f"{base}_sum{lab} {_fmt_float(child.sum)}")
                    lines.append(f"{base}_count{lab} {child.count}")
                else:
                    lines.append(f"{base}{_expo_labels(child.labels)} "
                                 f"{_fmt_float(child.value())}")
        return "\n".join(lines) + "\n"


def _expo_name(name: str, prefix: str) -> str:
    return f"{prefix}_" + re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _expo_help(text: str) -> str:
    # exposition format: HELP text escapes backslash and newline (a raw
    # newline would terminate the comment mid-text and corrupt the scrape)
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _expo_value(v: str) -> str:
    # label values escape backslash, newline AND double-quote
    return (v.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _expo_labels(labels: Dict[str, str], le: Optional[str] = None) -> str:
    items = sorted(labels.items())
    if le is not None:
        items.append(("le", le))
    if not items:
        return ""
    body = ",".join('{}="{}"'.format(k, _expo_value(v))
                    for k, v in items)
    return "{" + body + "}"


def _fmt_float(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


# -- module-level default registry ------------------------------------------

_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem reports into."""
    return _default


def snapshot() -> Dict[str, Any]:
    return _default.snapshot()


def prometheus_text() -> str:
    return _default.prometheus_text()


def reset() -> None:
    _default.reset()
