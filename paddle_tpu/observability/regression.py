"""Perf-regression sentinel (ISSUE 15 tentpole c).

Two halves:

  * :class:`EwmaDetector` — a calibrate-then-monitor anomaly detector
    for runtime perf streams (TTFT / TPOT / tick-time / measured-over-
    predicted ratio).  The first ``skip`` samples are discarded (jit
    compiles land in the first measure windows), the next ``warmup``
    samples average into a baseline, and from then on an EWMA of the
    stream must stay inside ``[baseline/(1+tol), baseline*(1+tol)]``.
    Latency streams monitor the upper side only (getting faster is not
    an anomaly); the cost-model drift detectors run two-sided (a model
    that suddenly over- or under-predicts is broken either way).
    Detections feed the ``serving.perf_anomalies{kind=}`` counters via
    :class:`.costmodel.TickAttribution`.

  * :func:`check_history` — the offline gate behind
    ``bench.py --check-history``: parse the committed ``BENCH_r*.json``
    training-bench trajectory and the ``BENCH_DECODE.json`` serving
    artifact and fail (exit non-zero) when a tracked metric regresses
    past its committed tolerance in :data:`HISTORY_TOLERANCES`.  This
    turns the bench artifacts from documentation into a gate: a PR that
    lands a slower decode row or a fatter int8 streamed-bytes ratio
    fails CI instead of relying on a reviewer eyeballing the diff.

Thresholds and their provenance are documented in BASELINE.md
"Cost-model accounting conventions".
"""

from __future__ import annotations

import glob
import json
import os
import re
import weakref
from typing import Any, Dict, List, Optional

__all__ = ["EwmaDetector", "HISTORY_TOLERANCES", "check_history", "reset"]


_LIVE: "weakref.WeakSet[EwmaDetector]" = weakref.WeakSet()


class EwmaDetector:
    """Calibrate-then-monitor EWMA threshold detector on one stream."""

    def __init__(self, kind: str, *, tol: float, alpha: float = 0.25,
                 warmup: int = 8, skip: int = 2,
                 two_sided: bool = False) -> None:
        self.kind = str(kind)
        self.tol = float(tol)
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.skip = int(skip)
        self.two_sided = bool(two_sided)
        self.reset()
        _LIVE.add(self)

    def reset(self) -> None:
        self.seen = 0
        self.baseline: Optional[float] = None
        self.ewma: Optional[float] = None
        self.anomalies = 0
        self._cal: List[float] = []

    @property
    def lo(self) -> float:
        base = self.baseline or 0.0
        return base / (1.0 + self.tol)

    @property
    def hi(self) -> float:
        base = self.baseline or 0.0
        return base * (1.0 + self.tol)

    def observe(self, v: float) -> bool:
        """Feed one sample; True when the post-calibration EWMA sits
        outside the band at this sample."""
        v = float(v)
        self.seen += 1
        if self.seen <= self.skip:
            return False
        if self.baseline is None:
            self._cal.append(v)
            if len(self._cal) >= self.warmup:
                self.baseline = sum(self._cal) / len(self._cal)
                self.ewma = self.baseline
                self._cal = []
            return False
        self.ewma = (1.0 - self.alpha) * self.ewma + self.alpha * v
        bad = self.ewma > self.hi or (self.two_sided and self.ewma < self.lo)
        if bad:
            self.anomalies += 1
        return bad

    def state(self) -> Dict[str, Any]:
        return {"kind": self.kind, "seen": self.seen,
                "baseline": self.baseline, "ewma": self.ewma,
                "anomalies": self.anomalies, "tol": self.tol,
                "two_sided": self.two_sided}


# -- committed history gate (bench.py --check-history) --------------------

#: Committed tolerances the history gate enforces.  Meanings
#: (BASELINE.md): *_drop_frac — a tracked higher-is-better metric's
#: latest committed value may sit at most this fraction below the best
#: previously committed value; the absolute floors/ceilings restate the
#: invariants the BENCH sections themselves gate, so a hand-edited (or
#: regressed re-run) artifact fails here even without re-running the
#: bench.
HISTORY_TOLERANCES: Dict[str, float] = {
    # BENCH_r*.json training-bench trajectory (parsed.value = MFU)
    "mfu_drop_frac": 0.05,
    # cpu_plumbing_smoke.int8_serving: int8/full streamed cache bytes
    # per context token (committed 0.254; the int8 PR gates <= 0.55x)
    "int8_streamed_ratio_max": 0.55,
    # cpu_plumbing_smoke.int8_serving capacity at equal pool bytes
    "int8_capacity_ratio_min": 1.8,
    # llama_940m_serving.decode: absolute floors restating the
    # committed rows — head row (b=1, 2048) runs 385.9 tok/s/chip and
    # the worst row (b=8 paged, 2048) sits at 0.652 of the
    # weight-stream bound; a regressed re-run (or hand-edit) that lands
    # below these fails the gate
    "decode_head_tok_s_floor": 347.0,
    "decode_of_bound_min": 0.60,
    # every serving section must keep the once-jitted step contract
    "step_traces_max": 1.0,
}


def _check(name: str, ok: Optional[bool], detail: str) -> Dict[str, Any]:
    return {"name": name, "ok": ok, "detail": detail}


def _bench_r_trajectory(root: str) -> List[Dict[str, Any]]:
    rows = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                blob = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = blob.get("parsed") or {}
        if "value" in parsed:
            rows.append({"n": int(m.group(1)),
                         "metric": parsed.get("metric", ""),
                         "value": float(parsed["value"])})
    rows.sort(key=lambda r: r["n"])
    return rows


def check_history(root: Optional[str] = None,
                  tolerances: Optional[Dict[str, float]] = None)\
        -> Dict[str, Any]:
    """Validate the committed bench trajectory under ``root`` (default:
    the repo root, two levels above this package).  Returns
    ``{"ok": bool, "checks": [...]}``; a check over a missing artifact
    reports ``ok: None`` (skipped) rather than failing, so partial
    checkouts stay green — the committed repo carries every artifact."""
    tol = dict(HISTORY_TOLERANCES)
    if tolerances:
        tol.update(tolerances)
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    checks: List[Dict[str, Any]] = []

    # 1) training-bench MFU trajectory: monotone-ish — the latest run
    # may not fall more than mfu_drop_frac below the best committed run
    rows = _bench_r_trajectory(root)
    if len(rows) >= 2:
        best = max(r["value"] for r in rows[:-1])
        last = rows[-1]["value"]
        floor = best * (1.0 - tol["mfu_drop_frac"])
        checks.append(_check(
            "bench_r_mfu_trajectory", last >= floor,
            f"latest {last:.4f} vs best {best:.4f} "
            f"(floor {floor:.4f}, n={[r['n'] for r in rows]})"))
    else:
        checks.append(_check("bench_r_mfu_trajectory", None,
                             f"only {len(rows)} BENCH_r rows"))

    # 2) BENCH_DECODE.json invariants
    decode_path = os.path.join(root, "BENCH_DECODE.json")
    blob: Dict[str, Any] = {}
    if os.path.exists(decode_path):
        try:
            with open(decode_path) as f:
                blob = json.load(f)
        except ValueError as e:
            checks.append(_check("bench_decode_parse", False, str(e)))
    if not blob:
        checks.append(_check("bench_decode_present", None,
                             "no BENCH_DECODE.json"))
    cpu = blob.get("cpu_plumbing_smoke", {})
    int8 = cpu.get("int8_serving", {})
    sb = int8.get("per_step_streamed_cache_bytes", {})
    if "ratio" in sb:
        checks.append(_check(
            "int8_streamed_bytes_ratio",
            float(sb["ratio"]) <= tol["int8_streamed_ratio_max"],
            f"int8/full per-context-token streamed bytes "
            f"{sb['ratio']} (max {tol['int8_streamed_ratio_max']})"))
    cap = int8.get("capacity_at_equal_pool_bytes", {})
    if "capacity_ratio" in cap:
        checks.append(_check(
            "int8_capacity_ratio",
            float(cap["capacity_ratio"]) >= tol["int8_capacity_ratio_min"],
            f"int8 capacity ratio {cap['capacity_ratio']} "
            f"(min {tol['int8_capacity_ratio_min']})"))
    # every committed step_traces count anywhere in the artifact must
    # honour the once-jitted contract
    bad_traces: List[str] = []

    def _walk(node: Any, path: str) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                p = f"{path}.{k}" if path else str(k)
                if k == "step_traces" and isinstance(v, (int, float)):
                    if v > tol["step_traces_max"]:
                        bad_traces.append(f"{p}={v}")
                else:
                    _walk(v, p)
        elif isinstance(node, list):
            for i, v in enumerate(node):
                _walk(v, f"{path}[{i}]")

    _walk(blob, "")
    checks.append(_check(
        "step_traces_budget", not bad_traces if blob else None,
        "all committed step_traces <= "
        f"{int(tol['step_traces_max'])}" if not bad_traces
        else f"over budget: {bad_traces}"))
    # deterministic-replay booleans committed by serving sections
    det_flags = []

    def _walk_det(node: Any, path: str) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                p = f"{path}.{k}" if path else str(k)
                if k.startswith("deterministic") and isinstance(v, bool):
                    det_flags.append((p, v))
                else:
                    _walk_det(v, p)

    _walk_det(blob, "")
    if det_flags:
        bad = [p for p, v in det_flags if not v]
        checks.append(_check(
            "deterministic_replay", not bad,
            f"{len(det_flags)} committed determinism flags"
            + (f"; false: {bad}" if bad else "")))
    # TPU decode rows: absolute floors restating the committed values
    dec = blob.get("llama_940m_serving", {}).get("decode")
    if isinstance(dec, list) and dec:
        head = dec[0]
        tps = head.get("tokens_per_sec_per_chip")
        if tps is not None:
            checks.append(_check(
                "decode_head_tok_s",
                float(tps) >= tol["decode_head_tok_s_floor"],
                f"head row {tps} tok/s/chip "
                f"(floor {tol['decode_head_tok_s_floor']})"))
        bounds = [float(r["of_weight_stream_bound"]) for r in dec
                  if "of_weight_stream_bound" in r]
        if bounds:
            checks.append(_check(
                "decode_of_weight_stream_bound",
                min(bounds) >= tol["decode_of_bound_min"],
                f"worst row {min(bounds)} of the weight-stream bound "
                f"(floor {tol['decode_of_bound_min']})"))
    # SLO goodput ordering: chunked admission must not regress below
    # the wave scheduler on the committed trace
    slo = cpu.get("slo_serving", {})
    if "chunked_strictly_better" in slo:
        checks.append(_check(
            "slo_chunked_goodput", bool(slo["chunked_strictly_better"]),
            "chunked goodput strictly better than wave on the "
            "committed deadline trace"))
    # perf_model section self-consistency (present once the section ran)
    pm = cpu.get("perf_model", {})
    if pm:
        ok = (pm.get("drift_findings", 1) == 0
              and pm.get("kv_ratio_consistent", False))
        checks.append(_check(
            "perf_model_row", ok,
            f"drift_findings={pm.get('drift_findings')} "
            f"kv_ratio_consistent={pm.get('kv_ratio_consistent')}"))
    # preempt_serving (ISSUE 16): the committed A/B must keep the
    # preemptive engines' goodput win, token-identity across all three
    # engines, and the byte-stable victim-decision signature
    ps = cpu.get("preempt_serving", {})
    if ps:
        ok = (bool(ps.get("preempt_goodput_strictly_better"))
              and bool(ps.get("outputs_token_identical"))
              and bool(ps.get("preempt_signature_stable")))
        checks.append(_check(
            "preempt_serving_row", ok,
            f"goodput_strictly_better="
            f"{ps.get('preempt_goodput_strictly_better')} "
            f"token_identical={ps.get('outputs_token_identical')} "
            f"decision_signature_stable="
            f"{ps.get('preempt_signature_stable')}"))

    # disagg_serving (ISSUE 18): the committed multi-host A/B must keep
    # the disaggregation win — decode-cohort TPOT p99 strictly better
    # with prefill burn moved off the decode worker, token-identical
    # outputs across arms, migration bytes actually accounted (every
    # decode-cohort request migrated, bytes > 0), and byte-stable
    # replay of both arms
    ds = cpu.get("disagg_serving", {})
    if ds:
        mig = ds.get("disaggregated", {})
        ok = (bool(ds.get("decode_tpot_strictly_better"))
              and bool(ds.get("outputs_token_identical"))
              and bool(ds.get("migrations_cover_decode_cohort"))
              and int(mig.get("migration_bytes", 0)) > 0
              and bool(ds.get("deterministic_replay")))
        checks.append(_check(
            "disagg_serving_row", ok,
            f"tpot_strictly_better="
            f"{ds.get('decode_tpot_strictly_better')} "
            f"token_identical={ds.get('outputs_token_identical')} "
            f"migrations_cover_decode_cohort="
            f"{ds.get('migrations_cover_decode_cohort')} "
            f"migration_bytes={mig.get('migration_bytes')} "
            f"deterministic={ds.get('deterministic_replay')}"))

    # control_plane (ISSUE 17): predictive admission must hold its
    # committed win — goodput at-or-above the reactive baseline with a
    # strict win on >= 1 SLO class, token-identity where both arms
    # admitted, a deterministic autoscaler action trace, and the fleet
    # simulator's 100k x 16 scale row inside the <60 s host-wall budget
    cp = cpu.get("control_plane", {})
    if cp:
        ok = (bool(cp.get("predictive_goodput_ge"))
              and bool(cp.get("strictly_better_classes"))
              and bool(cp.get("outputs_token_identical_where_both_admit"))
              and bool(cp.get("deterministic_replay")))
        checks.append(_check(
            "control_plane_row", ok,
            f"goodput_ge={cp.get('predictive_goodput_ge')} "
            f"class_wins={cp.get('strictly_better_classes')} "
            f"token_identical="
            f"{cp.get('outputs_token_identical_where_both_admit')} "
            f"deterministic={cp.get('deterministic_replay')}"))
        asc = cp.get("autoscale", {})
        if asc:
            ok = (bool(asc.get("deterministic"))
                  and bool(asc.get("scaled_up_under_pressure"))
                  and bool(asc.get("drained_then_retired_on_slack")))
            checks.append(_check(
                "autoscale_row", ok,
                f"deterministic={asc.get('deterministic')} "
                f"scaled_up={asc.get('scaled_up_under_pressure')} "
                f"drain_retire="
                f"{asc.get('drained_then_retired_on_slack')}"))
        fl = cp.get("fleet_sim", {})
        if fl:
            ok = (bool(fl.get("under_60s_host_wall"))
                  and int(fl.get("requests", 0)) >= 100_000
                  and int(fl.get("replicas", 0)) >= 16)
            checks.append(_check(
                "fleet_sim_row", ok,
                f"{fl.get('requests')} req x {fl.get('replicas')} "
                f"replicas in {fl.get('host_wall_s')} s host "
                f"(sim {fl.get('sim_wall_s')} s)"))

    # spec_model (ISSUE 20): the committed drafter A/B must keep the
    # draft-model win — accepted/step strictly above n-gram on the
    # novel-text trace (where prompt-lookup starves), greedy parity on
    # both traces, deterministic replay, zero lint findings, and the
    # mesh trace actually routed to the shard_map Pallas path
    smr = cpu.get("spec_model", {})
    if smr:
        mesh_ok = any(
            r.get("chosen_path") == "pallas_decode_shard_map"
            for r in smr.get("mesh_paths", [])) \
            or not smr.get("mesh_paths")
        ok = (bool(smr.get("model_beats_ngram_on_novel"))
              and bool(smr.get("novel_text", {}).get("greedy_parity"))
              and bool(smr.get("repetition_heavy", {})
                       .get("greedy_parity"))
              and bool(smr.get("deterministic_replay"))
              and int(smr.get("lint_findings", 1)) == 0
              and mesh_ok)
        checks.append(_check(
            "spec_model_row", ok,
            f"model_beats_ngram_on_novel="
            f"{smr.get('model_beats_ngram_on_novel')} parity="
            f"{smr.get('novel_text', {}).get('greedy_parity')}/"
            f"{smr.get('repetition_heavy', {}).get('greedy_parity')} "
            f"deterministic={smr.get('deterministic_replay')} "
            f"lint_findings={smr.get('lint_findings')} "
            f"shard_map_routed={mesh_ok}"))

    # multihost_obs (ISSUE 19): the committed federated-observability
    # row must keep its fidelity invariants — every worker's recovered
    # clock offset inside the estimator's own min-RTT error bound, the
    # pooled TTFT p99 (recomputed from summed buckets) inside the
    # per-worker p99 envelope, byte-stable fleet-obs signature across
    # identical-seed replays, and the surviving once-jit budget
    mo = cpu.get("multihost_obs", {})
    if mo:
        ok = (bool(mo.get("offset_within_bound"))
              and mo.get("pooled_p99_within_worker_envelope") is not False
              and bool(mo.get("deterministic_replay"))
              and int(mo.get("step_traces", 99)) <= 1)
        checks.append(_check(
            "multihost_obs_row", ok,
            f"offset_within_bound={mo.get('offset_within_bound')} "
            f"(worst err {mo.get('offset_worst_error_ms')} ms) "
            f"pooled_p99_in_envelope="
            f"{mo.get('pooled_p99_within_worker_envelope')} "
            f"deterministic={mo.get('deterministic_replay')} "
            f"step_traces={mo.get('step_traces')}"))

    ok = all(c["ok"] is not False for c in checks)
    return {"ok": ok, "root": root, "tolerances": tol, "checks": checks}


def reset() -> None:
    """Reset every live detector (observability.reset())."""
    for det in list(_LIVE):
        det.reset()
