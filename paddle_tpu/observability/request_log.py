"""Per-request lifecycle log: the request-granular half of observability.

The metrics registry answers "how is the fleet doing" in aggregates; a
capacity decision ("which requests missed their deadline, and WHERE did
the time go?") needs per-request timelines.  This module is that
substrate: every serving request carries one process-wide **uid** minted
at ``submit()`` and threaded router → replica → engine → slot, and every
lifecycle transition appends a structured event here:

  ``submitted`` → (``rejected`` | ``placed``? → ``admitted``) →
  ``prefill`` | ``prefill_chunk``* → ``first_token`` →
  ``spec_accept``* → ``retired``

plus ``admission_wait`` when a paged pool defers admission (the
preemption-relevant wait), and — under the preemptive scheduler —
``preempted`` → (``swapped_out`` → ``swapped_in``)? → ``resumed``
mid-decode cycles (any number of them per request) and a terminal
``retired`` with ``violation="cancelled"`` when ``cancel(rid)`` pulls
the request mid-flight.  Each event also mirrors into the span
tracer as a ``request.<name>`` instant with the uid as correlation arg,
so the per-request story lines up against the host span timeline in one
Perfetto load.

Three read surfaces:

  * :meth:`RequestLog.export_perfetto` — Trace Event JSON with ONE
    NAMED TRACK PER REQUEST (tid = uid, ``thread_name`` metadata) and
    queued/prefill/decode phase slices derived from the events;
  * :meth:`RequestLog.timeline_signature` — the structural timeline
    with uids, timings and per-process ids stripped: two identical-seed
    replays of the same load MUST produce equal signatures (the
    loadgen determinism contract, BASELINE.md "SLO accounting
    conventions");
  * :meth:`RequestLog.slo_report` — joins the recorded timelines
    against TTFT/TPOT deadlines (per-request targets recorded at
    submit from FLAGS_serving_slo_ttft_ms / FLAGS_serving_slo_tpot_ms,
    or explicit overrides) into goodput (fraction + tok/s of
    SLO-attaining requests) and a violation breakdown by cause
    (rejected / cancelled / queue_wait / prefill / decode).

Cost discipline: one lock + one list append per event, no device work;
events fire at scheduling transitions only (admission, chunk, accept,
retirement) — never per decoded token.  The store is bounded
(FLAGS_request_log_max_requests): oldest whole requests drop first and
are counted, exactly like the span tracer's ring.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["RequestLog", "get_request_log"]

# attrs stripped from timeline_signature(): per-process ids (engine /
# router ids are global counters, different on every run) and wall-clock
# measurements; everything else — slots, chunk sizes, token counts,
# reasons — must replay bit-identically under the same seed
_SIGNATURE_SKIP = ("engine", "replica", "router", "violation")


def _pct(vals: List[float], q: float) -> float:
    """numpy.percentile(..., interpolation='linear') on a sorted copy —
    local so the observability layer stays dependency-free."""
    s = sorted(vals)
    if not s:
        return 0.0
    k = (len(s) - 1) * q
    lo, hi = int(k), min(int(k) + 1, len(s) - 1)
    return s[lo] + (s[hi] - s[lo]) * (k - lo)


class RequestLog:
    """Bounded, thread-safe store of per-request event timelines."""

    def __init__(self, max_requests: Optional[int] = None):
        from .. import flags as _flags
        if max_requests is None:
            max_requests = int(_flags.flag("request_log_max_requests"))
        self.max_requests = max(1, int(max_requests))
        self.dropped = 0                     # whole requests evicted
        self._uids = itertools.count(1)
        self._last_uid = 0
        self._records: "OrderedDict[int, List[Dict[str, Any]]]" = \
            OrderedDict()
        self._lock = threading.Lock()
        self._pid = os.getpid()
        # the clock seam: event timestamps read (self._clock() - _t0).
        # Simulated fleets swap both for a virtual clock so timelines
        # (and, through transport._default_clock_ms, RPC stitching)
        # replay byte-deterministically.
        self._clock = time.perf_counter
        self._t0 = time.perf_counter()

    # -- recording ---------------------------------------------------------

    def new_uid(self) -> int:
        """Mint the next request uid (process-wide, monotonic).  Uids
        are correlation keys, not identities: signatures and SLO joins
        never depend on their absolute values."""
        with self._lock:
            self._last_uid = next(self._uids)
            return self._last_uid

    def mark(self) -> int:
        """High-water uid: pass to ``timeline_signature`` /
        ``slo_report`` / ``export_perfetto`` as ``since_uid`` to scope a
        readout to requests submitted after this point (how ``replay``
        segments one run out of a shared log)."""
        with self._lock:
            return self._last_uid

    def now_ms(self) -> float:
        """This log's relative clock reading (ms) — the base every
        event timestamp, and the plane/worker RPC stitch, shares."""
        return (self._clock() - self._t0) * 1e3

    def event(self, uid: int, name: str, t_ms: Optional[float] = None,
              **attrs: Any) -> None:
        """Append one lifecycle event and mirror it into the span
        tracer as a ``request.<name>`` instant with ``uid`` as the
        correlation arg.  ``t_ms`` overrides the stamp — how a plane
        merges a worker's shipped events at their clock-stitched plane
        time instead of their arrival time."""
        if t_ms is None:
            t_ms = self.now_ms()
        ev = {"name": name, "t_ms": float(t_ms), "attrs": dict(attrs)}
        with self._lock:
            rec = self._records.get(uid)
            if rec is None:
                while len(self._records) >= self.max_requests:
                    self._records.popitem(last=False)
                    self.dropped += 1
                rec = self._records[uid] = []
            rec.append(ev)
        from .tracing import get_tracer
        get_tracer().instant(f"request.{name}", cat="request", uid=uid,
                             **attrs)

    # -- readout -----------------------------------------------------------

    def timeline(self, uid: int) -> List[Dict[str, Any]]:
        """One request's events, in emission order (copies)."""
        with self._lock:
            return [dict(ev, attrs=dict(ev["attrs"]))
                    for ev in self._records.get(uid, [])]

    def records(self, since_uid: int = 0, until_uid: Optional[int] = None
                ) -> "OrderedDict[int, List[Dict[str, Any]]]":
        """All timelines with ``since_uid < uid <= until_uid`` (None =
        no upper bound), keyed by uid in submission order (copies).
        Bracketing a run with two ``mark()`` calls and passing both
        bounds scopes a readout to exactly that run, however many runs
        share the log."""
        with self._lock:
            return OrderedDict(
                (uid, [dict(ev, attrs=dict(ev["attrs"])) for ev in rec])
                for uid, rec in self._records.items()
                if uid > since_uid
                and (until_uid is None or uid <= until_uid))

    def event_names(self, uid: int) -> List[str]:
        with self._lock:
            return [ev["name"] for ev in self._records.get(uid, [])]

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self.dropped = 0

    def timeline_signature(self, since_uid: int = 0,
                           until_uid: Optional[int] = None) -> List[Tuple]:
        """The structural timeline, one tuple per request in submission
        order: event names plus their DETERMINISTIC attrs (uids,
        ``*_ms`` timings and per-process engine/router ids stripped).
        Two identical-seed replays of the same load must compare equal
        — the loadgen determinism contract."""
        out: List[Tuple] = []
        for rec in self.records(since_uid, until_uid).values():
            sig = []
            for ev in rec:
                attrs = tuple(sorted(
                    (k, v) for k, v in ev["attrs"].items()
                    if k not in _SIGNATURE_SKIP
                    and not k.endswith("_ms")))
                sig.append((ev["name"], attrs))
            out.append(tuple(sig))
        return out

    # -- Perfetto export ---------------------------------------------------

    def export_perfetto(self, path: Optional[str] = None,
                        since_uid: int = 0,
                        until_uid: Optional[int] = None) -> Dict[str, Any]:
        """Trace Event JSON with one named track per request: tid =
        uid under a dedicated "paddle_tpu requests" process, every
        lifecycle event as an instant, and queued / prefill / decode
        phase slices reconstructed from the submitted → admitted →
        first_token → retired timestamps.  Loads in ui.perfetto.dev /
        chrome://tracing as-is; ``path`` additionally writes the file."""
        recs = self.records(since_uid, until_uid)
        meta: List[Dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": self._pid,
             "tid": 0, "args": {"name": "paddle_tpu requests"}}]
        events: List[Dict[str, Any]] = []
        for uid, rec in recs.items():
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self._pid, "tid": uid,
                         "args": {"name": f"request {uid}"}})
            t_of: Dict[str, float] = {}
            for ev in rec:
                t_of.setdefault(ev["name"], ev["t_ms"])
                events.append({"name": ev["name"], "cat": "request",
                               "ph": "i", "s": "t",
                               "ts": ev["t_ms"] * 1e3,
                               "pid": self._pid, "tid": uid,
                               "args": dict(ev["attrs"], uid=uid)})
            # phase slices: submit→admit (queued), admit→first token
            # (prefill incl. any admission wait), first→retired (decode)
            for phase, a, b in (
                    ("queued", "submitted", "admitted"),
                    ("queued", "submitted", "rejected"),
                    ("prefill", "admitted", "first_token"),
                    ("decode", "first_token", "retired"),
                    # gap the preemptive scheduler evicted this request
                    # for (first preemption to first resume; nested
                    # cycles merge into one slice)
                    ("preempted", "preempted", "resumed")):
                if a in t_of and b in t_of and t_of[b] >= t_of[a]:
                    events.append({
                        "name": phase, "cat": "request", "ph": "X",
                        "ts": t_of[a] * 1e3,
                        "dur": (t_of[b] - t_of[a]) * 1e3,
                        "pid": self._pid, "tid": uid,
                        "args": {"uid": uid}})
        trace = {"traceEvents": meta + events,
                 "displayTimeUnit": "ms",
                 "otherData": {"producer":
                               "paddle_tpu.observability.request_log",
                               "dropped_requests": self.dropped}}
        if path is not None:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                json.dump(trace, f)
        return trace

    # -- SLO goodput -------------------------------------------------------

    def slo_report(self, since_uid: int = 0,
                   until_uid: Optional[int] = None,
                   ttft_ms: Optional[float] = None,
                   tpot_ms: Optional[float] = None,
                   wall_s: Optional[float] = None) -> Dict[str, Any]:
        """Join the recorded timelines against TTFT/TPOT deadlines.

        Targets default to the per-request values recorded at submit
        (FLAGS_serving_slo_ttft_ms / FLAGS_serving_slo_tpot_ms at the
        time; 0 = that deadline disabled); explicit ``ttft_ms`` /
        ``tpot_ms`` override them — the post-hoc join bench rows use.
        Conventions (BASELINE.md "SLO accounting conventions"): the
        goodput denominator counts EVERY submitted request, rejected
        ones included; TTFT is measured from submit, not admit; a
        violating request is attributed to exactly one cause —
        ``rejected``, else ``cancelled`` (retired via ``cancel(rid)``
        — rejected-style: in the denominator, never attaining), else a
        missed TTFT to its larger segment (``queue_wait`` vs
        ``prefill``), else a missed TPOT to ``decode``; a request still
        in flight counts as ``incomplete`` (never SLO-attaining).

        Fleet attribution (ISSUE 19): when timelines carry placement
        (``placed``/``migrated`` with a ``worker`` attr, or engine
        events), the report gains a ``by_worker`` section attributing
        every request's outcome to its LAST hosting worker (the one a
        migrated/failed-over request retired on) — the same join works
        for a multihost plane on the plane clock and for ``FleetSim``'s
        per-replica simulated clocks (keyed ``engine:<id>`` there)."""
        recs = self.records(since_uid, until_uid)
        total = len(recs)
        attained = 0
        attained_tokens = 0
        ttfts: List[float] = []
        tpots: List[float] = []
        viol = {"rejected": 0, "cancelled": 0, "queue_wait": 0,
                "prefill": 0, "decode": 0, "incomplete": 0}
        by_worker: Dict[str, Dict[str, Any]] = {}

        def tally(wkey: Optional[str], outcome: str) -> None:
            if wkey is None:
                return
            w = by_worker.setdefault(
                wkey, {"requests": 0, "attained": 0, "violations": {}})
            w["requests"] += 1
            if outcome == "attained":
                w["attained"] += 1
            else:
                w["violations"][outcome] = \
                    w["violations"].get(outcome, 0) + 1

        recorded_targets = set()
        for rec in recs.values():
            by = {}
            wkey: Optional[str] = None
            for ev in rec:
                by.setdefault(ev["name"], ev["attrs"])
                if ev["name"] in ("placed", "migrated") \
                        and ev["attrs"].get("worker") is not None:
                    wkey = str(ev["attrs"]["worker"])
                elif wkey is None \
                        and ev["attrs"].get("engine") is not None:
                    wkey = f"engine:{ev['attrs']['engine']}"
            sub = by.get("submitted", {})
            t_ttft = (float(sub.get("ttft_slo_ms", 0.0))
                      if ttft_ms is None else float(ttft_ms))
            t_tpot = (float(sub.get("tpot_slo_ms", 0.0))
                      if tpot_ms is None else float(tpot_ms))
            recorded_targets.add((t_ttft, t_tpot))
            if "rejected" in by and "admitted" not in by:
                viol["rejected"] += 1
                tally(wkey, "rejected")
                continue
            ret = by.get("retired")
            if ret is None:
                viol["incomplete"] += 1
                tally(wkey, "incomplete")
                continue
            if ret.get("reason") == "cancelled":
                viol["cancelled"] += 1
                tally(wkey, "cancelled")
                continue
            ttft = ret.get("ttft_ms")
            tpot = ret.get("tpot_ms")
            if ttft is not None:
                ttfts.append(float(ttft))
            if tpot is not None:
                tpots.append(float(tpot))
            kind = None
            if t_ttft > 0 and ttft is not None and ttft > t_ttft:
                qw = float(by.get("admitted", {}).get("queue_wait_ms",
                                                      0.0))
                kind = ("queue_wait" if qw >= float(ttft) - qw
                        else "prefill")
            elif t_tpot > 0 and tpot is not None and tpot > t_tpot:
                kind = "decode"
            if kind is None:
                attained += 1
                attained_tokens += int(ret.get("tokens", 0))
                tally(wkey, "attained")
            else:
                viol[kind] += 1
                tally(wkey, kind)

        def dist(vals):
            return {"count": len(vals),
                    "p50": round(_pct(vals, 0.50), 3),
                    "p99": round(_pct(vals, 0.99), 3)}

        if ttft_ms is not None or tpot_ms is not None:
            targets = {"ttft": float(ttft_ms or 0.0),
                       "tpot": float(tpot_ms or 0.0)}
        elif len(recorded_targets) == 1:
            t = recorded_targets.pop()
            targets = {"ttft": t[0], "tpot": t[1]}
        else:
            targets = {"ttft": "per_request", "tpot": "per_request"}
        out: Dict[str, Any] = {
            "requests": total,
            "attained": attained,
            "goodput": round(attained / total, 4) if total else 0.0,
            "attained_tokens": attained_tokens,
            "targets_ms": targets,
            "violations": viol,
            "ttft_ms": dist(ttfts),
            "tpot_ms": dist(tpots)}
        if by_worker:
            out["by_worker"] = {k: by_worker[k]
                                for k in sorted(by_worker)}
        if wall_s:
            out["goodput_tok_s"] = round(attained_tokens / wall_s, 1)
        return out


# -- module-level default log ------------------------------------------------

_log: Optional[RequestLog] = None
_log_lock = threading.Lock()


def get_request_log() -> RequestLog:
    """The process-wide request log every engine/router records into
    (created lazily so FLAGS_* read their environment overrides
    first)."""
    global _log
    if _log is None:
        with _log_lock:
            if _log is None:
                _log = RequestLog()
    return _log
