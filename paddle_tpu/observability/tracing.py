"""Host-side span tracer with Chrome-trace / Perfetto JSON export.

Dapper-style request tracing for the serving pipeline: nestable spans
opened on the host (scheduler tick, admission wave, the jitted decode
dispatch) recorded as complete events — ``{"ph": "X", "ts", "dur",
"pid", "tid", ...}`` microseconds — in the Trace Event format both
chrome://tracing and https://ui.perfetto.dev load directly.  Nesting
needs no parent pointers: Perfetto stacks events on one tid by ts/dur
containment, which the context-manager discipline guarantees.

Composition with device traces: :class:`paddle_tpu.profiler.RecordEvent`
emits BOTH a ``jax.profiler.TraceAnnotation`` (so the span shows up
inside the XLA/XPlane device dump) and a host span here — the same
labelled region appears in the device timeline and in this exporter's
host timeline, which is what lets queue-wait and dispatch gaps be read
against kernel activity.

Cost discipline: recording one span is two ``perf_counter_ns`` calls and
one deque append under a lock — O(1) host work, no device syncs.  The
buffer is a ring (``FLAGS_trace_buffer_events``): a long-running server
keeps the most recent window and counts what it dropped.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from collections import deque

__all__ = ["SpanTracer", "get_tracer", "span", "instant", "counter",
           "export_chrome_trace"]


class _OpenSpan:
    __slots__ = ("name", "cat", "args", "ts", "tid")

    def __init__(self, name: str, cat: str, args: Dict[str, Any],
                 ts: float, tid: int):
        self.name = name
        self.cat = cat
        self.args = args
        self.ts = ts
        self.tid = tid


class SpanTracer:
    """Collects host spans into a bounded ring buffer.

    ``span(name, **args)`` is the context-manager form; ``start`` /
    ``finish`` are the split form for callers with begin/end APIs
    (profiler.RecordEvent).  ``enabled=False`` turns both into no-ops.
    """

    def __init__(self, max_events: Optional[int] = None,
                 enabled: Optional[bool] = None):
        from .. import flags as _flags
        if max_events is None:
            max_events = int(_flags.flag("trace_buffer_events"))
        if enabled is None:
            enabled = bool(_flags.flag("observability_spans"))
        self.enabled = enabled
        self.max_events = max(1, int(max_events))
        self.dropped = 0
        self._events: "deque[Dict[str, Any]]" = deque()
        self._lock = threading.Lock()
        self._pid = os.getpid()
        # one wall-clock origin per tracer so every span shares a timebase
        self._t0 = time.perf_counter_ns()

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._t0) / 1e3

    # -- recording ---------------------------------------------------------

    def start(self, name: str, cat: str = "host",
              **args: Any) -> Optional[_OpenSpan]:
        if not self.enabled:
            return None
        return _OpenSpan(name, cat, args, self._now_us(),
                         threading.get_ident())

    def finish(self, span: Optional[_OpenSpan]) -> None:
        if span is None or not self.enabled:
            return
        ev = {"name": span.name, "cat": span.cat, "ph": "X",
              "ts": span.ts, "dur": self._now_us() - span.ts,
              "pid": self._pid, "tid": span.tid}
        if span.args:
            ev["args"] = span.args
        self._append(ev)

    @contextmanager
    def span(self, name: str, cat: str = "host", **args: Any):
        s = self.start(name, cat, **args)
        try:
            yield s
        finally:
            self.finish(s)

    def instant(self, name: str, cat: str = "host", **args: Any) -> None:
        """Zero-duration marker (eviction, admission rejection, ...)."""
        if not self.enabled:
            return
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": self._now_us(), "pid": self._pid,
              "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._append(ev)

    def counter(self, name: str, cat: str = "host",
                **values: Any) -> None:
        """Chrome-trace counter sample (ph "C"): one numeric series per
        kwarg, rendered as stacked counter tracks in Perfetto.  The
        cost model emits ``serving.tick_model`` predicted/measured
        samples here every tick, riding next to the ``serving.step``
        spans."""
        if not self.enabled:
            return
        ev = {"name": name, "cat": cat, "ph": "C",
              "ts": self._now_us(), "pid": self._pid,
              "tid": threading.get_ident(),
              "args": {k: float(v) for k, v in values.items()}}
        self._append(ev)

    def _append(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self._events.popleft()
                self.dropped += 1
                overflowed = True
            else:
                overflowed = False
            self._events.append(ev)
        if overflowed:
            self._mirror_dropped()

    def _mirror_dropped(self) -> None:
        """Publish ``dropped`` as the ``obs.trace_dropped_events``
        gauge so a wrapped ring can't masquerade as a complete timeline
        in ``snapshot()`` — previously it was counted in the
        ``export_chrome_trace`` metadata only.  Only the process-default
        tracer publishes: private tracers in tests must not clobber the
        fleet count.  Called outside the ring lock (the registry has its
        own)."""
        if _tracer is not self:
            return
        from .metrics import default_registry
        default_registry().gauge(
            "obs.trace_dropped_events",
            "span-tracer ring evictions since start/reset; nonzero "
            "means exported timelines are a recent-window suffix, not "
            "the whole story").set(float(self.dropped))

    # -- readout -----------------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0
        # re-register the gauge at 0 so every snapshot() taken after a
        # reset still carries the (zero) drop count
        self._mirror_dropped()

    def export_chrome_trace(self, path: Optional[str] = None
                            ) -> Dict[str, Any]:
        """Trace Event JSON (object form).  Loads in Perfetto /
        chrome://tracing as-is; ``path`` additionally writes the file."""
        events = self.events()
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "tid": 0, "args": {"name": "paddle_tpu host"}}]
        for tid in sorted({e["tid"] for e in events}):
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self._pid, "tid": tid,
                         "args": {"name": f"host-thread-{tid}"}})
        trace = {"traceEvents": meta + events,
                 "displayTimeUnit": "ms",
                 "otherData": {"producer": "paddle_tpu.observability",
                               "dropped_events": self.dropped}}
        if path is not None:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                json.dump(trace, f)
        return trace


# -- module-level default tracer --------------------------------------------

_tracer: Optional[SpanTracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> SpanTracer:
    """The process-wide tracer every subsystem records into (created
    lazily so FLAGS_* read their environment overrides first)."""
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                _tracer = SpanTracer()
                _tracer._mirror_dropped()
    return _tracer


def span(name: str, cat: str = "host", **args: Any):
    return get_tracer().span(name, cat, **args)


def instant(name: str, cat: str = "host", **args: Any) -> None:
    get_tracer().instant(name, cat, **args)


def counter(name: str, cat: str = "host", **values: Any) -> None:
    get_tracer().counter(name, cat, **values)


def export_chrome_trace(path: Optional[str] = None) -> Dict[str, Any]:
    return get_tracer().export_chrome_trace(path)
