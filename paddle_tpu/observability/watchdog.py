"""Retrace watchdog: per-call-site jit trace counting with a budget.

The serving engine's whole design rests on "the step function compiles
exactly once"; PR 1 proved it with a hand-written counter
(``ServingEngine.step_traces``) incremented by a Python side effect
inside the traced body — side effects fire at TRACE time only, so the
count is compilations, not calls.  :func:`track_retraces` generalises
that trick into a reusable guarantee: wrap any function before jitting
and every compilation increments the shared-registry counter
``jit.traces{site=<name>}``; give it a ``budget`` and blowing past it
warns or raises (``FLAGS_retrace_watchdog``) at the moment the offending
trace happens — with the argument shapes/dtypes that caused it in the
message, which is exactly the information a retrace regression needs.

The tier-1 conftest arms the watchdog (``raise``) for every test, so a
future change that makes the once-jitted serving step shape-polymorphic
fails loudly in CI instead of silently recompiling per request.
"""

from __future__ import annotations

import functools
import warnings
from typing import Any, Callable, Dict, Optional

from . import metrics as _metrics

__all__ = ["RetraceError", "RetraceWarning", "TrackedFunction",
           "track_retraces"]


class RetraceError(RuntimeError):
    """A tracked call-site compiled more often than its budget allows."""


class RetraceWarning(UserWarning):
    pass


def _describe_args(args, kwargs) -> str:
    def one(a):
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None:
            return f"{dtype}{tuple(shape)}"
        return type(a).__name__
    parts = [one(a) for a in args]
    parts += [f"{k}={one(v)}" for k, v in kwargs.items()]
    return ", ".join(parts)


class TrackedFunction:
    """Callable wrapper returned by :func:`track_retraces`.

    ``fn(...)`` dispatches to the (jitted) wrapped function; ``.traces``
    reads the registry counter — the number of times jax traced the
    wrapped body since this site's counter was created.

    ``python_fn`` is the ORIGINAL python function (before the counting
    hook and ``jax.jit``) and ``jit_kwargs`` the kwargs the jit was
    built with — the graph lint (paddle_tpu/static_analysis) reads both
    so ``analyze(tracked_fn, *args)`` traces the raw body (no watchdog
    budget spent) while still seeing what the real call site donates.
    """

    def __init__(self, fn: Callable, name: str, counter,
                 python_fn: Optional[Callable] = None,
                 jit_kwargs: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self.name = name
        self.counter = counter
        self.python_fn = python_fn
        self.jit_kwargs = dict(jit_kwargs or {})
        functools.update_wrapper(self, fn, updated=())

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)

    @property
    def traces(self) -> int:
        return int(self.counter.value())


def track_retraces(fn: Callable, name: str, budget: Optional[int] = None,
                   labels: Optional[Dict[str, Any]] = None,
                   registry: Optional[_metrics.MetricsRegistry] = None,
                   jit: bool = True, **jit_kwargs) -> TrackedFunction:
    """Wrap ``fn`` so every jit trace of it is counted (and budgeted).

    ``fn`` must be the PYTHON function — the counting hook runs as a
    trace-time side effect inside the traced body, so it must be wrapped
    *before* ``jax.jit`` (``jit=True``, the default, applies the jit
    here; pass ``jit=False`` to count traces of a function something
    else will jit, e.g. a ``shard_map`` body).

    ``budget``: max allowed compilations for this site (``1`` = "traces
    once, never retraces").  Exceeding it consults
    ``FLAGS_retrace_watchdog`` at violation time: ``raise`` →
    :class:`RetraceError` (inside the offending trace, so the bad call
    never runs), ``warn`` → :class:`RetraceWarning`, ``off`` → count
    only.  ``labels`` extend the counter's label set (the serving engine
    adds ``engine=<id>`` so parallel engines budget independently).
    """
    reg = registry if registry is not None else _metrics.default_registry()
    counter = reg.counter(
        "jit.traces",
        "jit compilations per tracked call-site (trace-time side effect; "
        "value N means N compiled programs, not N calls)",
    ).labels(site=name, **(labels or {}))

    @functools.wraps(fn)
    def counted(*args, **kwargs):
        n = counter.inc()
        if budget is not None and n > budget:
            from .. import flags as _flags
            action = str(_flags.flag("retrace_watchdog"))
            if action != "off":
                msg = (f"{name}: trace #{int(n)} exceeds the retrace "
                       f"budget of {budget} — the call signature that "
                       f"retraced: ({_describe_args(args, kwargs)}).  A "
                       f"shape/dtype/static-arg varied across calls at a "
                       f"site meant to compile {budget} time(s).")
                if action == "raise":
                    raise RetraceError(msg)
                warnings.warn(msg, RetraceWarning, stacklevel=2)
        return fn(*args, **kwargs)

    if jit:
        import jax
        wrapped: Callable = jax.jit(counted, **jit_kwargs)
    else:
        if jit_kwargs:
            raise TypeError("jit_kwargs given but jit=False")
        wrapped = counted
    return TrackedFunction(wrapped, name, counter,
                           python_fn=fn, jit_kwargs=jit_kwargs)
