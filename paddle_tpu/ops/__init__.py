"""Hot-path ops: the TPU-native equivalent of the reference's fused CUDA
kernels (upstream layout: paddle/phi/kernels/fusion/gpu/ and
paddle/phi/kernels/gpu/flash_attn_kernel.cu).

Every op has (a) a pure-XLA reference implementation — correct everywhere,
used on CPU and as the numerical oracle — and (b) where it pays, a Pallas
kernel for TPU (paddle_tpu/ops/pallas/).  Dispatch picks the Pallas path on
TPU backends (or when FLAGS_pallas_interpret forces interpreter mode for
testing).
"""

from .attention import (cached_decode_attention,
                        cached_decode_attention_reference, flash_attention,
                        flash_attention_reference)
from .norms import rms_norm, rms_norm_reference
from .rope import apply_rope, build_rope_cache, fused_rope
from .fused import (fused_attention, fused_bias_dropout_residual_layer_norm,
                    fused_dropout_add, fused_feedforward, fused_layer_norm,
                    fused_linear, fused_linear_activation,
                    fused_multi_transformer, masked_multihead_attention,
                    variable_length_memory_efficient_attention)

__all__ = [
    "flash_attention", "flash_attention_reference",
    "cached_decode_attention", "cached_decode_attention_reference",
    "rms_norm", "rms_norm_reference",
    "apply_rope", "build_rope_cache", "fused_rope",
    "fused_bias_dropout_residual_layer_norm",
    "fused_multi_transformer",
    "variable_length_memory_efficient_attention",
    "fused_attention", "fused_dropout_add", "fused_feedforward",
    "fused_layer_norm", "fused_linear", "fused_linear_activation",
    "masked_multihead_attention",
]
