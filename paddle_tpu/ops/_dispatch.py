"""Backend dispatch helpers for ops with Pallas fast paths."""

from __future__ import annotations

import contextlib
import functools
import threading

import jax

from .. import flags

# trace-time kernel-path relabel hint (see kernel_path_hint): thread-local
# so concurrent traces (pytest-xdist, background compiles) don't cross
_HINT = threading.local()


@functools.cache
def default_backend() -> str:
    return jax.default_backend()


@contextlib.contextmanager
def kernel_path_hint(op: str):
    """Relabel ``ops.kernel_path`` counts made while the context is open.

    Dispatch counting happens at TRACE time, so a caller that knows what a
    shape *means* — the serving engine tracing its speculative-decode
    verify step, where the q window is draft tokens, not a prefill chunk —
    wraps the traced call and every routing decision inside lands under
    ``op=<hint>`` (e.g. ``spec_verify``) instead of the generic op name.
    Purely an observability relabel: routing itself is unchanged.
    """
    prev = getattr(_HINT, "op", None)
    _HINT.op = op
    try:
        yield
    finally:
        _HINT.op = prev


def kernel_path_op(default: str) -> str:
    """The op label a dispatch site should count under: the innermost
    active :func:`kernel_path_hint`, or ``default``."""
    return getattr(_HINT, "op", None) or default


def use_pallas() -> bool:
    """True when the Pallas TPU path should be taken.

    On TPU: always.  Elsewhere: only when FLAGS_pallas_interpret is set
    (Pallas interpreter mode — used to test the kernels on CPU).
    """
    if flags.flag("pallas_interpret"):
        return True
    return default_backend() in ("tpu", "axon")


def pallas_interpret() -> bool:
    return bool(flags.flag("pallas_interpret")) or default_backend() not in (
        "tpu", "axon")


def count_kernel_path(op: str, path: str, **labels) -> None:
    """Count one kernel-routing decision in the shared metrics registry
    (``ops.kernel_path{op=...,path=...}``).

    Dispatch decisions run at TRACE time, so the counter reads as
    "compiled programs that chose this path", not calls — zero per-step
    cost, and a routing regression (a serving shape silently sliding off
    its Pallas kernel onto the XLA fallback) shows up as a counter
    moving in ``observability.snapshot()`` instead of only as a perf
    mystery.  Extra ``labels`` refine the series (``cache="paged"``).
    """
    from .. import observability
    observability.default_registry().counter(
        "ops.kernel_path",
        "kernel-path selections per op, counted at dispatch/trace time",
    ).labels(op=op, path=path, **labels).inc()
