"""Backend dispatch helpers for ops with Pallas fast paths."""

from __future__ import annotations

import functools

import jax

from .. import flags


@functools.cache
def default_backend() -> str:
    return jax.default_backend()


def use_pallas() -> bool:
    """True when the Pallas TPU path should be taken.

    On TPU: always.  Elsewhere: only when FLAGS_pallas_interpret is set
    (Pallas interpreter mode — used to test the kernels on CPU).
    """
    if flags.flag("pallas_interpret"):
        return True
    return default_backend() in ("tpu", "axon")


def pallas_interpret() -> bool:
    return bool(flags.flag("pallas_interpret")) or default_backend() not in (
        "tpu", "axon")
