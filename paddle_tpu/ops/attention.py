"""Flash attention: XLA reference implementation + dispatch to the Pallas
TPU kernel.

Equivalent of the reference's flash-attention integration (upstream layout:
paddle/phi/kernels/gpu/flash_attn_kernel.cu, which wraps the external
flashattn library and exposes ``softmax_lse`` — the log-sum-exp needed by
ring attention).  Layout convention matches the reference:
``(batch, seq, num_heads, head_dim)``; GQA is supported by passing fewer KV
heads than Q heads.

The reference implementation below is *mathematically* flash attention
(numerically stable softmax, fp32 accumulation, returns LSE) but leaves the
tiling to XLA; the Pallas kernel (paddle_tpu/ops/pallas/flash_attention.py)
implements the blocked online-softmax algorithm for TPU HBM-bandwidth
efficiency and is selected on TPU backends.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import flags
from ..framework import random as _random
from ..utils.logging import vlog_once
from . import _dispatch

NEG_INF = -1e30

# -- structured fallback reasons ------------------------------------------
# Every Pallas→XLA demotion carries a KIND, and the kind — not a string
# match on the message — decides whether the fallback is logged.  The
# contract (pinned by tests/test_attention.py):
#   backend  XLA is simply the right path (no Pallas backend) — silent
#   mesh     bare mesh-sharded trace the shard_map fast path can't take
#            (per-shard geometry/batch ineligible) — silent by design
#   policy   deliberate routing the bench justified (the min_len
#            threshold, decode extra_mask) — silent
#   feature  a caller-requested feature outside the kernel's contract
#            (dropout, a custom training mask) — WARN once (the caller
#            asked for the fast path's regime and silently left it)
#   shape    geometry the kernel cannot take at all — WARN (a shape
#            quietly sliding off the fast path is a perf surprise)
#   kernel   the kernel itself refused at call time — WARN (dispatch
#            and kernel disagree; the dispatch-agreement lint's regime)
KIND_BACKEND = "backend"
KIND_MESH = "mesh"
KIND_POLICY = "policy"
KIND_FEATURE = "feature"
KIND_SHAPE = "shape"
KIND_KERNEL = "kernel"
WARN_KINDS = frozenset({KIND_FEATURE, KIND_SHAPE, KIND_KERNEL})


class FallbackReason(str):
    """A fallback reason: a plain ``str`` (every existing consumer keeps
    matching on text) that also carries its ``kind`` — the structured
    half the warn gates read.  Reasons of unknown provenance (a bare
    string from an older call site) default to ``kernel``, the loud
    kind: an unclassified fallback should be seen, not buried."""

    kind = KIND_KERNEL

    def __new__(cls, text, kind: str = KIND_KERNEL):
        self = str.__new__(cls, text)
        self.kind = kind
        return self


def reason_kind(reason) -> str:
    """The kind of a fallback reason (``kernel`` for bare strings)."""
    return getattr(reason, "kind", KIND_KERNEL)


def _fallback(reason):
    """Record a Pallas→XLA fallback: error under FLAGS_flash_attention_force,
    else a one-shot VLOG(1) per distinct reason (round-2 verdict weak #3 —
    a silent fallback is a large unexplained perf regression on TPU).
    Whether the log fires is the reason KIND's call (``WARN_KINDS``):
    backend/mesh/policy demotions are the design, shape/kernel demotions
    are surprises."""
    if flags.flag("flash_attention_force"):
        raise RuntimeError(
            f"flash_attention: Pallas kernel ineligible ({reason}) and "
            f"FLAGS_flash_attention_force is set")
    if reason_kind(reason) in WARN_KINDS:
        vlog_once(1, f"flash_attention:{reason}",
                  f"flash_attention: falling back to the XLA reference "
                  f"path ({reason})")


def _repeat_kv(k, n_rep: int):
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :],
                            (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def flash_attention_reference(q, k, v, attn_mask=None, dropout_p: float = 0.0,
                              causal: bool = False, scale: Optional[float] = None,
                              return_lse: bool = True):
    """Stable attention with fp32 accumulation.

    q: (B, Sq, Hq, D); k, v: (B, Skv, Hkv, D) with Hq % Hkv == 0.
    attn_mask: bool (True = keep) or additive float mask, broadcastable to
    (B, Hq, Sq, Skv).
    Returns (out, lse) — lse: (B, Hq, Sq) fp32, log-sum-exp of scaled scores.
    """
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    if scale is None:
        scale = d ** -0.5
    k = _repeat_kv(k, hq // hkv)
    v = _repeat_kv(v, hq // hkv)

    # (B, H, Sq, Skv) scores in fp32
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt,
                        preferred_element_type=jnp.float32)
    if causal:
        # bottom-right aligned causal mask (flash-attn convention for Sq<Skv)
        qi = jnp.arange(sq)[:, None] + (skv - sq)
        ki = jnp.arange(skv)[None, :]
        scores = jnp.where(ki <= qi, scores, NEG_INF)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, NEG_INF)
        else:
            scores = scores + attn_mask.astype(jnp.float32)

    m = jnp.max(scores, axis=-1, keepdims=True)
    m = jnp.maximum(m, NEG_INF)  # guard fully-masked rows
    p = jnp.exp(scores - m)
    # fully-masked rows (m == NEG_INF): exp(NEG_INF - NEG_INF) = 1 would make
    # them mean-of-v; define out = 0, lse = NEG_INF instead (the flash-attn
    # convention, matched by the Pallas kernel)
    dead = m <= NEG_INF / 2
    p = jnp.where(dead, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    lse = jnp.where(dead, NEG_INF,
                    m + jnp.log(jnp.maximum(l, 1e-37))).squeeze(-1)  # (B,H,Sq)

    p = p / jnp.maximum(l, 1e-37)
    if dropout_p > 0.0:
        keep = jax.random.bernoulli(_random.site_key(), 1.0 - dropout_p,
                                    p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vt,
                     preferred_element_type=jnp.float32)
    out = jnp.swapaxes(out, 1, 2).astype(q.dtype)  # (B, Sq, H, D)
    if return_lse:
        return out, lse
    return out


def decode_attention_path(b: int, s: int, hq: int, hkv: int, d: int,
                          kv_len: int, has_extra_mask: bool = False,
                          paged_block_len: Optional[int] = None,
                          quantized: bool = False):
    """The flash-decode dispatch decision for one shape, exposed so
    bench.py can record the chosen path per row: returns
    ``("pallas_decode", None)`` or ``("xla_math", reason)``.

    Every decision is also counted into the shared metrics registry
    (``ops.kernel_path{op="decode_attention", path=..., cache=...}``) —
    dispatch runs at trace time, so the counters say which paths the
    compiled programs actually took and a routing regression is visible
    in ``observability.snapshot()``.

    ``paged_block_len``: set when the cache is the paged block pool
    (serving/kv_cache.py) — the kernel then pins its KV chunk to one
    block, so the block length must be 128-aligned; ``kv_len`` is the
    LOGICAL length ``max_blocks · block_len``.

    Threshold provenance (BENCH_DECODE.json, 940M llama3-arch, v5e): the
    XLA math path sits AT the bf16 weight-stream bound through
    max_length 2048 (0.97–1.07x) — routing those shapes to a kernel buys
    nothing — but falls to 0.652x at b=8, max_length 8192 because it
    streams the dead cache tail; that regime goes to the Pallas
    flash-decode kernel (FLAGS_decode_attention_min_len, default 4096).
    """
    path, reason = _decode_attention_decision(b, s, hq, hkv, d, kv_len,
                                              has_extra_mask,
                                              paged_block_len)
    # a kernel_path_hint (ops/_dispatch.py) relabels the decision — the
    # serving engine's speculative verify step counts as op="spec_verify"
    # so a draft window silently sliding off its path is its own series
    # a quantized pool relabels the cache axis: ops.kernel_path
    # {op="decode_attention", cache="int8"} is the int8 serving path's
    # own routing series (the satellite observability contract)
    _dispatch.count_kernel_path(
        _dispatch.kernel_path_op("decode_attention"), path,
        cache="int8" if quantized else
        ("paged" if paged_block_len is not None else "contiguous"))
    return path, reason


def _mesh_sharded_trace() -> bool:
    """True when the current trace runs BARE under a multi-device mesh
    (the serving engine's mesh step, or a globally installed hybrid
    group with any axis > 1).  A bare ``pallas_call`` is opaque to
    GSPMD — the partitioner would replicate its operands onto every
    device, undoing the sharding — so mesh-partitioned programs take
    the XLA math/gather path, which GSPMD partitions natively
    (vocab-parallel logits, mp-sharded cache contractions).  Inside a
    ``shard_map``/pmap body the trace is PER-SHARD (a named axis env is
    bound) and the kernel is exactly right — ring/context-parallel
    attention already runs Pallas that way — so those traces are
    exempt.  The decode dispatch wires exactly that: an eligible
    mesh-sharded decode shape re-enters through
    :func:`_shard_map_decode_attention` (kv-heads split over mp, rows
    over dp/sharding) and only the ineligible remainder demotes to the
    XLA gather path."""
    from ..distributed import env as _denv
    mesh = _denv.active_mesh()
    if mesh is None:
        return False
    if not any(mesh.shape[a] > 1 for a in mesh.axis_names):
        return False
    try:                       # per-shard (shard_map/pmap) trace: exempt
        from jax._src.core import nonempty_axis_env
        if nonempty_axis_env():
            return False
    except ImportError:        # future jax: fail toward the safe gate
        pass
    return True


def decode_shape_gate(s, hq, hkv, d, kv_len, paged_block_len=None):
    """The SHAPE-only half of the flash-decode dispatch decision: would
    this geometry fit the Pallas kernel, ignoring environment (backend,
    mesh trace, extra masks, the min_len perf threshold)?  Every bound
    derives from ``ops.pallas.limits`` — the same module the kernel's
    own gates read — and the kernel-registry's dispatch-agreement lint
    (``static_analysis.kernel_rules.dispatch_agreement_findings``)
    sweeps a shape lattice to prove the two stay in step.  Returns
    ``("pallas_decode", None)`` or ``("xla_math", reason)``."""
    from .pallas import limits as _limits
    if hkv == 0 or hq % hkv:
        return "xla_math", f"q heads {hq} not a multiple of kv heads {hkv}"
    if hq // hkv > _limits.MAX_Q_ROWS:
        return "xla_math", (f"GQA group size {hq // hkv} > "
                            f"{_limits.MAX_Q_ROWS}")
    if s > _limits.MAX_Q_LEN:
        # a q longer than any serving prefill chunk is whole-prompt
        # prefill — the flash kernel's regime, not the cached path's
        return "xla_math", (f"q_len {s} > {_limits.MAX_Q_LEN} "
                            f"(whole-prefill-shaped)")
    if d > _limits.MAX_HEAD_DIM:
        return "xla_math", f"head_dim {d} > {_limits.MAX_HEAD_DIM}"
    if paged_block_len is not None:
        if paged_block_len % _limits.LANES:
            return "xla_math", (f"paged block_len {paged_block_len} not "
                                f"128-aligned")
        return "pallas_decode", None
    if kv_len % _limits.LANES:
        return "xla_math", f"max_length {kv_len} not 128-aligned"
    return "pallas_decode", None


def _shard_map_eligible(b, s, hq, hkv, d, kv_len, has_extra_mask,
                        paged_block_len) -> Optional[str]:
    """Can this bare mesh-sharded decode shape take the Pallas kernel
    PER SHARD under :func:`_shard_map_decode_attention`?  ``None`` when
    eligible, else the blocking condition.  Eligibility = the mesh only
    spans the decode axes (mp over kv-heads, dp/sharding over rows),
    both head counts and the batch divide evenly, and the PER-SHARD
    geometry (Hq/mp, Hkv/mp heads) passes the same policy + shape gates
    a single-chip shape does — so the per-shard trace inside the
    shard_map body re-dispatches straight onto the kernel."""
    from .. import flags as _flags
    from ..distributed import env as _denv
    mesh = _denv.active_mesh()
    axes = {a: mesh.shape[a] for a in mesh.axis_names if mesh.shape[a] > 1}
    extra = sorted(a for a in axes if a not in ("mp", "dp", "sharding"))
    if extra:
        return f"mesh axes {extra} beyond mp/dp/sharding"
    mp = axes.get("mp", 1)
    rows = axes.get("dp", 1) * axes.get("sharding", 1)
    if hkv == 0 or hq % mp or hkv % mp:
        return f"heads (hq={hq}, hkv={hkv}) not divisible by mp={mp}"
    if b % rows:
        return f"batch {b} not divisible by dp*sharding={rows}"
    if has_extra_mask:
        return "extra_mask"
    if kv_len < int(_flags.flag("decode_attention_min_len")):
        return f"kv_len {kv_len} < FLAGS_decode_attention_min_len"
    path, why = decode_shape_gate(s, hq // mp, hkv // mp, d, kv_len,
                                  paged_block_len)
    if path != "pallas_decode":
        return f"per-shard shape: {why}"
    return None


def _decode_attention_decision(b, s, hq, hkv, d, kv_len, has_extra_mask,
                               paged_block_len):
    from .. import flags as _flags
    if not _dispatch.use_pallas():
        return "xla_math", FallbackReason(
            f"no Pallas-capable backend ({_dispatch.default_backend()})",
            KIND_BACKEND)
    if _mesh_sharded_trace():
        blocked = _shard_map_eligible(b, s, hq, hkv, d, kv_len,
                                      has_extra_mask, paged_block_len)
        if blocked is None:
            # the mesh fast path: wrap the per-shard kernel in shard_map
            # (kv-heads over mp, rows over dp/sharding — the output
            # stays row-parallel, no new collectives)
            return "pallas_decode_shard_map", None
        return "xla_math", FallbackReason(
            f"mesh-sharded trace: {blocked}; the XLA gather path "
            f"partitions under GSPMD", KIND_MESH)
    if has_extra_mask:
        return "xla_math", FallbackReason("extra_mask", KIND_POLICY)
    if kv_len < int(_flags.flag("decode_attention_min_len")):
        return "xla_math", FallbackReason(
            f"kv_len {kv_len} < FLAGS_decode_attention_min_len (XLA at "
            f"the weight-stream bound there)", KIND_POLICY)
    path, why = decode_shape_gate(s, hq, hkv, d, kv_len, paged_block_len)
    if why is not None:
        why = FallbackReason(why, KIND_SHAPE)
    return path, why


def _shard_map_decode_attention(q, k_cache, v_cache, pos, scale=None,
                                live_len=None, block_tables=None,
                                k_scale=None, v_scale=None):
    """The mesh fast path: re-enter :func:`cached_decode_attention`
    PER SHARD under ``shard_map`` — kv-heads split over ``mp`` (exactly
    how mp attention layers place them: contiguous head blocks, so the
    GQA group structure survives the split), rows over ``dp``/
    ``sharding``.  Inside the body a named axis env is bound, so
    ``_mesh_sharded_trace()`` is False and the per-shard dispatch
    re-runs at Hq/mp × Hkv/mp geometry — counting its own
    ``pallas_decode`` row and degrading per shard to the XLA math path
    if the kernel refuses at call time.  Attention is embarrassingly
    parallel over rows and kv-head groups, so the body needs NO
    collectives and the output stays row-parallel (the PR-8 comm model
    is unchanged).

    Paged layout: the pool is head-sharded only (every shard holds all
    blocks at its head slice) and the block tables are per-row logical
    — they ride the row axes with their rows, whole per shard."""
    from jax.sharding import PartitionSpec as P

    from ..distributed import env as _denv
    mesh = _denv.active_mesh()
    names = set(mesh.axis_names)
    batch = tuple(a for a in ("dp", "sharding") if a in names) or None
    mp = "mp" if "mp" in names else None
    paged = block_tables is not None
    quantized = k_scale is not None
    q_spec = P(batch, None, mp, None)
    kv_spec = P(None, None, mp, None) if paged else P(batch, None, mp,
                                                      None)
    args = [q, k_cache, v_cache, pos]
    in_specs = [q_spec, kv_spec, kv_spec,
                P(batch) if getattr(pos, "ndim", 0) == 1 else P()]
    if paged:
        args.append(block_tables)
        in_specs.append(P(batch, None))
    if quantized:
        s_spec = P(None, mp) if paged else P(batch, None, mp)
        args += [jnp.asarray(k_scale, jnp.float32),
                 jnp.asarray(v_scale, jnp.float32)]
        in_specs += [s_spec, s_spec]

    def body(*ops):
        q_, k_, v_, pos_ = ops[:4]
        i = 4
        bt_ = ks_ = vs_ = None
        if paged:
            bt_ = ops[i]
            i += 1
        if quantized:
            ks_, vs_ = ops[i], ops[i + 1]
        return cached_decode_attention(q_, k_, v_, pos_, scale=scale,
                                       live_len=live_len,
                                       block_tables=bt_,
                                       k_scale=ks_, v_scale=vs_)

    fn = jax.shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                       out_specs=q_spec, check_vma=False)
    return fn(*args)


def cached_decode_attention(q, k_cache, v_cache, pos,
                            scale: Optional[float] = None,
                            extra_mask=None, live_len: Optional[int] = None,
                            block_tables=None,
                            k_scale=None, v_scale=None):
    """Incremental decode attention over a pre-allocated cache — the
    serving hot path (parity: the reference's masked_multihead_attention /
    fused decode-attention core, upstream
    paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu).

    q: (B, s, Hq, D) — the new tokens (s is 1 in steady-state decode);
    k_cache/v_cache: (B, L, Hkv, D) with the new K/V already written at
    ``pos..pos+s``; slots ``> pos+i`` are masked.  ``pos`` is a scalar
    (whole-batch decode, the ``generate()`` path) or an int (B,) vector of
    per-row positions (the serving engine's slot batch, where every row
    is a different request at a different depth).  ``live_len``: optional
    STATIC upper bound on max(pos)+s — both paths then read only the
    first ``live_len`` cache slots.

    Dispatch: long-cache shapes (kv_len >= FLAGS_decode_attention_min_len)
    on Pallas backends route to the split-KV flash-decode kernel
    (ops/pallas/decode_attention.py), whose scalar-prefetch-clamped index
    maps stream only each row's LIVE cache prefix — per-step cost scales
    with actual context depth, not max_length.  Everything else (and any
    ``extra_mask``) runs :func:`cached_decode_attention_reference`, the
    XLA math path, which the decode bench measured at the weight-stream
    bound for short caches.  Returns (B, s, Hq, D) in q.dtype.

    ``block_tables``: int (B, max_blocks) — switches to the PAGED cache
    layout (serving/kv_cache.py): k_cache/v_cache are the pooled
    (num_blocks, block_len, Hkv, D) arrays and row i's logical block j
    lives in physical block ``block_tables[i, j]``.  The Pallas kernel
    dereferences the table in its scalar-prefetch index maps; the XLA
    fallback gathers the table into the contiguous layout first.

    ``k_scale``/``v_scale``: f32 per-block-per-kv-head dequant scales for
    an int8 cache (paged: ``(num_blocks, Hkv)``; contiguous:
    ``(B, n_granules, Hkv)``) — the Pallas kernel dequantizes inside its
    KV-chunk loop; the XLA fallback dequantizes after its gather.
    """
    b, s, hq, d = q.shape
    quantized = k_scale is not None
    if block_tables is not None:
        _, block_len, hkv, _ = k_cache.shape
        kv_len = block_tables.shape[1] * block_len
        path, reason = decode_attention_path(b, s, hq, hkv, d, kv_len,
                                             extra_mask is not None,
                                             paged_block_len=block_len,
                                             quantized=quantized)
    else:
        _, kv_len, hkv, _ = k_cache.shape
        path, reason = decode_attention_path(b, s, hq, hkv, d, kv_len,
                                             extra_mask is not None,
                                             quantized=quantized)
    if path == "pallas_decode_shard_map":
        try:
            return _shard_map_decode_attention(
                q, k_cache, v_cache, pos, scale=scale, live_len=live_len,
                block_tables=block_tables,
                k_scale=k_scale, v_scale=v_scale)
        except NotImplementedError as e:
            reason = FallbackReason(str(e), KIND_KERNEL)
    elif path == "pallas_decode":
        try:
            from .pallas.decode_attention import decode_attention_pallas
            return decode_attention_pallas(
                q, k_cache, v_cache, pos, scale=scale, live_len=live_len,
                block_tables=block_tables,
                k_scale=k_scale, v_scale=v_scale,
                interpret=_dispatch.pallas_interpret())
        except NotImplementedError as e:
            reason = FallbackReason(str(e), KIND_KERNEL)
    if _dispatch.use_pallas() and reason_kind(reason) in WARN_KINDS:
        # shape/kernel demotions ARE perf surprises worth one log line;
        # backend/mesh/policy demotions are the design (see the kind
        # contract at the top of this module)
        vlog_once(1, f"decode_attention:{reason}",
                  f"cached_decode_attention: falling back to the XLA math "
                  f"path ({reason})")
    return cached_decode_attention_reference(q, k_cache, v_cache, pos,
                                             scale=scale,
                                             extra_mask=extra_mask,
                                             live_len=live_len,
                                             block_tables=block_tables,
                                             k_scale=k_scale,
                                             v_scale=v_scale)


@jax.jit
def _dequant_decode_attention(k_cache, v_cache, k_scale, v_scale):
    """Widen an int8 K/V view back to f32 under its per-block-per-kv-head
    scales — the XLA fallback's dequant, numerically the oracle for the
    kernel's in-chunk dequant.

    A NAMED jitted helper on purpose: the int8→f32 convert of a
    cache-sized tensor is exactly the widening the ``dtype-promotion``
    graph-lint rule exists to flag, so it must happen under a path
    component (``pjit[_dequant_decode_attention]``) the rule's
    decode-attention-scoped int8 allowlist can recognise; an unintended
    widening elsewhere in a quantized layout still fails the lint.
    """
    # scales are per (block, kv_head): k_cache here is the per-row
    # (B, n_blocks, bl, Hkv, D) gathered view and the scale row
    # broadcasts over the block's token axis
    k = k_cache.astype(jnp.float32) * k_scale[..., None, :, None]
    v = v_cache.astype(jnp.float32) * v_scale[..., None, :, None]
    return k, v


def cached_decode_attention_reference(q, k_cache, v_cache, pos,
                                      scale: Optional[float] = None,
                                      extra_mask=None,
                                      live_len: Optional[int] = None,
                                      block_tables=None,
                                      k_scale=None, v_scale=None):
    """The XLA math path of :func:`cached_decode_attention` (and its
    numerical oracle): masked softmax over the whole cache read.

    Decode is HBM-bound, so this path is shaped around traffic, where the
    generic ``flash_attention_reference`` (a training oracle) is not:

      * GQA stays *grouped* — Q reshapes to (B, s, Hkv, G, D) and the
        einsums contract against the (B, L, Hkv, D) cache directly; the
        oracle's ``_repeat_kv`` materialises Hq/Hkv copies;
      * K/V enter the MXU as bf16 with fp32 *accumulation*
        (preferred_element_type) — the oracle upcasts whole tensors to
        fp32 first, 2x the bytes.  Only the (B, Hq, s, L) score tile is
        fp32, and at s=1 it is KB-scale.

    Measured (BENCH_DECODE.json, 940M llama, b=8, L=8192): this path +
    in-place cache writes took the step from 42.7 ms to the weight-stream
    regime at short max_length; its per-step cost is O(S·max_len) —
    streaming the dead cache tail — which is what the flash-decode
    kernel's live-prefix reads fix at long max_length.

    ``block_tables`` (int (B, max_blocks)): PAGED layout — k_cache/
    v_cache are the pooled (num_blocks, block_len, Hkv, D) arrays; the
    per-row physical blocks are gathered into the contiguous
    (B, max_blocks·block_len, Hkv, D) view first (an HBM copy — this is
    the parity oracle and the small-shape fallback, not the long-cache
    hot path), after which the math is identical.  A ``live_len`` bound
    trims whole table columns before the gather.
    """
    b, s, hq, d = q.shape
    if block_tables is not None:
        _, bl, hkv_p, _ = k_cache.shape
        mb = block_tables.shape[1]
        if live_len is not None and live_len < mb * bl:
            mb = -(-int(live_len) // bl)
            block_tables = block_tables[:, :mb]
        # (B, mb) pool gather -> (B, mb, bl, Hkv, D) -> contiguous view
        k_cache = jnp.take(k_cache, block_tables, axis=0, mode="clip")
        v_cache = jnp.take(v_cache, block_tables, axis=0, mode="clip")
        if k_scale is not None:
            # int8 pool: gather the same blocks' scale rows and widen
            # (the named helper keeps the widening lint-allowlistable)
            k_cache, v_cache = _dequant_decode_attention(
                k_cache, v_cache,
                jnp.take(jnp.asarray(k_scale, jnp.float32), block_tables,
                         axis=0, mode="clip"),
                jnp.take(jnp.asarray(v_scale, jnp.float32), block_tables,
                         axis=0, mode="clip"))
        k_cache = k_cache.reshape(b, mb * bl, hkv_p, d)
        v_cache = v_cache.reshape(b, mb * bl, hkv_p, d)
    elif k_scale is not None:
        # contiguous int8 rows: view each row as its scale granules,
        # widen under the per-granule-per-head scales, view back
        _, L0, hkv_c, _ = k_cache.shape
        n_gran = k_scale.shape[1]
        gr = L0 // n_gran
        k_cache, v_cache = _dequant_decode_attention(
            k_cache.reshape(b, n_gran, gr, hkv_c, d),
            v_cache.reshape(b, n_gran, gr, hkv_c, d),
            jnp.asarray(k_scale, jnp.float32),
            jnp.asarray(v_scale, jnp.float32))
        k_cache = k_cache.reshape(b, L0, hkv_c, d)
        v_cache = v_cache.reshape(b, L0, hkv_c, d)
    if live_len is not None and live_len < k_cache.shape[1]:
        k_cache = k_cache[:, :live_len]
        v_cache = v_cache[:, :live_len]
        if extra_mask is not None and extra_mask.shape[-1] != live_len:
            extra_mask = extra_mask[..., :live_len]
    _, L, hkv, _ = k_cache.shape
    g = hq // hkv
    if scale is None:
        scale = d ** -0.5
    qg = q.reshape(b, s, hkv, g, d)
    scores = jnp.einsum("bskgd,blkd->bkgsl", qg, k_cache,
                        preferred_element_type=jnp.float32)
    scores = scores * jnp.float32(scale)
    kj = jnp.arange(L)
    if getattr(pos, "ndim", 0) == 1:                  # per-row positions
        qi = pos[:, None] + jnp.arange(s)[None, :]    # (B, s)
        keep = (kj[None, None] <= qi[:, :, None])     # (B, s, L)
        keep = keep[:, None, None]                    # (B,1,1,s,L)
    else:
        qi = pos + jnp.arange(s)[:, None]             # (s, 1)
        keep = (kj[None] <= qi)[None, None, None]     # (1,1,1,s,L)
    if extra_mask is not None:
        # bool; (B, L) key-padding form, or rank-3 broadcastable to
        # (B, s, L) — lifted into the (B, Hkv, G, s, L) layout
        em = extra_mask[:, None, :] if extra_mask.ndim == 2 else extra_mask
        em = jnp.broadcast_to(em, (b, s, L))
        keep = keep & em[:, None, None]
    scores = jnp.where(keep, scores, NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgsl,blkd->bskgd", w.astype(v_cache.dtype), v_cache,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, s, hq, d).astype(q.dtype)


def cache_mask(pos, q_len: int, kv_len: int):
    """Bool (1, 1, q_len, kv_len) mask for attention over a pre-allocated
    KV cache: query i (global position pos+i) may attend cache slot j iff
    j <= pos+i (causal + don't read the uninitialised tail).  A (B,)
    ``pos`` vector (per-row slot positions) yields (B, 1, q_len, kv_len)."""
    kj = jnp.arange(kv_len)
    if getattr(pos, "ndim", 0) == 1:
        qi = pos[:, None] + jnp.arange(q_len)[None, :]      # (B, q)
        return (kj[None, None] <= qi[:, :, None])[:, None]  # (B,1,q,kv)
    qi = pos + jnp.arange(q_len)[:, None]
    return (kj[None] <= qi)[None, None]


def segment_mask(q_segment_ids, kv_segment_ids):
    """Packed-sequence (varlen) mask: query i may attend key j iff they
    belong to the same packed document (parity: the reference's
    flash_attn_varlen / cu_seqlens path, expressed TPU-style as segment
    ids over a FIXED-shape packed batch instead of ragged offsets —
    ragged shapes defeat XLA; equal-shape packing is the TPU idiom).

    q_segment_ids: (B, Sq) int; kv_segment_ids: (B, Skv) int.  Returns a
    bool mask (B, 1, Sq, Skv) combinable with ``causal=True``.
    """
    return (q_segment_ids[:, None, :, None]
            == kv_segment_ids[:, None, None, :])


def flash_attention(q, k, v, attn_mask=None, dropout_p: float = 0.0,
                    causal: bool = False, scale: Optional[float] = None,
                    return_lse: bool = False, segment_ids=None,
                    kv_segment_ids=None):
    """Public entry (parity: ``paddle.nn.functional.flash_attention``).

    Dispatches to the Pallas blocked kernel on TPU when the shape/feature set
    is eligible (no dropout, no custom mask — same restrictions as the
    reference's flash path, which falls back to the math path otherwise).

    ``segment_ids``: (B, Sq) ints marking packed-document membership (the
    varlen form); cross-document attention is masked out.  On the Pallas
    path the mask lives INSIDE the kernel (segment blocks ride the grid),
    keeping the flash memory profile for packed pretraining batches; the
    XLA fallback materialises the (B, 1, S, S) mask — measured on v5e at
    B=4, S=4096, H=8: 67 MB of temp HBM for the kernel vs 2.15 GB for the
    masked path (XLA memory_analysis).

    ``kv_segment_ids``: (B, Skv) ids for keys that are not the queries' own
    positions — ring attention's visiting KV blocks (SURVEY §5 long-context
    row: varlen × context parallelism).  Defaults to ``segment_ids``.
    """
    if (segment_ids is not None and kv_segment_ids is None
            and q.shape[1] != k.shape[1]):
        raise ValueError(
            "segment_ids without kv_segment_ids assume self-attention "
            f"(q and kv share positions); got sq={q.shape[1]}, "
            f"skv={k.shape[1]} — pass kv_segment_ids for cross-slice "
            "attention")
    if kv_segment_ids is not None and segment_ids is None:
        raise ValueError("kv_segment_ids requires segment_ids")
    if not _dispatch.use_pallas():
        _fallback(FallbackReason(
            "no Pallas-capable backend "
            f"({_dispatch.default_backend()})", KIND_BACKEND))
    else:
        reason = None
        if _mesh_sharded_trace():
            # same gate as the decode dispatch: a bare pallas_call would
            # force GSPMD to replicate its operands; the XLA reference
            # partitions cleanly, so the fallback IS the design here
            # (the mesh kind keeps it out of the one-shot log)
            reason = FallbackReason(
                "mesh-sharded trace (GSPMD partitions the XLA path)",
                KIND_MESH)
        elif dropout_p != 0.0:
            reason = FallbackReason("dropout_p != 0", KIND_FEATURE)
        elif attn_mask is not None:
            reason = FallbackReason("custom attn_mask", KIND_FEATURE)
        elif q.shape[-1] > 256:
            reason = FallbackReason(f"head_dim {q.shape[-1]} > 256",
                                    KIND_SHAPE)
        if reason is None:
            try:
                from .pallas.flash_attention import flash_attention_pallas
                out, lse = flash_attention_pallas(
                    q, k, v, causal=causal, scale=scale,
                    interpret=_dispatch.pallas_interpret(),
                    segment_ids=segment_ids,
                    kv_segment_ids=kv_segment_ids)
                _dispatch.count_kernel_path("flash_attention", "pallas")
                return (out, lse) if return_lse else out
            except NotImplementedError as e:
                reason = FallbackReason(str(e), KIND_KERNEL)
        _fallback(reason)
    _dispatch.count_kernel_path("flash_attention", "xla_reference")
    if segment_ids is not None:
        seg = segment_mask(segment_ids,
                           segment_ids if kv_segment_ids is None
                           else kv_segment_ids)
        if attn_mask is None:
            attn_mask = seg
        elif attn_mask.dtype == jnp.bool_:
            attn_mask = attn_mask & seg
        else:  # additive float mask: fold the segment mask into the bias
            attn_mask = attn_mask + jnp.where(seg, 0.0, NEG_INF).astype(
                attn_mask.dtype)
    res = flash_attention_reference(q, k, v, attn_mask=attn_mask,
                                    dropout_p=dropout_p, causal=causal,
                                    scale=scale, return_lse=True)
    return res if return_lse else res[0]
