"""Composite "fused" ops (parity: paddle.incubate.nn.functional fusions).

The reference hand-writes these as single CUDA kernels
(paddle/phi/kernels/fusion/gpu/, upstream layout).  Here they are
*compositions*: under jit XLA fuses the elementwise chain into its
neighbours, which is exactly the design stance SURVEY §7 prescribes — and
the measured lesson of BENCH_OPS.json (the hand-written Pallas rms_norm
lost to XLA at every shape once dispatch latency was excluded).  The
names exist for API parity and as the contract a future Pallas kernel
would have to beat, not because a kernel hides behind them.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["fused_bias_dropout_residual_layer_norm",
           "variable_length_memory_efficient_attention"]


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate: float = 0.5, ln_epsilon: float = 1e-5,
        training: bool = True):
    """layer_norm(residual + dropout(x + bias)) — the transformer block's
    post-attention epilogue as one jit-fusable expression."""
    from ..nn import functional as F

    y = x if bias is None else x + bias
    y = F.dropout(y, p=dropout_rate, training=training)
    y = residual + y
    return F.layer_norm(y, [y.shape[-1]], ln_scale, ln_bias,
                        epsilon=ln_epsilon)


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None,
        scale: Optional[float] = None, causal: bool = False):
    """Variable-length attention (parity: paddle.incubate.nn.functional.
    variable_length_memory_efficient_attention, the cutlass fMHA wrapper).

    Per-row valid lengths become position-range masks routed into the
    flash kernel via segment ids where eligible (padding positions get a
    sentinel segment so they attend nowhere) — the same in-kernel masking
    machinery the varlen training path uses; the XLA fallback materialises
    the mask.  query/key/value: (B, H, S, D) (the reference's layout);
    seq_lens/kv_seq_lens: (B,) valid lengths.  Returns (B, H, S, D).
    """
    from .attention import flash_attention

    b, h, s, d = query.shape
    skv = key.shape[2]
    # (B, S, H, D) is our kernel layout
    q = jnp.swapaxes(query, 1, 2)
    k = jnp.swapaxes(key, 1, 2)
    v = jnp.swapaxes(value, 1, 2)
    pos_q = jnp.arange(s)[None, :]
    pos_k = jnp.arange(skv)[None, :]
    # valid rows: segment 1; padding: distinct sentinels (2 for q, 3 for
    # kv) so cross-attention between padding rows is masked too
    seg_q = jnp.where(pos_q < jnp.asarray(seq_lens)[:, None], 1, 2)
    seg_k = jnp.where(pos_k < jnp.asarray(kv_seq_lens)[:, None], 1, 3)
    # segment ids ALWAYS apply (padding keys must never enter the
    # softmax); an additive mask composes with them — the dispatcher
    # folds both into the reference path when a custom mask forces it off
    # the kernel
    out = flash_attention(q, k, v, attn_mask=mask, causal=causal,
                          scale=scale, segment_ids=seg_q,
                          kv_segment_ids=seg_k)
    # zero the padding query rows (their softmax saw only masked keys)
    out = jnp.where((pos_q < jnp.asarray(seq_lens)[:, None])[..., None,
                                                             None],
                    out, 0.0)
    return jnp.swapaxes(out, 1, 2)
