"""Composite "fused" ops (parity: paddle.incubate.nn.functional fusions).

The reference hand-writes these as single CUDA kernels
(paddle/phi/kernels/fusion/gpu/, upstream layout).  Here they are
*compositions*: under jit XLA fuses the elementwise chain into its
neighbours, which is exactly the design stance SURVEY §7 prescribes — and
the measured lesson of BENCH_OPS.json (the hand-written Pallas rms_norm
lost to XLA at every shape once dispatch latency was excluded).  The
names exist for API parity and as the contract a future Pallas kernel
would have to beat, not because a kernel hides behind them.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["fused_bias_dropout_residual_layer_norm",
           "variable_length_memory_efficient_attention",
           "fused_multi_transformer"]


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate: float = 0.5, ln_epsilon: float = 1e-5,
        training: bool = True):
    """layer_norm(residual + dropout(x + bias)) — the transformer block's
    post-attention epilogue as one jit-fusable expression."""
    from ..nn import functional as F

    y = x if bias is None else x + bias
    y = F.dropout(y, p=dropout_rate, training=training)
    y = residual + y
    return F.layer_norm(y, [y.shape[-1]], ln_scale, ln_bias,
                        epsilon=ln_epsilon)


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None,
        scale: Optional[float] = None, causal: bool = False):
    """Variable-length attention (parity: paddle.incubate.nn.functional.
    variable_length_memory_efficient_attention, the cutlass fMHA wrapper).

    Per-row valid lengths become position-range masks routed into the
    flash kernel via segment ids where eligible (padding positions get a
    sentinel segment so they attend nowhere) — the same in-kernel masking
    machinery the varlen training path uses; the XLA fallback materialises
    the mask.  query/key/value: (B, H, S, D) (the reference's layout);
    seq_lens/kv_seq_lens: (B,) valid lengths.  Returns (B, H, S, D).
    """
    from .attention import flash_attention

    b, h, s, d = query.shape
    skv = key.shape[2]
    # (B, S, H, D) is our kernel layout
    q = jnp.swapaxes(query, 1, 2)
    k = jnp.swapaxes(key, 1, 2)
    v = jnp.swapaxes(value, 1, 2)
    pos_q = jnp.arange(s)[None, :]
    pos_k = jnp.arange(skv)[None, :]
    # valid rows: segment 1; padding: distinct sentinels (2 for q, 3 for
    # kv) so cross-attention between padding rows is masked too
    seg_q = jnp.where(pos_q < jnp.asarray(seq_lens)[:, None], 1, 2)
    seg_k = jnp.where(pos_k < jnp.asarray(kv_seq_lens)[:, None], 1, 3)
    # segment ids ALWAYS apply (padding keys must never enter the
    # softmax); an additive mask composes with them — the dispatcher
    # folds both into the reference path when a custom mask forces it off
    # the kernel
    out = flash_attention(q, k, v, attn_mask=mask, causal=causal,
                          scale=scale, segment_ids=seg_q,
                          kv_segment_ids=seg_k)
    # zero the padding query rows (their softmax saw only masked keys)
    out = jnp.where((pos_q < jnp.asarray(seq_lens)[:, None])[..., None,
                                                             None],
                    out, 0.0)
    return jnp.swapaxes(out, 1, 2)


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases,
        linear_weights, linear_biases, ffn_ln_scales, ffn_ln_biases,
        ffn1_weights, ffn1_biases, ffn2_weights, ffn2_biases,
        pre_layer_norm: bool = True, epsilon: float = 1e-5,
        cache_kvs=None, time_step=None, attn_mask=None,
        activation: str = "gelu", dropout_rate: float = 0.0,
        training: bool = False):
    """Whole decoder stack in one call (parity: paddle.incubate.nn.
    functional.fused_multi_transformer — the reference's single fused
    inference kernel for serving stacks).

    Composition by design: each layer is pre-LN → QKV → causal attention
    (flash kernel when eligible; cached math path at decode) → out proj →
    residual → FFN, and XLA fuses the chain — the measured stance of
    BENCH_OPS.json.  Per-layer params arrive as lists, paddle's layout:
    ``qkv_weights[i]``: (3, num_head, head_dim, embed_dim);
    ``linear_weights[i]``: (num_head·head_dim, embed_dim);
    ``ffn1_weights[i]``: (embed_dim, ffn_dim); ``ffn2_weights[i]``:
    (ffn_dim, embed_dim).

    ``cache_kvs``: optional list of (2, B, num_head, max_len, head_dim)
    arrays; with ``time_step`` (an int: tokens already cached) the call is
    one decode step over the cache.  Returns ``out`` or
    ``(out, cache_kvs)`` when caches are passed — the reference's
    convention.
    """
    from ..nn import functional as F
    from .attention import (NEG_INF, cache_mask, flash_attention,
                            flash_attention_reference)

    act = {"gelu": F.gelu, "relu": F.relu}[activation]
    b, s, _ = x.shape
    n_layers = len(qkv_weights)
    new_caches = [] if cache_kvs is not None else None
    pos = 0 if time_step is None else time_step

    def ln(v, scales, biases, i):
        return F.layer_norm(v, [v.shape[-1]], scales[i],
                            biases[i] if biases else None, epsilon=epsilon)

    def drop(v):
        return F.dropout(v, p=dropout_rate, training=training) \
            if dropout_rate > 0.0 else v

    out = x
    for i in range(n_layers):
        residual = out
        h = ln(out, ln_scales, ln_biases, i) if pre_layer_norm else out
        wq = qkv_weights[i]                 # (3, nh, hd, E)
        _, nh, hd, e = wq.shape
        qkv = jnp.einsum("bse,cnhe->cbsnh", h, wq)     # (3, B, S, nh, hd)
        if qkv_biases and qkv_biases[i] is not None:
            qkv = qkv + qkv_biases[i].reshape(3, 1, 1, nh, hd)
        q, k, v = qkv[0], qkv[1], qkv[2]               # (B, S, nh, hd)

        if cache_kvs is not None:
            cache = cache_kvs[i]                       # (2, B, nh, L, hd)
            k_c = jax.lax.dynamic_update_slice(
                cache[0], jnp.swapaxes(k, 1, 2).astype(cache.dtype),
                (0, 0, pos, 0))
            v_c = jax.lax.dynamic_update_slice(
                cache[1], jnp.swapaxes(v, 1, 2).astype(cache.dtype),
                (0, 0, pos, 0))
            new_caches.append(jnp.stack([k_c, v_c]))
            if (isinstance(pos, int) and pos == 0 and s > 1
                    and attn_mask is None):
                # prefill: attention over the cache at pos 0 is exactly
                # causal attention over the fresh K/V — take the flash
                # kernel instead of an O(S·max_len) masked math pass
                attn = flash_attention(q, k, v, causal=True)
            else:
                mask = cache_mask(pos, s, k_c.shape[2])
                if attn_mask is not None:  # padding masks compose
                    mask = (mask & attn_mask
                            if attn_mask.dtype == jnp.bool_
                            else jnp.where(mask, attn_mask,
                                           jnp.float32(NEG_INF)))
                attn = flash_attention_reference(
                    q, jnp.swapaxes(k_c, 1, 2), jnp.swapaxes(v_c, 1, 2),
                    attn_mask=mask, return_lse=False)
        else:
            # same semantics either way: causal, with an optional padding
            # mask composed on top (never REPLACING causality — the two
            # branches must agree for identical arguments)
            attn = flash_attention(q, k, v, causal=True,
                                   attn_mask=attn_mask)
        proj = attn.reshape(b, s, nh * hd) @ linear_weights[i]
        if linear_biases and linear_biases[i] is not None:
            proj = proj + linear_biases[i]
        out = residual + drop(proj)
        if not pre_layer_norm:             # post-LN: normalise AFTER the add
            out = ln(out, ln_scales, ln_biases, i)

        residual = out
        h = (ln(out, ffn_ln_scales, ffn_ln_biases, i) if pre_layer_norm
             else out)
        h = h @ ffn1_weights[i]
        if ffn1_biases and ffn1_biases[i] is not None:
            h = h + ffn1_biases[i]
        h = act(h)
        h = h @ ffn2_weights[i]
        if ffn2_biases and ffn2_biases[i] is not None:
            h = h + ffn2_biases[i]
        out = residual + drop(h)
        if not pre_layer_norm:
            out = ln(out, ffn_ln_scales, ffn_ln_biases, i)

    if cache_kvs is not None:
        return out, new_caches
    return out
