"""Composite "fused" ops (parity: paddle.incubate.nn.functional fusions).

The reference hand-writes these as single CUDA kernels
(paddle/phi/kernels/fusion/gpu/, upstream layout).  Here they are
*compositions*: under jit XLA fuses the elementwise chain into its
neighbours, which is exactly the design stance SURVEY §7 prescribes — and
the measured lesson of BENCH_OPS.json (the hand-written Pallas rms_norm
lost to XLA at every shape once dispatch latency was excluded).  The
names exist for API parity and as the contract a future Pallas kernel
would have to beat, not because a kernel hides behind them.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["fused_bias_dropout_residual_layer_norm",
           "variable_length_memory_efficient_attention",
           "fused_multi_transformer",
           # round-5 tranche (remaining paddle.incubate.nn.functional)
           "fused_linear", "fused_linear_activation", "fused_dropout_add",
           "fused_layer_norm", "fused_feedforward", "fused_attention",
           "masked_multihead_attention"]


def fused_linear(x, weight, bias=None, transpose_weight: bool = False):
    """matmul + bias in one call (parity: paddle.incubate.nn.functional.
    fused_linear — the cublasLt gemm-epilogue wrapper).  Under jit XLA
    fuses the bias add into the GEMM epilogue on its own; the name is the
    API contract."""
    w = jnp.swapaxes(weight, -1, -2) if transpose_weight else weight
    y = x @ w
    return y if bias is None else y + bias


def fused_linear_activation(x, y, bias=None, trans_x: bool = False,
                            trans_y: bool = False,
                            activation: Optional[str] = None):
    """GEMM + bias + activation epilogue (parity: paddle.incubate.nn.
    functional.fused_linear_activation)."""
    from ..nn import functional as F

    a = jnp.swapaxes(x, -1, -2) if trans_x else x
    b = jnp.swapaxes(y, -1, -2) if trans_y else y
    out = a @ b
    if bias is not None:
        out = out + bias
    act = {None: lambda v: v, "none": lambda v: v, "relu": F.relu,
           "gelu": F.gelu}[activation]
    return act(out)


def fused_dropout_add(x, y, p=0.5, training: bool = True,
                      mode: str = "upscale_in_train", name=None):
    """dropout(x) + y (parity: paddle.incubate.nn.functional.
    fused_dropout_add — one kernel upstream, one fused XLA region here)."""
    from ..nn import functional as F

    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_layer_norm(x, norm_weight, norm_bias, epsilon: float = 1e-5,
                     residual_alpha: float = 1.0, begin_norm_axis: int = 1,
                     bias=None, residual=None):
    """(x·1 + bias + residual_alpha·residual) → LayerNorm (parity:
    paddle.incubate.nn.functional.fused_layer_norm).  Returns the
    normalised output; the pre-norm sum is recomputed free under XLA
    fusion when a caller also needs it."""
    from ..nn import functional as F

    y = x
    if bias is not None:
        y = y + bias
    if residual is not None:
        y = y + residual_alpha * residual
    shape = y.shape[begin_norm_axis:]
    return F.layer_norm(y, list(shape), norm_weight, norm_bias,
                        epsilon=epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight,
                      linear1_bias=None, linear2_bias=None,
                      ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None,
                      dropout1_rate: float = 0.5,
                      dropout2_rate: float = 0.5,
                      activation: str = "relu",
                      ln1_epsilon: float = 1e-5, ln2_epsilon: float = 1e-5,
                      pre_layer_norm: bool = False,
                      training: bool = True):
    """The transformer FFN block as one call (parity: paddle.incubate.nn.
    functional.fused_feedforward):

        residual + dropout2(linear2(dropout1(act(linear1(ln(x))))))

    with LN before (pre_layer_norm) or after the residual add."""
    from ..nn import functional as F

    act = {"relu": F.relu, "gelu": F.gelu}[activation]
    residual = x
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, [h.shape[-1]], ln1_scale, ln1_bias,
                         epsilon=ln1_epsilon)
    h = h @ linear1_weight
    if linear1_bias is not None:
        h = h + linear1_bias
    h = F.dropout(act(h), p=dropout1_rate, training=training)
    h = h @ linear2_weight
    if linear2_bias is not None:
        h = h + linear2_bias
    out = residual + F.dropout(h, p=dropout2_rate, training=training)
    if not pre_layer_norm:
        out = F.layer_norm(out, [out.shape[-1]], ln2_scale, ln2_bias,
                           epsilon=ln2_epsilon)
    return out


def fused_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                    pre_ln_scale=None, pre_ln_bias=None,
                    ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
                    qkv_bias=None, linear_bias=None, cache_kv=None,
                    attn_mask=None, dropout_rate=0.5,
                    attn_dropout_rate=0.5, ln_epsilon=1e-5,
                    training: bool = True):
    """One whole attention block (parity: paddle.incubate.nn.functional.
    fused_attention): LN → QKV → MHA → out-proj → dropout → residual
    (→ LN when post-norm).  ``qkv_weight``: (3, num_head, head_dim,
    embed_dim); ``cache_kv``: optional (2, B, num_head, max_len, head_dim)
    to prepend (the reference's CacheKV decode form returns the attention
    over cache+fresh keys)."""
    from ..nn import functional as F
    from .attention import flash_attention

    b, s, e = x.shape
    residual = x
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, [e], pre_ln_scale, pre_ln_bias,
                         epsilon=pre_ln_epsilon)
    _, nh, hd, _ = qkv_weight.shape
    qkv = jnp.einsum("bse,cnhe->cbsnh", h, qkv_weight)
    if qkv_bias is not None:
        qkv = qkv + qkv_bias.reshape(3, 1, 1, nh, hd)
    q, k, v = qkv[0], qkv[1], qkv[2]
    if cache_kv is not None:
        k = jnp.concatenate([jnp.swapaxes(cache_kv[0], 1, 2), k], 1)
        v = jnp.concatenate([jnp.swapaxes(cache_kv[1], 1, 2), v], 1)
    attn = flash_attention(q, k, v, causal=cache_kv is None,
                           attn_mask=attn_mask,
                           dropout_p=attn_dropout_rate if training else 0.0)
    proj = attn.reshape(b, s, nh * hd) @ linear_weight
    if linear_bias is not None:
        proj = proj + linear_bias
    out = residual + F.dropout(proj, p=dropout_rate, training=training)
    if not pre_layer_norm:
        out = F.layer_norm(out, [e], ln_scale, ln_bias, epsilon=ln_epsilon)
    return out


def masked_multihead_attention(x, cache_kv, src_mask=None,
                               sequence_lengths=None, rotary_tensor=None,
                               seq_len: int = 1,
                               use_neox_rotary_style: bool = False):
    """One-token decode attention over a KV cache (parity: paddle.incubate.
    nn.functional.masked_multihead_attention — the reference's MMHA decode
    kernel, upstream fused_multi_transformer's per-step core).

    ``x``: (B, 3·H·D) fused QKV for the new token; ``cache_kv``:
    (2, B, H, max_len, D); ``sequence_lengths``: (B,) tokens already in the
    cache (defaults to 0 — the first step); ``src_mask``: optional
    (B, 1, 1, max_len+…) additive mask; ``rotary_tensor``: optional
    (B, 1, 1, D) [cos‖sin] rotary table for the current position (GPT-J
    interleave by default, NeoX half-split with ``use_neox_rotary_style``).
    Returns ``(out, cache_kv)`` with ``out``: (B, H·D).

    TPU design: the cache write is ``lax.dynamic_update_slice`` per row
    (vmap over the batch — rows decode at different positions), attention
    is the masked math path over the cache, the serving-measured regime
    (BENCH_DECODE.json) for single-token queries.
    """
    from . import _dispatch as _disp
    from .attention import NEG_INF

    # one path today (the masked math pass is the measured serving regime
    # for 1-token queries); counted so the op's dispatch is observable
    # alongside every other _dispatch decision
    _disp.count_kernel_path("masked_multihead_attention", "xla_math")

    two, b, h, max_len, d = cache_kv.shape
    assert two == 2
    qkv = x.reshape(b, 3, h, d)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]          # (B, H, D)
    if sequence_lengths is None:
        pos = jnp.zeros((b,), jnp.int32)
    else:
        pos = jnp.asarray(sequence_lengths, jnp.int32).reshape(b)
    if rotary_tensor is not None:
        rot = rotary_tensor.reshape(b, 1, -1)           # (B, 1, 2·D/2…)
        cos, sin = jnp.split(rot, 2, axis=-1)           # (B, 1, D/2)

        def rope(t):
            if use_neox_rotary_style:                   # half-split halves
                t1, t2 = jnp.split(t, 2, axis=-1)
            else:                                       # GPT-J interleave
                t1, t2 = t[..., 0::2], t[..., 1::2]
            r1 = t1 * cos - t2 * sin
            r2 = t2 * cos + t1 * sin
            if use_neox_rotary_style:
                return jnp.concatenate([r1, r2], -1)
            return jnp.stack([r1, r2], -1).reshape(t.shape)

        q, k = rope(q), rope(k)

    def write_row(cache_row, k_row, v_row, p):
        kc = jax.lax.dynamic_update_slice(cache_row[0], k_row[:, None],
                                          (0, p, 0))
        vc = jax.lax.dynamic_update_slice(cache_row[1], v_row[:, None],
                                          (0, p, 0))
        return jnp.stack([kc, vc])

    cache_kv = jax.vmap(write_row)(
        jnp.swapaxes(cache_kv, 0, 1), k.astype(cache_kv.dtype),
        v.astype(cache_kv.dtype), pos)
    cache_kv = jnp.swapaxes(cache_kv, 0, 1)
    kc, vc = cache_kv[0], cache_kv[1]                   # (B, H, L, D)
    # bf16 operands, fp32 accumulation — the cached_decode_attention
    # discipline: only the (B, H, L) score tile is fp32
    scores = jnp.einsum("bhd,bhld->bhl", q, kc,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(d))
    valid = jnp.arange(max_len)[None, None, :] <= pos[:, None, None]
    scores = jnp.where(valid, scores, jnp.float32(NEG_INF))
    if src_mask is not None:
        scores = scores + src_mask.reshape(b, 1, -1)[..., :max_len
                                                     ].astype(jnp.float32)
    w = jax.nn.softmax(scores, -1)
    out = jnp.einsum("bhl,bhld->bhd", w.astype(vc.dtype), vc,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, h * d).astype(x.dtype), cache_kv


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate: float = 0.5, ln_epsilon: float = 1e-5,
        training: bool = True):
    """layer_norm(residual + dropout(x + bias)) — the transformer block's
    post-attention epilogue as one jit-fusable expression."""
    from ..nn import functional as F

    y = x if bias is None else x + bias
    y = F.dropout(y, p=dropout_rate, training=training)
    y = residual + y
    return F.layer_norm(y, [y.shape[-1]], ln_scale, ln_bias,
                        epsilon=ln_epsilon)


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None,
        scale: Optional[float] = None, causal: bool = False):
    """Variable-length attention (parity: paddle.incubate.nn.functional.
    variable_length_memory_efficient_attention, the cutlass fMHA wrapper).

    Per-row valid lengths become position-range masks routed into the
    flash kernel via segment ids where eligible (padding positions get a
    sentinel segment so they attend nowhere) — the same in-kernel masking
    machinery the varlen training path uses; the XLA fallback materialises
    the mask.  query/key/value: (B, H, S, D) (the reference's layout);
    seq_lens/kv_seq_lens: (B,) valid lengths.  Returns (B, H, S, D).
    """
    from .attention import flash_attention

    b, h, s, d = query.shape
    skv = key.shape[2]
    # (B, S, H, D) is our kernel layout
    q = jnp.swapaxes(query, 1, 2)
    k = jnp.swapaxes(key, 1, 2)
    v = jnp.swapaxes(value, 1, 2)
    pos_q = jnp.arange(s)[None, :]
    pos_k = jnp.arange(skv)[None, :]
    # valid rows: segment 1; padding: distinct sentinels (2 for q, 3 for
    # kv) so cross-attention between padding rows is masked too
    seg_q = jnp.where(pos_q < jnp.asarray(seq_lens)[:, None], 1, 2)
    seg_k = jnp.where(pos_k < jnp.asarray(kv_seq_lens)[:, None], 1, 3)
    # segment ids ALWAYS apply (padding keys must never enter the
    # softmax); an additive mask composes with them — the dispatcher
    # folds both into the reference path when a custom mask forces it off
    # the kernel
    out = flash_attention(q, k, v, attn_mask=mask, causal=causal,
                          scale=scale, segment_ids=seg_q,
                          kv_segment_ids=seg_k)
    # zero the padding query rows (their softmax saw only masked keys)
    out = jnp.where((pos_q < jnp.asarray(seq_lens)[:, None])[..., None,
                                                             None],
                    out, 0.0)
    return jnp.swapaxes(out, 1, 2)


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases,
        linear_weights, linear_biases, ffn_ln_scales, ffn_ln_biases,
        ffn1_weights, ffn1_biases, ffn2_weights, ffn2_biases,
        pre_layer_norm: bool = True, epsilon: float = 1e-5,
        cache_kvs=None, time_step=None, attn_mask=None,
        activation: str = "gelu", dropout_rate: float = 0.0,
        training: bool = False):
    """Whole decoder stack in one call (parity: paddle.incubate.nn.
    functional.fused_multi_transformer — the reference's single fused
    inference kernel for serving stacks).

    Composition by design: each layer is pre-LN → QKV → causal attention
    (flash kernel when eligible; cached math path at decode) → out proj →
    residual → FFN, and XLA fuses the chain — the measured stance of
    BENCH_OPS.json.  Per-layer params arrive as lists, paddle's layout:
    ``qkv_weights[i]``: (3, num_head, head_dim, embed_dim);
    ``linear_weights[i]``: (num_head·head_dim, embed_dim);
    ``ffn1_weights[i]``: (embed_dim, ffn_dim); ``ffn2_weights[i]``:
    (ffn_dim, embed_dim).

    ``cache_kvs``: optional list of (2, B, num_head, max_len, head_dim)
    arrays; with ``time_step`` (an int: tokens already cached) the call is
    one decode step over the cache.  Returns ``out`` or
    ``(out, cache_kvs)`` when caches are passed — the reference's
    convention.
    """
    from ..nn import functional as F
    from .attention import (NEG_INF, cache_mask, cached_decode_attention,
                            flash_attention, flash_attention_reference)

    act = {"gelu": F.gelu, "relu": F.relu}[activation]
    b, s, _ = x.shape
    n_layers = len(qkv_weights)
    new_caches = [] if cache_kvs is not None else None
    pos = 0 if time_step is None else time_step

    # the attention-path decision is loop-invariant; count it ONCE per
    # trace so ops.kernel_path{op="fused_multi_transformer"} says which
    # regime each compiled stack took (same discipline as the
    # attention/matmul dispatchers — a routing regression is a counter
    # move, not a perf mystery)
    from . import _dispatch as _disp
    if cache_kvs is None:
        _disp.count_kernel_path("fused_multi_transformer", "flash_causal")
    elif isinstance(pos, int) and pos == 0 and s > 1 and attn_mask is None:
        _disp.count_kernel_path("fused_multi_transformer", "flash_prefill")
    elif attn_mask is None:
        _disp.count_kernel_path("fused_multi_transformer", "cached_decode")
    else:
        _disp.count_kernel_path("fused_multi_transformer",
                                "masked_reference")

    def ln(v, scales, biases, i):
        return F.layer_norm(v, [v.shape[-1]], scales[i],
                            biases[i] if biases else None, epsilon=epsilon)

    def drop(v):
        return F.dropout(v, p=dropout_rate, training=training) \
            if dropout_rate > 0.0 else v

    out = x
    for i in range(n_layers):
        residual = out
        h = ln(out, ln_scales, ln_biases, i) if pre_layer_norm else out
        wq = qkv_weights[i]                 # (3, nh, hd, E)
        _, nh, hd, e = wq.shape
        qkv = jnp.einsum("bse,cnhe->cbsnh", h, wq)     # (3, B, S, nh, hd)
        if qkv_biases and qkv_biases[i] is not None:
            qkv = qkv + qkv_biases[i].reshape(3, 1, 1, nh, hd)
        q, k, v = qkv[0], qkv[1], qkv[2]               # (B, S, nh, hd)

        if cache_kvs is not None:
            cache = cache_kvs[i]                       # (2, B, nh, L, hd)
            # chunk-sized in-place writes (never rebuild the full cache —
            # the whole-slice jnp.stack form forced per-step cache copies;
            # see LlamaAttention.decode's measured note)
            cache = jax.lax.dynamic_update_slice(
                cache, jnp.swapaxes(k, 1, 2).astype(cache.dtype)[None],
                (0, 0, 0, pos, 0))
            cache = jax.lax.dynamic_update_slice(
                cache, jnp.swapaxes(v, 1, 2).astype(cache.dtype)[None],
                (1, 0, 0, pos, 0))
            new_caches.append(cache)
            if (isinstance(pos, int) and pos == 0 and s > 1
                    and attn_mask is None):
                # prefill: attention over the cache at pos 0 is exactly
                # causal attention over the fresh K/V — take the flash
                # kernel instead of an O(S·max_len) masked math pass
                attn = flash_attention(q, k, v, causal=True)
            elif attn_mask is None:
                attn = cached_decode_attention(
                    q, jnp.swapaxes(cache[0], 1, 2),
                    jnp.swapaxes(cache[1], 1, 2), pos)
            else:
                mask = cache_mask(pos, s, cache.shape[3])
                mask = (mask & attn_mask
                        if attn_mask.dtype == jnp.bool_
                        else jnp.where(mask, attn_mask,
                                       jnp.float32(NEG_INF)))
                attn = flash_attention_reference(
                    q, jnp.swapaxes(cache[0], 1, 2),
                    jnp.swapaxes(cache[1], 1, 2),
                    attn_mask=mask, return_lse=False)
        else:
            # same semantics either way: causal, with an optional padding
            # mask composed on top (never REPLACING causality — the two
            # branches must agree for identical arguments)
            attn = flash_attention(q, k, v, causal=True,
                                   attn_mask=attn_mask)
        proj = attn.reshape(b, s, nh * hd) @ linear_weights[i]
        if linear_biases and linear_biases[i] is not None:
            proj = proj + linear_biases[i]
        out = residual + drop(proj)
        if not pre_layer_norm:             # post-LN: normalise AFTER the add
            out = ln(out, ln_scales, ln_biases, i)

        residual = out
        h = (ln(out, ffn_ln_scales, ffn_ln_biases, i) if pre_layer_norm
             else out)
        h = h @ ffn1_weights[i]
        if ffn1_biases and ffn1_biases[i] is not None:
            h = h + ffn1_biases[i]
        h = act(h)
        h = h @ ffn2_weights[i]
        if ffn2_biases and ffn2_biases[i] is not None:
            h = h + ffn2_biases[i]
        out = residual + drop(h)
        if not pre_layer_norm:
            out = ln(out, ffn_ln_scales, ffn_ln_biases, i)

    if cache_kvs is not None:
        return out, new_caches
    return out
