"""RMSNorm — reference XLA implementation + Pallas TPU kernel entry.

Equivalent of the reference's fused rms_norm CUDA kernel
(upstream layout: paddle/phi/kernels/fusion/gpu/fused_rms_norm* /
paddle.incubate.nn.functional.fused_rms_norm).  On TPU, XLA already fuses
the reduction + scale into neighbouring ops well; the Pallas kernel exists
for the long-row case where controlling the tiling beats XLA's default.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def rms_norm_reference(x, weight=None, epsilon: float = 1e-6):
    dt = x.dtype
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * lax.rsqrt(ms + epsilon)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    return y.astype(dt)


def rms_norm(x, weight=None, epsilon: float = 1e-6):
    # XLA fuses this well on TPU; keep one entry point so a Pallas kernel can
    # be swapped in for shapes where it wins (measured, not assumed).
    return rms_norm_reference(x, weight, epsilon)
