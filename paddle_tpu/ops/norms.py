"""RMSNorm — XLA reference implementation + long-row Pallas TPU kernel.

Equivalent of the reference's fused rms_norm CUDA kernel
(upstream layout: paddle/phi/kernels/fusion/gpu/fused_rms_norm* /
paddle.incubate.nn.functional.fused_rms_norm).  Inside a transformer block
XLA fuses the norm into its matmul neighbours and there is nothing to win;
the Pallas kernel (pallas/rms_norm.py) targeted the *standalone long-row*
case.  Gradients always take the XLA reference path (one owner for
training numerics); the kernel covers forward/inference.

Measurement history — an honesty correction (round 4): the round-3
docstring claimed up to 1.73x over XLA from a per-call timing loop.  The
checked-in harness (``python bench.py --op rms_norm`` → BENCH_OPS.json)
re-measured with tunnel dispatch latency excluded (in-graph chained
iterations, two-point differencing — see bench._time_compiled) and found
**XLA as fast or faster at every shape** (Pallas at 0.46–0.73x on the
shapes too large for VMEM residency effects).  The 1.73x was dispatch
latency, not kernel time.  Accordingly ``FLAGS_rms_norm_pallas_min_dim``
now defaults to disabled; the kernel remains as an opt-in reference and
the Mosaic testbed the TPU lane exercises (tests/test_tpu_lane.py pins
its numerics on-chip at an explicit threshold).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .. import flags
from . import _dispatch


def rms_norm_reference(x, weight=None, epsilon: float = 1e-6):
    dt = x.dtype
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * lax.rsqrt(ms + epsilon)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    return y.astype(dt)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms_pallas_diffable(x, weight, epsilon, interpret):
    from .pallas.rms_norm import rms_norm_pallas
    return rms_norm_pallas(x, weight, epsilon, interpret=interpret)


def _rms_fwd(x, weight, epsilon, interpret):
    return _rms_pallas_diffable(x, weight, epsilon, interpret), (x, weight)


def _rms_bwd(epsilon, interpret, res, g):
    x, weight = res
    if weight is None:
        _, vjp = jax.vjp(lambda x_: rms_norm_reference(x_, None, epsilon), x)
        return vjp(g) + (None,)
    _, vjp = jax.vjp(
        lambda x_, w_: rms_norm_reference(x_, w_, epsilon), x, weight)
    return vjp(g)


_rms_pallas_diffable.defvjp(_rms_fwd, _rms_bwd)


def rms_norm(x, weight=None, epsilon: float = 1e-6):
    """Public entry (parity: fused_rms_norm).  Routes long rows to the
    Pallas kernel on TPU; everything else to the XLA reference.  Every
    routing decision is counted into ``ops.kernel_path{op="rms_norm"}``
    at trace time, like the attention/matmul dispatchers."""
    if (_dispatch.use_pallas()
            and x.shape[-1] >= flags.flag("rms_norm_pallas_min_dim")):
        try:
            out = _rms_pallas_diffable(x, weight, epsilon,
                                       _dispatch.pallas_interpret())
            _dispatch.count_kernel_path("rms_norm", "pallas")
            return out
        except NotImplementedError:
            pass
    _dispatch.count_kernel_path("rms_norm", "xla_reference")
    return rms_norm_reference(x, weight, epsilon)
