"""Pallas TPU kernels — the framework's equivalent of the reference's
hand-written CUDA kernels (paddle/phi/kernels/fusion/gpu/, upstream layout)."""
