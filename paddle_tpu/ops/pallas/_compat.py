"""Small jax-version compat shims for the Pallas TPU kernels.

``pltpu.TPUCompilerParams`` was renamed ``pltpu.CompilerParams`` across
jax releases; the kernels in this package run on both spellings.
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")
