"""Split-KV flash-decode Pallas TPU kernel — the batched/long-context
serving hot path.

TPU-native equivalent of the FlashDecoding scheme (Dao et al.; the
PagedAttention-class engines' decode kernel on GPU): at q_len 1 the
(1, L) score row gives the MXU nothing to tile, so the win is pure
dataflow — split the KV cache into chunks, keep online-softmax partials
(m, l, acc) in VMEM across the chunk walk, and never materialise the
(B, Hq, s, L) score tensor the XLA math path
(:func:`~paddle_tpu.ops.attention.cached_decode_attention_reference`)
builds in HBM.

What makes this kernel O(actual context depth) instead of O(max_length)
— the regime BENCH_DECODE.json flagged (b=8, max_length 8192: 4.27 ms vs
the 2.78 ms bf16 weight-stream floor, 0.652x of the bound, because the
math path streams and mask-softmaxes the dead tail of the pre-allocated
cache every step):

  * per-row positions arrive as a **scalar-prefetch** operand, so the
    KV-chunk BlockSpec index maps can read them *before* the grid step
    runs and **clamp dead-tail chunks to the last live block** — Pallas
    elides the DMA when consecutive grid steps map to the same block, so
    the dead tail of the cache is never streamed from HBM.  This is the
    dynamic-shape-safe form of "the host passes ceil((max(pos)+s)/BLOCK)
    as the KV-chunk grid bound": the bound is derived in-kernel from the
    position vector itself, the grid stays static, and the serving
    engine's once-jitted step function never retraces as slots deepen;
  * a caller who *does* know a static bound (the bench depth sweep)
    passes ``live_len`` and the grid is trimmed outright;
  * dead chunks also skip their matmuls via ``pl.when`` — a skipped
    chunk costs one predicated-off grid step, not bandwidth.

GQA stays grouped: Q is reshaped to (B, Hkv, G·s, D) and each kv head's
(G·s, D) query tile contracts the cache directly — bf16 operands on the
MXU with an fp32 accumulator, no Hq/Hkv KV broadcast.  The cache is read
in its **native** (B, L, Hkv, D) layout, viewed as (B, L, Hkv·D) so each
KV chunk is one contiguous DMA; the per-head (bk, D) slice is a static
lane slice in VMEM.  Per-row ``pos`` masking happens inside the kernel
(key j visible to query row (si, g) iff j <= pos_b + si) with the same
fully-masked-row convention as the flash kernel (out = 0).

The cross-chunk merge is the same LSE algebra the ring-attention path
uses (ops/ring_attention.py ``merge_attention``), specialised to the
running (m, l, acc) form since chunks arrive sequentially.

**Chunked prefill** (serving/engine.py mixed steps): the same kernel
generalises from q_len 1 to a q *chunk* — a span of prompt tokens
attending its cached prefix plus its own causal self-block.  q is cut
into tiles of ``bq`` tokens (``bq·G <= 64`` MXU rows each, sublane-padded
per tile) walked by a second grid dimension; the per-row ``pos`` mask
already encodes "key j visible to query offset si iff j <= pos + si", so
prefix + self-block causality needs no new machinery, and the dead-tail
clamp becomes per-tile (early q tiles skip the chunk's own later KV
blocks — causal block skipping for free).  Routing for these shapes is
counted under ``ops.kernel_path{op="chunked_prefill"}``.

**Speculative verify** (serving/engine.py spec-decode steps): the q-tile
machinery above IS the verify pass of self-drafted speculative decoding —
a (B, k+1) window of [current token, k drafts] at per-row depths scores
every draft in ONE pass of the weights, because the per-row ``pos`` mask
already gives query offset ``si`` exactly the causal view "cached prefix
+ the window's own earlier tokens".  The dispatch contract is the
chunked-prefill one (``s <= 2048``, ``s·G`` tiled at 64 rows), no new
kernel surface; the engine wraps its verify trace in
``ops._dispatch.kernel_path_hint("spec_verify")`` so these builds (and
their routing decisions) land under ``ops.kernel_path{op="spec_verify"}``
instead of the prefill-chunk label.

**Paged KV cache** (serving/kv_cache.py): the kernel also serves the
block-table layout, where the cache is one pooled ``(num_blocks,
block_len, Hkv, D)`` array and each row's logical positions are backed by
the physical blocks its ``(B, max_blocks)`` block table names.  The table
rides in as a SECOND scalar-prefetch operand and the KV-chunk index maps
dereference it: grid step (bi, ki) DMAs physical block
``table[bi, min(ki, last_live)]``.  One KV chunk == one cache block
(``block_len`` must be 128-aligned), so a block is one contiguous DMA
exactly as before, blocks may be scattered anywhere in the pool, shared
between rows, or partially filled (the in-kernel ``pos`` mask already
handles partial blocks — column indices are logical).  The contiguous
layout is the degenerate case: the caller's cache reshapes to a
``(B·chunks, bk, Hkv·D)`` pool (a free view) under the identity table
``table[bi, ki] = bi·chunks + ki``, which is how PR 2's dead-tail
clamping now reads — clamping the logical chunk index before the table
lookup maps dead-tail grid steps to the row's last live block, the DMA is
elided, and HBM traffic still stops at the live prefix.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._compat import CompilerParams
from . import limits as _limits

NEG_INF = -1e30
# shape bounds live in ops/pallas/limits.py — ONE source of truth shared
# with the dispatch gate (ops.attention.decode_shape_gate) and the
# kernel pre-flight (static_analysis/kernel_registry.py); the
# dispatch-agreement lint proves the three stay in step
_LANES = _limits.LANES  # VPU lane width: m/l scratch rows padded to this
_MAX_Q_ROWS = _limits.MAX_Q_ROWS  # per-TILE s·G row cap — larger q tiles
_MAX_Q_LEN = _limits.MAX_Q_LEN  # beyond this: whole-prefill, flash territory


def _pick_block_kv(kv_len: int, cap: int) -> int:
    """Largest KV chunk <= cap that divides kv_len on the 128-lane
    tiling; 0 when none exists (caller falls back to XLA)."""
    for d in range(min(cap, kv_len), 0, -1):
        if kv_len % d == 0 and d % 128 == 0:
            return d
    return 0


def _kernel(pos_ref, bt_ref, q_ref, k_ref, v_ref, *refs, scale, s, g,
            hkv, d, bq, tile_p, bk, chunks, quantized):
    if quantized:
        # int8 cache: the per-block-per-kv-head scales ride as two more
        # block-table-indexed operands (same index map, same dead-tail
        # clamp, same DMA elision) — one (1, hkv) f32 row per KV chunk
        ks_ref, vs_ref, o_ref, acc_sc, m_sc, l_sc = refs
    else:
        o_ref, acc_sc, m_sc, l_sc = refs
    del bt_ref  # consumed by the index maps, not the body
    bi = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    pos_b = pos_ref[bi]
    # last chunk holding a key visible to ANY row of this q tile (query
    # offsets qi·bq .. min((qi+1)·bq, s) - 1)
    last_live = (pos_b + jnp.minimum((qi + 1) * bq, s) - 1) // bk

    @pl.when(ki == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)

    @pl.when(ki <= last_live)
    def _compute():
        # key j visible to tile row r = si·g + gi (si local to the tile)
        # iff j <= pos_b + qi·bq + si; rows past bq·g are sublane padding
        # and rows whose query offset runs past s are the last tile's
        # ragged tail — both fully masked (out = 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (tile_p, bk), 1) + ki * bk
        rr = jax.lax.broadcasted_iota(jnp.int32, (tile_p, bk), 0)
        si = qi * bq + rr // g
        keep = (cols <= pos_b + si) & (rr < bq * g) & (si < s)
        kv = k_ref[0]  # (bk, hkv·d) — one contiguous chunk, all kv heads
        vv = v_ref[0]
        for h in range(hkv):
            qh = q_ref[0, h]                   # (tile_p, d)
            kh = kv[:, h * d:(h + 1) * d]      # static lane slice
            vh = vv[:, h * d:(h + 1) * d]
            if quantized:
                # int8 in [-127, 127] is exact in bf16, so the cast is
                # lossless; the block's uniform scale folds into the
                # existing post-dot scalar multiplies (K into the
                # softmax scale, V after the PV accumulate) — no
                # per-element dequant multiply on the chunk
                kh = kh.astype(qh.dtype)
                vh = vh.astype(qh.dtype)
                k_s = scale * ks_ref[0, h]
                v_s = vs_ref[0, h]
            else:
                k_s = scale
            sc = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * k_s  # (tile_p, bk)
            sc = jnp.where(keep, sc, NEG_INF)
            m_prev = m_sc[h][:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)    # rescale earlier chunks
            p = jnp.exp(sc - m_new)
            p = jnp.where(keep, p, 0.0)  # kill exp(NEG_INF - NEG_INF) = 1
            l_new = alpha * l_sc[h][:, :1] + jnp.sum(p, axis=1,
                                                     keepdims=True)
            pv = jax.lax.dot_general(
                p.astype(vh.dtype), vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            if quantized:
                pv = pv * v_s
            acc_sc[h] = acc_sc[h] * alpha + pv
            m_sc[h] = jnp.broadcast_to(m_new, m_sc[h].shape)
            l_sc[h] = jnp.broadcast_to(l_new, l_sc[h].shape)

    @pl.when(ki == chunks - 1)
    def _finish():
        for h in range(hkv):
            l = l_sc[h][:, :1]
            o_ref[0, h] = (acc_sc[h]
                           / jnp.maximum(l, 1e-37)).astype(o_ref.dtype)


def decode_attention_pallas(q, k_cache, v_cache, pos,
                            scale: Optional[float] = None,
                            block_kv: int = 0,
                            live_len: Optional[int] = None,
                            interpret: bool = False,
                            block_tables=None,
                            k_scale=None, v_scale=None):
    """Flash-decode over a pre-allocated cache → (B, s, Hq, D) in q.dtype.

    q: (B, s, Hq, D) new-token queries (s = 1 in steady-state decode,
    small for prefill-into-occupied-slot); ``pos``: scalar or int (B,)
    per-row positions — cache slots > pos+i are masked.  Two cache
    layouts:

      * **contiguous** (``block_tables`` is None): k_cache/v_cache are
        (B, L, Hkv, D) with the new K/V already written;
      * **paged**: k_cache/v_cache are the pooled (num_blocks, block_len,
        Hkv, D) arrays and ``block_tables`` is the int (B, max_blocks)
        map from each row's logical block index to its physical block
        (serving/kv_cache.py conventions: every entry valid, dead tail
        null-filled).  The logical cache length is
        ``max_blocks · block_len`` and the KV chunk is pinned to one
        block, so ``block_len`` must be 128-aligned.

    ``live_len``: optional static bound on max(pos)+s (trims the chunk
    grid outright; without it the scalar-prefetch clamp stops the HBM
    streaming at each row's live prefix dynamically).  Raises
    NotImplementedError for shapes the kernel does not cover (callers
    fall back to the XLA math path).

    **int8 cache** (``k_scale``/``v_scale`` given): k_cache/v_cache hold
    int8 payloads and the f32 scales carry the per-block-per-kv-head
    dequant factor — paged: ``(num_blocks, Hkv)`` rows of the same pool
    the block table indexes; contiguous: ``(B, n_granules, Hkv)`` where
    the KV chunk is pinned to the scale granule
    (``kv_len // n_granules``, 128-aligned).  Dequant happens inside the
    chunk loop by folding each block's scale into the post-dot scalar
    multiplies, so the HBM stream is the int8 payload — half the bf16
    bytes.
    """
    b, s, hq, d = q.shape
    quantized = k_scale is not None
    if quantized and v_scale is None:
        raise ValueError("int8 cache needs both k_scale and v_scale")
    if block_tables is not None:
        n_pool, bk, hkv, _ = k_cache.shape
        if bk % 128:
            raise NotImplementedError(
                f"paged block_len {bk} is not 128-aligned")
        bt = jnp.asarray(block_tables, jnp.int32)
        kv_len = bt.shape[1] * bk
        # pool layout: one physical block == one KV chunk == one DMA
        k2 = k_cache.reshape(n_pool, bk, hkv * d)
        v2 = v_cache.reshape(n_pool, bk, hkv * d)
        if quantized:
            ks2 = jnp.asarray(k_scale, jnp.float32).reshape(n_pool, hkv)
            vs2 = jnp.asarray(v_scale, jnp.float32).reshape(n_pool, hkv)
    else:
        _, kv_len, hkv, _ = k_cache.shape
    if hq % hkv or hkv == 0:
        raise NotImplementedError(
            f"q heads ({hq}) must be a multiple of kv heads ({hkv})")
    g = hq // hkv
    rows = s * g
    if g > _MAX_Q_ROWS:
        raise NotImplementedError(f"GQA group size {g} > {_MAX_Q_ROWS}")
    if s > _MAX_Q_LEN:
        raise NotImplementedError(
            f"q_len {s} > {_MAX_Q_LEN}: whole-prefill-shaped q belongs to "
            f"the flash kernel")
    if d > _limits.MAX_HEAD_DIM:
        raise NotImplementedError(
            f"head_dim {d} > {_limits.MAX_HEAD_DIM}")
    # q tiling: one grid step covers bq query tokens (bq·g MXU rows).
    # s <= bq is the steady-decode / small-s case — nq == 1, exactly the
    # original kernel.  Larger s (a chunked-prefill q chunk attending its
    # paged prefix plus its own causal self-block) walks q tiles over a
    # second grid dimension; the per-tile dead-tail clamp skips KV chunks
    # past pos + (qi+1)·bq - 1, so early tiles also skip the chunk's own
    # later keys — causal block skipping for free.
    bq = min(s, max(1, _MAX_Q_ROWS // g))
    nq = -(-s // bq)
    if scale is None:
        scale = d ** -0.5
    if block_tables is None:
        if quantized:
            # the scale granule pins the KV chunk: one chunk == one
            # (block, head) scale entry, exactly the paged contract
            n_gran = k_scale.shape[1]
            bk = kv_len // n_gran
            if bk * n_gran != kv_len or bk % 128:
                raise NotImplementedError(
                    f"int8 scale granule {kv_len}/{n_gran} is not a "
                    f"128-aligned divisor of the cache length")
        else:
            if not block_kv:
                from ...flags import flag
                block_kv = int(flag("decode_attention_block_kv"))
            bk = _pick_block_kv(kv_len, block_kv)
            if not bk:
                raise NotImplementedError(
                    f"max_length {kv_len} has no 128-aligned chunk "
                    f"divisor <= {block_kv}")
        # contiguous = paged under the identity table: view the cache as a
        # (B·chunks, bk, Hkv·D) pool (free reshape) with table
        # [bi, ki] = bi·chunks + ki — same DMAs, one code path
        full = kv_len // bk
        bt = (jnp.arange(b, dtype=jnp.int32)[:, None] * full
              + jnp.arange(full, dtype=jnp.int32)[None, :])
        k2 = k_cache.reshape(b * full, bk, hkv * d)
        v2 = v_cache.reshape(b * full, bk, hkv * d)
        if quantized:
            ks2 = jnp.asarray(k_scale, jnp.float32).reshape(
                b * full, hkv)
            vs2 = jnp.asarray(v_scale, jnp.float32).reshape(
                b * full, hkv)
    chunks = kv_len // bk
    if live_len is not None:
        chunks = max(1, min(chunks, -(-int(live_len) // bk)))
    tile_p = max(8, -(-(bq * g) // 8) * 8)  # sublane-pad each q tile
    if getattr(pos, "ndim", 0) == 1:
        pos_arr = jnp.asarray(pos, jnp.int32)
    else:
        pos_arr = jnp.full((b,), pos, jnp.int32)
    # grouped-GQA q layout: (B, Hkv, s·G, D), row r = si·g + gi — then cut
    # into nq tiles of bq·g rows, each sublane-padded to tile_p, so one
    # BlockSpec block == one padded tile at row offset qi·tile_p
    qg = q.reshape(b, s, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b, hkv, rows, d)
    if nq * bq * g != rows:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, nq * bq * g - rows), (0, 0)))
    qg = qg.reshape(b, hkv, nq, bq * g, d)
    if tile_p != bq * g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, 0), (0, tile_p - bq * g),
                          (0, 0)))
    qg = qg.reshape(b, hkv, nq * tile_p, d)

    # past every eligibility gate: this trace builds the kernel — count
    # which cache layout it was built for (routing visibility, trace-time
    # side effect only); a tiled q walk is the chunked-prefill mode, and
    # an active kernel_path_hint relabels the build (the serving engine's
    # speculative verify window counts as op="spec_verify" — same q-tiled
    # machinery, different meaning: the q rows are draft tokens scored
    # against the live cache, not a prompt chunk streaming in)
    from .. import _dispatch as _disp
    _disp.count_kernel_path(
        _disp.kernel_path_op(
            "chunked_prefill" if nq > 1 else "decode_attention_kernel"),
        "paged" if block_tables is not None else "contiguous",
        **({"cache": "int8"} if quantized else {}))

    kernel = functools.partial(
        _kernel, scale=float(scale), s=s, g=g, hkv=hkv, d=d, bq=bq,
        tile_p=tile_p, bk=bk, chunks=chunks, quantized=quantized)

    def q_idx(bi, qi, ki, pos_ref, bt_ref):
        return (bi, 0, qi, 0)

    def kv_idx(bi, qi, ki, pos_ref, bt_ref):
        # clamp the LOGICAL chunk index to this q tile's last live block,
        # then dereference the block table: dead-tail chunks re-map to the
        # same physical block as the previous grid step → Pallas elides
        # the DMA, so HBM traffic stops at the tile's live prefix.
        # Null-block aliasing rule (checked statically by the kernel
        # pre-flight's ClampCheck and asserted by kv_cache.table_row):
        # dead-tail table columns past `last` MAY hold NULL_BLOCK (0) —
        # the clamp guarantees they are never dereferenced — but a LIVE
        # column (<= last) mapping to block 0 would alias the null
        # block's pad data into this row's attention window.
        last = (pos_ref[bi] + jnp.minimum((qi + 1) * bq, s) - 1) // bk
        return (bt_ref[bi, jnp.minimum(ki, last)], 0, 0)

    def sc_idx(bi, qi, ki, pos_ref, bt_ref):
        # the scale rows ride the same table dereference (and the same
        # dead-tail clamp) as the KV chunks they dequantize
        last = (pos_ref[bi] + jnp.minimum((qi + 1) * bq, s) - 1) // bk
        return (bt_ref[bi, jnp.minimum(ki, last)], 0)

    in_specs = [
        pl.BlockSpec((1, hkv, tile_p, d), q_idx),
        pl.BlockSpec((1, bk, hkv * d), kv_idx),
        pl.BlockSpec((1, bk, hkv * d), kv_idx),
    ]
    operands = (pos_arr, bt, qg, k2, v2)
    if quantized:
        in_specs += [pl.BlockSpec((1, hkv), sc_idx),
                     pl.BlockSpec((1, hkv), sc_idx)]
        operands += (ks2, vs2)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, nq, chunks),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, hkv, tile_p, d), q_idx),
            scratch_shapes=[
                pltpu.VMEM((hkv, tile_p, d), jnp.float32),
                pltpu.VMEM((hkv, tile_p, _LANES), jnp.float32),
                pltpu.VMEM((hkv, tile_p, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, nq * tile_p, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(*operands)
    out = out.reshape(b, hkv, nq, tile_p, d)[:, :, :, :bq * g]
    out = out.reshape(b, hkv, nq * bq * g, d)[:, :, :rows]
    out = out.reshape(b, hkv, s, g, d).transpose(0, 2, 1, 3, 4)
    return out.reshape(b, s, hq, d).astype(q.dtype)
