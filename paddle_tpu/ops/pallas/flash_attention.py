"""Blocked flash-attention Pallas kernel (placeholder gate).

The real kernel lands with the Llama milestone; until then dispatch falls
back to the XLA reference implementation.
"""


def flash_attention_pallas(q, k, v, causal=False, scale=None, interpret=False):
    raise NotImplementedError
