"""Blocked flash-attention Pallas TPU kernel (forward + backward).

TPU-native equivalent of the reference's flash-attention integration
(upstream layout: paddle/phi/kernels/gpu/flash_attn_kernel.cu +
flash_attn_grad_kernel.cu, which wrap the external CUDA flashattn library).
Here the kernel is first-party, written for the MXU/VMEM architecture:

  * online-softmax forward (Flash-2): the KV loop is the innermost grid
    dimension; running max ``m``, normaliser ``l`` and the fp32 accumulator
    live in VMEM scratch that persists across that dimension, so the
    (Sq, Skv) score matrix never exists in HBM;
  * returns the per-row log-sum-exp (``softmax_lse`` in the reference's
    API) — the hook that makes ring/context-parallel attention possible;
  * backward recomputes scores blockwise from (q, k, v, out, lse) — the
    Flash-2 two-kernel scheme: one accumulating dq over KV blocks, one
    accumulating dk/dv over Q blocks, with ``delta = rowsum(dO·O)``
    precomputed in XLA;
  * GQA: K/V keep their own (fewer) heads; the BlockSpec index maps fold
    the q-head → kv-head mapping, so grouped KV is never broadcast in HBM;
  * causal masking is bottom-right aligned (matches the reference's
    flash-attn convention when Sq < Skv) and fully-masked tiles skip their
    matmuls via ``pl.when``.

Layout: public API takes (B, S, H, D) (the reference's flash-attn layout);
kernels run in (B, H, S, D).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._compat import CompilerParams

NEG_INF = -1e30
from . import limits as _limits

_LANES = _limits.LANES  # VPU lane width: m/l scratch rows padded to this


def _aligned_divisor(seq: int, cap: int, align: int) -> int:
    """Largest block <= cap that divides seq on the (8,128) register
    tiling — so any aligned seq gets the kernel at the best dividing tile
    instead of falling back when the flag doesn't divide it."""
    for d in range(min(cap, seq), 0, -1):
        if seq % d == 0 and d % align == 0:
            return d
    return min(cap, seq)  # none aligned: _validate rejects → XLA path


def _block_sizes(sq: int, skv: int, head_dim: int):
    """Tile sizes for the Pallas grid; tunable via the
    ``flash_attention_block_q``/``flash_attention_block_kv`` flags (parity:
    the reference's FLAGS-tuned fused-attention tiling).

    The flag values are swept at head_dim 128 (see flags.py); for larger
    heads the caps scale down by d/128 so the fp32 scores + q/kv/acc tiles
    stay inside VMEM — a Mosaic OOM is a hard compile error, not a
    catchable fallback."""
    from ...flags import flag
    scale = max(1, head_dim // 128)
    cap_q = max(8, int(flag("flash_attention_block_q")) // scale)
    cap_k = max(128, int(flag("flash_attention_block_kv")) // scale)
    return (_aligned_divisor(sq, cap_q, 8),
            _aligned_divisor(skv, cap_k, 128))


def _validate(q, k, v, sq, skv, bq, bk):
    if sq % bq or skv % bk:
        raise NotImplementedError(
            f"flash kernel needs seq divisible by block ({sq}%{bq}, "
            f"{skv}%{bk})")
    if bq % 8 or bk % 128:
        # scores tile is (bq sublanes x bk lanes): keep blocks on the
        # (8, 128) register tiling; odd seqs shorter than the block would
        # otherwise become odd-sized single blocks — let those take the
        # XLA path instead of a Mosaic corner case
        raise NotImplementedError(
            f"flash kernel blocks must align to (8, 128), got ({bq}, {bk})")
    if q.shape[-1] != k.shape[-1] or k.shape[:2] != v.shape[:2]:
        raise NotImplementedError("q/k/v head_dim mismatch")
    if k.shape[1] == 0 or q.shape[1] % k.shape[1]:
        raise NotImplementedError("q heads must be a multiple of kv heads")


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _seg_mask(sq_ref, skv_ref):
    """Segment-id blocks → (bq, bk) same-document mask.

    Blocks arrive pre-broadcast in Mosaic-friendly layouts (q ids over the
    lane dim, kv ids over sublanes — the (8,128) tiling forbids raw (1, b)
    blocks): sq_ref (1, bq, _LANES), skv_ref (1, 8, bk)."""
    return sq_ref[0][:, :1] == skv_ref[0][:1, :]


def _seg_broadcast(seg_q, seg_kv):
    """(B, Sq)/(B, Skv) ids → lane/sublane-broadcast arrays for the grid."""
    b, sq = seg_q.shape
    skv = seg_kv.shape[1]
    q3 = jnp.broadcast_to(seg_q.astype(jnp.int32)[:, :, None],
                          (b, sq, _LANES))
    kv3 = jnp.broadcast_to(seg_kv.astype(jnp.int32)[:, None, :],
                           (b, 8, skv))
    return q3, kv3


def _mask_for(causal, segmented, bq, bk, q_start, kv_start, offset,
              sq_ref, skv_ref):
    mask = None
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = (cols + kv_start) <= (rows + q_start + offset)
    if segmented:
        sm = _seg_mask(sq_ref, skv_ref)
        mask = sm if mask is None else (mask & sm)
    return mask


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, segmented,
                offset, bq, bk, kv_steps):
    if segmented:
        sq_ref, skv_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc = rest
    else:
        o_ref, lse_ref, acc_sc, m_sc, l_sc = rest
    ki = pl.program_id(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    q_start = qi * bq
    kv_start = ki * bk
    # bottom-right causal: query row i attends to kv cols <= i + offset;
    # fully-masked tiles skip their matmuls entirely
    run = (kv_start <= q_start + (bq - 1) + offset) if causal \
        else (ki >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # (bq, d)
        k = k_ref[0, 0].astype(jnp.float32)  # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        mask = _mask_for(causal, segmented, bq, bk, q_start, kv_start,
                         offset, sq_ref if segmented else None,
                         skv_ref if segmented else None)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_sc[:, :1]                                   # (bq, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)              # (bq, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                        # rescale old
        p = jnp.exp(s - m_new)                                 # (bq, bk)
        if mask is not None:
            # exp(NEG_INF - NEG_INF) = 1 for fully-masked rows; zero it
            p = jnp.where(mask, p, 0.0)
        l_new = alpha * l_sc[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)                    # (bk, d)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha + pv
        m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(ki == kv_steps - 1)
    def _finish():
        l = l_sc[:, :1]
        safe_l = jnp.maximum(l, 1e-37)
        o_ref[0, 0] = (acc_sc[:] / safe_l).astype(o_ref.dtype)
        lse = m_sc[:, :1] + jnp.log(safe_l)
        # fully-masked rows: lse = -inf-ish, out = 0 (matches reference).
        # lane dim broadcast to _LANES: TPU block tiling needs a 128 last dim
        lse_ref[0, 0] = jnp.broadcast_to(
            jnp.where(l > 0, lse, NEG_INF), (lse.shape[0], lse_ref.shape[-1]))


def _fwd(q, k, v, seg_q=None, seg_kv=None, scale: float = 1.0,
         causal: bool = False, interpret: bool = False):
    """q: (B, Hq, Sq, D); k, v: (B, Hkv, Skv, D) → (out, lse).
    seg_q/seg_kv: optional (B, Sq)/(B, Skv) int32 packed-document ids."""
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    g = hq // hkv
    bq, bk = _block_sizes(sq, skv, d)
    offset = skv - sq
    kv_steps = skv // bk
    segmented = seg_q is not None

    grid = (b, hq, sq // bq, skv // bk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, segmented=segmented,
        offset=offset, bq=bq, bk=bk, kv_steps=kv_steps)
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda b_, h, qi, ki: (b_, h, qi, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda b_, h, qi, ki: (b_, h // g, ki, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda b_, h, qi, ki: (b_, h // g, ki, 0)),
    ]
    args = [q, k, v]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, bq, _LANES), lambda b_, h, qi, ki: (b_, qi, 0)),
            pl.BlockSpec((1, 8, bk), lambda b_, h, qi, ki: (b_, 0, ki)),
        ]
        args += list(_seg_broadcast(seg_q, seg_kv))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h, qi, ki: (b_, h, qi, 0)),
            pl.BlockSpec((1, 1, bq, _LANES),
                         lambda b_, h, qi, ki: (b_, h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*args)
    return out, lse  # lse lane-broadcast (b, hq, sq, _LANES); callers slice


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                   scale, causal, segmented, offset, bq, bk, kv_steps):
    if segmented:
        sq_ref, skv_ref, dq_ref, dq_sc = rest
    else:
        sq_ref = skv_ref = None
        dq_ref, dq_sc = rest
    ki = pl.program_id(3)
    qi = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    q_start = qi * bq
    kv_start = ki * bk
    run = (kv_start <= q_start + (bq - 1) + offset) if causal \
        else (ki >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]                       # (bq, 1)
        delta = delta_ref[0, 0][:, :1]                   # (bq, 1)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _mask_for(causal, segmented, bq, bk, q_start, kv_start,
                         offset, sq_ref, skv_ref)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)                             # (bq, bk)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)  # kill exp(NEG_INF - NEG_INF) = 1
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_sc[:] += jax.lax.dot_general(ds, kb, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    @pl.when(ki == kv_steps - 1)
    def _finish():
        dq_ref[0, 0] = dq_sc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                    scale, causal, segmented, offset, bq, bk, q_steps):
    if segmented:
        sq_ref, skv_ref, dk_ref, dv_ref, dk_sc, dv_sc = rest
    else:
        sq_ref = skv_ref = None
        dk_ref, dv_ref, dk_sc, dv_sc = rest
    qi = pl.program_id(3)
    ki = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    q_start = qi * bq
    kv_start = ki * bk
    run = (kv_start <= q_start + (bq - 1) + offset) if causal \
        else (ki >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        kb = k_ref[0, 0].astype(jnp.float32)
        vb = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _mask_for(causal, segmented, bq, bk, q_start, kv_start,
                         offset, sq_ref, skv_ref)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse)                              # (bq, bk)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)  # kill exp(NEG_INF - NEG_INF) = 1
        dv_sc[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                     # (bq, bk)
        dk_sc[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    @pl.when(qi == q_steps - 1)
    def _finish():
        dk_ref[0, 0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd(scale, causal, interpret, res, grads):
    q, k, v, seg_q, seg_kv, out, lse4 = res  # lse4: lane-broadcast residual
    do, dlse = grads
    do = do.astype(q.dtype)
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    g = hq // hkv
    bq, bk = _block_sizes(sq, skv, d)
    offset = skv - sq
    segmented = seg_q is not None
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # (b, hq, sq)
    # the lse cotangent folds into the ds formula exactly:
    #   ds = p*(dp - delta)*scale + p*dlse*scale = p*(dp - (delta-dlse))*scale
    delta = delta - dlse.astype(jnp.float32)
    # lane-broadcast for TPU block tiling (last dim = _LANES); lse stays in
    # its broadcast layout from the forward — no slice/re-broadcast round trip
    delta4 = jnp.broadcast_to(delta[..., None], (*delta.shape, _LANES))
    seg_args = list(_seg_broadcast(seg_q, seg_kv)) if segmented else []

    def seg_specs(ix_q, ix_kv):
        return ([pl.BlockSpec((1, bq, _LANES), ix_q),
                 pl.BlockSpec((1, 8, bk), ix_kv)] if segmented else [])

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, segmented=segmented,
        offset=offset, bq=bq, bk=bk, kv_steps=skv // bk)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, hq, sq // bq, skv // bk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h, qi, ki: (b_, h, qi, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h, qi, ki: (b_, h // g, ki, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h, qi, ki: (b_, h // g, ki, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h, qi, ki: (b_, h, qi, 0)),
            pl.BlockSpec((1, 1, bq, _LANES),
                         lambda b_, h, qi, ki: (b_, h, qi, 0)),
            pl.BlockSpec((1, 1, bq, _LANES),
                         lambda b_, h, qi, ki: (b_, h, qi, 0)),
        ] + seg_specs(lambda b_, h, qi, ki: (b_, qi, 0),
                      lambda b_, h, qi, ki: (b_, 0, ki)),
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda b_, h, qi, ki: (b_, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse4, delta4, *seg_args)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, segmented=segmented,
        offset=offset, bq=bq, bk=bk, q_steps=sq // bq)
    # per-q-head dk/dv; grouped heads are reduced after the kernel
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, hq, skv // bk, sq // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h, ki, qi: (b_, h, qi, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h, ki, qi: (b_, h // g, ki, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h, ki, qi: (b_, h // g, ki, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h, ki, qi: (b_, h, qi, 0)),
            pl.BlockSpec((1, 1, bq, _LANES),
                         lambda b_, h, ki, qi: (b_, h, qi, 0)),
            pl.BlockSpec((1, 1, bq, _LANES),
                         lambda b_, h, ki, qi: (b_, h, qi, 0)),
        ] + seg_specs(lambda b_, h, ki, qi: (b_, qi, 0),
                      lambda b_, h, ki, qi: (b_, 0, ki)),
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b_, h, ki, qi: (b_, h, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h, ki, qi: (b_, h, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, skv, d), k.dtype),
            jax.ShapeDtypeStruct((b, hq, skv, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse4, delta4, *seg_args)
    if g > 1:
        dk = dk.reshape(b, hkv, g, skv, d).sum(axis=2).astype(k.dtype)
        dv = dv.reshape(b, hkv, g, skv, d).sum(axis=2).astype(v.dtype)
    if segmented:
        import numpy as _np
        f0 = jax.dtypes.float0
        return (dq, dk, dv, _np.zeros(seg_q.shape, f0),
                _np.zeros(seg_kv.shape, f0))
    return dq, dk, dv, None, None


# ---------------------------------------------------------------------------
# public entry with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash(q, k, v, seg_q, seg_kv, scale, causal, interpret):
    out, lse4 = _fwd(q, k, v, seg_q, seg_kv, scale, causal, interpret)
    return out, lse4[..., 0]


def _flash_fwd(q, k, v, seg_q, seg_kv, scale, causal, interpret):
    out, lse4 = _fwd(q, k, v, seg_q, seg_kv, scale, causal, interpret)
    return (out, lse4[..., 0]), (q, k, v, seg_q, seg_kv, out, lse4)


_flash.defvjp(_flash_fwd, _bwd)


def flash_attention_pallas(q, k, v, causal: bool = False,
                           scale: Optional[float] = None,
                           interpret: bool = False, segment_ids=None,
                           kv_segment_ids=None):
    """(B, S, H, D) flash attention → (out (B,S,H,D), lse (B,H,S)).

    ``segment_ids``: optional (B, Sq) int packed-document ids (varlen
    form); cross-document pairs are masked INSIDE the kernel — packed
    pretraining batches keep the flash memory profile instead of an O(S²)
    masked fallback.  ``kv_segment_ids``: optional (B, Skv) ids for the
    keys when they are NOT the queries' own positions — the ring-attention
    case, where each hop attends a visiting KV block from another rank's
    sequence slice; defaults to ``segment_ids`` (self-attention)."""
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    if scale is None:
        scale = d ** -0.5
    bq, bk = _block_sizes(sq, skv, d)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    _validate(qt, kt, vt, sq, skv, bq, bk)
    if segment_ids is not None and kv_segment_ids is None and sq != skv:
        raise NotImplementedError(
            "segment_ids without kv_segment_ids assume self-attention "
            "(sq == skv); pass kv_segment_ids for cross-slice attention")
    seg_q = (None if segment_ids is None
             else jnp.asarray(segment_ids, jnp.int32))
    seg_kv = (seg_q if kv_segment_ids is None
              else jnp.asarray(kv_segment_ids, jnp.int32))
    if seg_q is None and seg_kv is not None:
        raise ValueError("kv_segment_ids requires segment_ids")
    out, lse = _flash(qt, kt, vt, seg_q, seg_kv, float(scale), bool(causal),
                      bool(interpret))
    return jnp.swapaxes(out, 1, 2), lse
