"""Weight-only-int8 matmul Pallas TPU kernel — the serving bandwidth op.

TPU-native equivalent of the reference's fast-dequant weight-only GEMM
(upstream layout: paddle/phi/kernels/fusion/cutlass/ — the
weight_only_linear int8 path behind paddle.nn.quant).

Why a kernel when XLA can express ``x @ (w8.astype(bf16) * scale)``:
measured on the decode bench (BENCH_DECODE.json ``int8_decode``), XLA
hoists that dequantised weight out of the decode scan as a loop-invariant
bf16 buffer — per-step HBM traffic stays bf16 and int8 buys nothing.
Inside this kernel there is no hoistable intermediate: the int8 tile is
converted to bf16 *in VMEM* right before the MXU contraction, so HBM only
ever streams int8 bytes — half the weight traffic of a bf16 matmul, which
is the whole bill for batch≤8 decode.

Layout: ``out[B, N] = (x[B, K] @ w8[K, N]) * scale[N]`` — the
per-out-channel scale commutes with the contraction, so it is applied
ONCE to the f32 accumulator at the final K step (cheaper than scaling
tiles, and exactly equivalent for per-column scales).

Grid: (N blocks, K blocks), K minor — each out block accumulates over
the K walk in an f32 VMEM scratch that persists across the inner
dimension; Pallas double-buffers the streaming w8 tiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import limits as _limits


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, k_steps: int):
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # int8 → bf16 happens HERE, in VMEM: HBM streamed only int8 bytes
    wb = w_ref[...].astype(jnp.bfloat16)
    acc_ref[...] += jax.lax.dot_general(
        x_ref[...], wb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == k_steps - 1)
    def _done():
        o_ref[...] = (acc_ref[...]
                      * s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _pick(dim: int, cap: int) -> int:
    b = 128
    while b * 2 <= cap and dim % (b * 2) == 0:
        b *= 2
    return b


def int8_matmul_pallas(x, w8, scale, block_k: int = 0, block_n: int = 0,
                       interpret: bool = False):
    """``(x @ w8) * scale`` with in-kernel dequant.

    x: (..., K) floating; w8: (K, N) int8; scale: (N,) — from
    nn/quant.py's ``weight_quantize``.  Returns (..., N) in x.dtype.
    Raises NotImplementedError for unsupported shapes (callers fall back
    to the XLA composition).
    """
    k, n = w8.shape
    if w8.dtype != jnp.int8:
        raise NotImplementedError(f"weight dtype {w8.dtype} != int8")
    if x.shape[-1] != k or scale.shape != (n,):
        raise ValueError(f"shape mismatch: x {x.shape}, w8 {w8.shape}, "
                         f"scale {scale.shape}")
    if k % 128 or n % 128:
        raise NotImplementedError(
            f"int8 matmul kernel needs K, N % 128 == 0, got {k}, {n}")
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    if rows == 0:
        raise NotImplementedError("empty batch")
    x2 = x.reshape(rows, k)
    # MXU sublane: pad the (tiny, serving-sized) row count up to 8
    rows_p = max(8, -(-rows // 8) * 8)
    if rows_p != rows:
        x2 = jnp.pad(x2, ((0, rows_p - rows), (0, 0)))
    if rows_p > _limits.MAX_GEMM_ROWS:
        raise NotImplementedError(
            f"decode-shaped kernel: row count {rows} > "
            f"{_limits.MAX_GEMM_ROWS} (training-size GEMMs belong to "
            f"XLA's own int8 handling)")
    bk = block_k or _pick(k, 2048)
    bn = block_n or _pick(n, 512)
    k_steps = k // bk

    out = pl.pallas_call(
        functools.partial(_kernel, k_steps=k_steps),
        grid=(n // bn, k_steps),
        in_specs=[
            pl.BlockSpec((rows_p, bk), lambda ni, ki: (0, ki)),
            pl.BlockSpec((bk, bn), lambda ni, ki: (ki, ni)),
            pl.BlockSpec((1, bn), lambda ni, ki: (0, ni)),
        ],
        out_specs=pl.BlockSpec((rows_p, bn), lambda ni, ki: (0, ni)),
        out_shape=jax.ShapeDtypeStruct((rows_p, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((rows_p, bn), jnp.float32)],
        interpret=interpret,
    )(x2, w8, scale.reshape(1, n))
    return out[:rows].reshape(x.shape[:-1] + (n,))
