"""Shared Pallas kernel limits — ONE source of truth for the shape
bounds the kernels enforce, the dispatch rules gate on, and the static
kernel pre-flight (paddle_tpu/static_analysis/kernel_rules.py) checks.

Before ISSUE 14 these literals lived three times: as
``NotImplementedError`` gates inside each kernel, as hard-coded numbers
in ``ops.attention.decode_attention_path``'s dispatch decision, and as
folklore in docstrings.  A drift between any two of them is a silent
routing bug — dispatch sends a shape the kernel rejects (runtime
NotImplementedError on the serving hot path) or refuses a shape the
kernel handles (perf left on the floor).  Deriving all three sites from
this module makes the drift impossible, and the registry's
dispatch-agreement lint (``kernel_rules.dispatch_agreement_findings``)
sweeps a shape lattice to prove dispatch and kernel still agree.

The values themselves are TPU architecture facts, not tunables:

  * ``LANES`` — the VPU/MXU lane width; last-dim tiles and KV chunk
    lengths must be 128-aligned for a chunk to be one clean DMA;
  * ``SUBLANES`` — the second-minor register-tile height per dtype
    ((8, 128) f32, (16, 128) bf16, (32, 128) int8): blocks whose
    second-minor dim is not a multiple waste sublane occupancy unless
    the kernel pads explicitly;
  * ``MAX_Q_ROWS`` — the per-tile s·G row cap of the flash-decode
    kernel's q tiling (one MXU-rows-worth of grouped queries);
  * ``MAX_Q_LEN`` — beyond this a q is whole-prefill-shaped and belongs
    to the flash kernel, not the cached-decode path;
  * ``MAX_HEAD_DIM`` — two lane tiles; larger heads blow the per-head
    VMEM scratch budget of the decode kernels;
  * ``MAX_GEMM_ROWS`` — the int8 weight-only matmul is decode-shaped
    (batch·seq rows stay tiny); training-size GEMMs belong to XLA.
"""

from __future__ import annotations

LANES = 128          # VPU lane width / minimal last-dim tile
MAX_Q_ROWS = 64      # flash-decode per-tile s·G row cap
MAX_Q_LEN = 2048     # q longer than any prefill chunk => flash kernel
MAX_HEAD_DIM = 256   # decode-attention head_dim ceiling (2 lane tiles)
MAX_GEMM_ROWS = 256  # int8_matmul row ceiling (decode-shaped GEMMs)

# second-minor register-tile height by dtype name (jnp dtype .name)
SUBLANES = {
    "float32": 8,
    "int32": 8,
    "bfloat16": 16,
    "float16": 16,
    "int8": 32,
}


def sublanes(dtype_name: str) -> int:
    """Sublane tile height for a dtype name; unknown dtypes get the f32
    tile (the most permissive check)."""
    return SUBLANES.get(str(dtype_name), 8)
