"""Row-resident RMSNorm Pallas TPU kernel.

TPU-native equivalent of the reference's fused rms_norm CUDA kernel
(upstream layout: paddle/phi/kernels/fusion/gpu/fused_rms_norm*).

Why a kernel at all when XLA fuses elementwise chains: a *standalone*
rms_norm lowers in XLA to a reduce pass plus a broadcast-multiply pass —
two HBM reads of ``x`` and one write.  This kernel keeps a block of rows
resident in VMEM and does the reduction + scale in one visit: one read,
one write, ~1.5x less HBM traffic.  That only matters when the op is
HBM-bound and NOT already fused into a neighbouring matmul — i.e. long
rows at layer boundaries — which is why the dispatcher
(paddle_tpu/ops/norms.py) routes only row sizes ≥ its threshold here and
leaves everything else to XLA.

Forward only by design: under ``jax.grad`` the cotangent path falls back
to the XLA reference implementation via ``jax.custom_vjp`` so training
numerics are owned by one code path; the kernel serves inference/serving
and the forward half of training steps.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, w_ref, o_ref, *, epsilon: float):
    xf = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(ms + epsilon)
    if w_ref is not None:
        y = y * w_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _kernel_nw(x_ref, o_ref, *, epsilon: float):
    _kernel(x_ref, None, o_ref, epsilon=epsilon)


def _pick_block_rows(rows: int, d: int) -> int:
    """Largest power-of-two row block that divides ``rows`` and keeps the
    block under ~2 MB fp32 — with Pallas double-buffering the in/out blocks
    plus the fp32 upcast temp, that stays well inside the 16 MB VMEM."""
    budget = max(8, (2 * 1024 * 1024) // (4 * d))
    br = 1
    while br * 2 <= min(rows, 512, budget) and rows % (br * 2) == 0:
        br *= 2
    return br


def rms_norm_pallas(x, weight=None, epsilon: float = 1e-6,
                    interpret: bool = False):
    """x: (..., D) → same shape/dtype; weight: (D,) or None.

    Raises NotImplementedError for shapes the kernel does not handle
    (caller falls back to the XLA path).
    """
    d = x.shape[-1]
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    if rows == 0 or d % 128:
        raise NotImplementedError(
            f"rms_norm kernel needs last dim % 128 == 0, got {d}")
    if rows % 8:
        raise NotImplementedError(
            f"rms_norm kernel needs row count % 8 == 0, got {rows}")
    x2 = x.reshape(rows, d)
    br = _pick_block_rows(rows, d)

    in_specs = [pl.BlockSpec((br, d), lambda i: (i, 0))]
    args = [x2]
    if weight is not None:
        if weight.shape != (d,):
            raise NotImplementedError(
                f"weight shape {weight.shape} != ({d},)")
        in_specs.append(pl.BlockSpec((1, d), lambda i: (0, 0)))
        args.append(weight.reshape(1, d))
        kern = functools.partial(_kernel, epsilon=epsilon)
    else:
        kern = functools.partial(_kernel_nw, epsilon=epsilon)

    out = pl.pallas_call(
        kern,
        grid=(rows // br,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=interpret,
    )(*args)
    return out.reshape(x.shape)
