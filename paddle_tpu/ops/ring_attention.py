"""Ring attention + Ulysses: sequence/context-parallel attention.

TPU-native, in-tree equivalent of the reference's long-context stack
(upstream: the ``sep`` axis plumbing in fleet's topology.py; the ring
flash-attention itself lives out-of-tree in PaddleNLP's
ring_flash_attention.py — SURVEY.md §5 "long-context").  Here both schemes
are first-class framework ops (the survey's stated place to exceed the
reference in-tree):

  * **ring attention**: Q stays put; KV blocks rotate around the ``sep``
    mesh axis via ``lax.ppermute`` (collective-permute rides the ICI ring).
    Each hop runs the Pallas flash kernel on the resident block and merges
    online in log-space using the kernel's LSE output — the
    blockwise/ring-attention recurrence.  Causality is handled per hop:
    diagonal block = causal kernel, source-after-destination = skipped
    (masked to -inf), source-before = full attention.
  * **Ulysses**: ``lax.all_to_all`` re-shards seq↔heads so each rank runs
    full-sequence attention on a head slice, then transposes back.  Cheaper
    than ring for moderate sequence lengths; needs heads % sep == 0.

Both are *per-shard* functions to be used inside ``shard_map`` (the model
wraps them via paddle_tpu.distributed.context_parallel); autodiff flows
through ppermute/all_to_all, so no hand-written backward is needed.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .attention import NEG_INF, flash_attention

__all__ = ["merge_attention", "ring_attention_shard",
           "ulysses_attention_shard"]


def merge_attention(out_a, lse_a, out_b, lse_b):
    """Combine two attention partial results over disjoint KV sets.

    out: (B, S, H, D); lse: (B, H, S) — the log-sum-exp the flash kernel
    returns.  Stable log-space merge; fully-masked parts (lse = NEG_INF)
    contribute nothing.
    """
    m = jnp.maximum(lse_a, lse_b)
    m = jnp.where(m <= NEG_INF / 2, 0.0, m)  # both dead: avoid -inf - -inf
    wa = jnp.exp(lse_a - m)                   # (B, H, S)
    wb = jnp.exp(lse_b - m)
    denom = jnp.maximum(wa + wb, 1e-37)
    lse = m + jnp.log(denom)
    # weights move to (B, S, H, 1) for the out layout
    wa_o = jnp.swapaxes(wa / denom, 1, 2)[..., None].astype(out_a.dtype)
    wb_o = jnp.swapaxes(wb / denom, 1, 2)[..., None].astype(out_b.dtype)
    out = out_a * wa_o + out_b * wb_o
    lse = jnp.where((lse_a <= NEG_INF / 2) & (lse_b <= NEG_INF / 2),
                    NEG_INF, lse)
    return out, lse


def _as_varying(x, like, axis_name):
    """Mark a constant as varying over every mesh axis that ``like`` varies
    over (plus ``axis_name``) — lax.switch branches and scan carries must
    agree on varying-axes types.  On jax versions without varying-manual-
    axes typing (no ``jax.typeof``/``lax.pcast``) this is a no-op: those
    versions don't distinguish the types either."""
    if not hasattr(jax, "typeof") or not hasattr(lax, "pcast"):
        return x
    want = frozenset(getattr(jax.typeof(like), "vma", frozenset())) \
        | {axis_name}
    have = frozenset(getattr(jax.typeof(x), "vma", frozenset()))
    missing = tuple(want - have)
    return lax.pcast(x, missing, to="varying") if missing else x


def _block(q, k, v, mode, scale, axis_name, seg_q=None, seg_kv=None):
    """One Q-block × KV-block attention partial.  mode: 0=skip, 1=full,
    2=causal-diagonal.  Returns (out, lse).

    ``seg_q``/``seg_kv``: packed-document ids of the local queries and of
    the *visiting* KV block (they differ on off-diagonal hops) — the
    varlen × ring composition; cross-document pairs mask inside the flash
    kernel, and a hop whose whole KV block is cross-document yields dead
    rows (lse = -inf) that the merge ignores."""
    def skip(_):
        b, s, h, d = q.shape
        return (_as_varying(jnp.zeros_like(q), q, axis_name),
                _as_varying(jnp.full((b, h, s), NEG_INF, jnp.float32), q,
                            axis_name))

    def full(_):
        return flash_attention(q, k, v, causal=False, scale=scale,
                               return_lse=True, segment_ids=seg_q,
                               kv_segment_ids=seg_kv)

    def diag(_):
        return flash_attention(q, k, v, causal=True, scale=scale,
                               return_lse=True, segment_ids=seg_q,
                               kv_segment_ids=seg_kv)

    return lax.switch(mode, (skip, full, diag), None)


def ring_attention_shard(q, k, v, axis_name: str, causal: bool = True,
                         scale: Optional[float] = None, segment_ids=None):
    """Per-shard ring attention (run inside shard_map over ``axis_name``).

    q/k/v: this rank's sequence slice, (B, S_local, H, D) / (B, S_local,
    H_kv, D).  Global sequence order = rank order along the axis.
    ``segment_ids``: this rank's slice of the packed-document ids,
    (B, S_local) — they rotate around the ring WITH the KV blocks, so each
    hop masks local queries against the visiting block's documents (the
    varlen × context-parallel composition; LSE merge is unchanged).
    Returns (out, lse) for the local slice.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    p = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % p) for i in range(p)]  # KV moves to the next rank
    seg_q = (None if segment_ids is None
             else jnp.asarray(segment_ids, jnp.int32))

    def step(carry, t):
        out, lse, kt, vt, st = carry
        src = (my - t) % p  # whose KV block we hold at hop t
        if causal:
            mode = jnp.where(src == my, 2, jnp.where(src < my, 1, 0))
        else:
            mode = jnp.asarray(1)
        o_t, l_t = _block(q, kt, vt, mode, scale, axis_name,
                          seg_q=seg_q, seg_kv=st)
        out, lse = merge_attention(out, lse, o_t, l_t)
        # rotate every hop (uniform across ranks — collectives must not sit
        # under data-dependent control flow); the p-th rotation restores KV
        kt = lax.ppermute(kt, axis_name, perm)
        vt = lax.ppermute(vt, axis_name, perm)
        if st is not None:
            st = lax.ppermute(st, axis_name, perm)
        return (out, lse, kt, vt, st), None

    b, s, h, d = q.shape
    out0 = _as_varying(jnp.zeros_like(q), q, axis_name)
    lse0 = _as_varying(jnp.full((b, h, s), NEG_INF, jnp.float32), q,
                       axis_name)
    (out, lse, _, _, _), _ = lax.scan(step, (out0, lse0, k, v, seg_q),
                                      jnp.arange(p))
    return out, lse


def ulysses_attention_shard(q, k, v, axis_name: str, causal: bool = True,
                            scale: Optional[float] = None, segment_ids=None):
    """Per-shard Ulysses attention: all_to_all seq↔heads, full-seq flash,
    all_to_all back.  Heads (q and kv) must divide the axis size.

    ``segment_ids``: this rank's (B, S_local) packed-document ids; since
    every rank sees the FULL sequence after the all_to_all (on a head
    slice), the ids are all-gathered along the axis — (B, S) int32 is
    cheap on the wire — and the flash kernel masks as in the single-shard
    varlen case."""
    p = lax.axis_size(axis_name)

    def to_full_seq(x):  # (B, S/p, H, D) -> (B, S, H/p, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_local_seq(x):  # (B, S, H/p, D) -> (B, S/p, H, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    if q.shape[2] % p or k.shape[2] % p:
        raise ValueError(f"Ulysses needs heads divisible by the cp degree "
                         f"(q heads {q.shape[2]}, kv heads {k.shape[2]}, "
                         f"degree {p})")
    qf, kf, vf = to_full_seq(q), to_full_seq(k), to_full_seq(v)
    seg_full = (None if segment_ids is None
                else lax.all_gather(jnp.asarray(segment_ids, jnp.int32),
                                    axis_name, axis=1, tiled=True))
    out, lse = flash_attention(qf, kf, vf, causal=causal, scale=scale,
                               return_lse=True, segment_ids=seg_full)
    # lse is (B, H/p, S_global): transpose back to the per-shard contract
    # (B, H_local, S_local) that ring_attention_shard honours
    lse = lax.all_to_all(lse, axis_name, split_axis=2, concat_axis=1,
                         tiled=True)
    return to_local_seq(out), lse
