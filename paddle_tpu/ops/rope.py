"""Rotary position embedding (RoPE).

Equivalent of the reference's fused_rotary_position_embedding CUDA kernel
(upstream layout: paddle/phi/kernels/fusion/gpu/fused_rope_*,
paddle.incubate.nn.functional.fused_rotary_position_embedding).

Convention: NeoX/Llama half-rotation — split head_dim in halves rather than
interleaving pairs; inputs are (batch, seq, heads, head_dim).  cos/sin caches
are fp32; rotation is computed in fp32 and cast back (bf16-safe).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp


def build_rope_cache(seq_len: int, head_dim: int, base: float = 10000.0,
                     scaling_factor: float = 1.0,
                     dtype=jnp.float32) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin caches of shape (seq_len, head_dim//2)."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2,
                                          dtype=jnp.float32) / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32) / scaling_factor
    freqs = jnp.outer(t, inv_freq)  # (S, D/2)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(x, cos, sin, position_ids: Optional[jnp.ndarray] = None):
    """Rotate (B, S, H, D) by cos/sin caches (S_cache, D/2)."""
    dt = x.dtype
    if position_ids is not None:
        cos = jnp.take(cos, position_ids, axis=0)  # (B, S, D/2)
        sin = jnp.take(sin, position_ids, axis=0)
        cos = cos[:, :, None, :]
        sin = sin[:, :, None, :]
    else:
        s = x.shape[1]
        cos = cos[None, :s, None, :]
        sin = sin[None, :s, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    y1 = x1 * cos - x2 * sin
    y2 = x2 * cos + x1 * sin
    return jnp.concatenate([y1, y2], axis=-1).astype(dt)


def fused_rope(q, k, cos, sin, position_ids=None):
    """Apply RoPE to q and k (the reference's fused_rope signature shape)."""
    return (apply_rope(q, cos, sin, position_ids),
            apply_rope(k, cos, sin, position_ids))
