"""RWKV wkv op — the linear-attention recurrence (BASELINE.json config #5).

Equivalent of the reference's wkv CUDA kernel (RWKV-4 family; vendored on
the PaddleNLP side, with the cuda kernel shipped as a custom op).  The
recurrence per channel c:

    wkv_t = (Σ_{i<t} e^{-(t-1-i)w + k_i} v_i + e^{u + k_t} v_t)
          / (Σ_{i<t} e^{-(t-1-i)w + k_i}     + e^{u + k_t})

computed with the running-max-exponent stabilisation of the official
kernel: state (p, q, o) where p/q are the exp-weighted numerator/
denominator relative to the running max o — no overflow for any k.

A ``lax.scan`` carries the (B, C)-shaped state over L; each step is pure
VPU elementwise work, fused by XLA into a few ops — the op is
bandwidth-light (state is tiny), so a sequential scan is the right TPU
shape; there is no matmul to win back on the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["wkv", "wkv_with_state", "wkv_init_state", "wkv_reference"]


def wkv_with_state(w, u, k, v, state):
    """:func:`wkv` with an explicit carried recurrence state — the O(1)
    incremental-decode form (the reference kernel's ``aa/bb/pp`` state).

    ``state``: (p, q, o) each (B, C) fp32 — exp-weighted numerator,
    denominator, and their shared running max exponent.
    Returns (out (B, L, C) fp32, new_state).
    """
    w = -jnp.asarray(w, jnp.float32)       # per-step log-decay (<= 0)
    u = jnp.asarray(u, jnp.float32)
    k = jnp.asarray(k, jnp.float32)
    v = jnp.asarray(v, jnp.float32)

    def step(st, kv_t):
        p, q, o = st                        # (B, C) each
        k_t, v_t = kv_t
        # output at t: include the bonus term e^{u + k_t} v_t
        no = jnp.maximum(o, u + k_t)
        a = jnp.exp(o - no)
        b = jnp.exp(u + k_t - no)
        out = (a * p + b * v_t) / (a * q + b)
        # state update: decay the history by e^{w}, absorb token t
        no2 = jnp.maximum(o + w, k_t)
        a2 = jnp.exp(o + w - no2)
        b2 = jnp.exp(k_t - no2)
        return (a2 * p + b2 * v_t, a2 * q + b2, no2), out

    final, out = lax.scan(step, state, (jnp.moveaxis(k, 1, 0),
                                        jnp.moveaxis(v, 1, 0)))
    return jnp.moveaxis(out, 0, 1), final


def wkv_init_state(batch: int, channels: int):
    """The empty-history state (p = q = 0, running max at -inf)."""
    return (jnp.zeros((batch, channels), jnp.float32),
            jnp.zeros((batch, channels), jnp.float32),
            jnp.full((batch, channels), -1e38, jnp.float32))


def wkv(w, u, k, v):
    """RWKV linear-attention mix.

    Args:
      w: (C,) channel decay rates, >= 0 (applied as e^{-w} per step).
      u: (C,) first-token bonus.
      k, v: (B, L, C) keys / values.
    Returns: (B, L, C) mixed values, fp32.
    """
    B, _, C = k.shape
    return wkv_with_state(w, u, k, v, wkv_init_state(B, C))[0]


def wkv_reference(w, u, k, v):
    """NumPy float64 oracle — the direct double sum, no stabilisation."""
    w = np.asarray(w, np.float64)
    u = np.asarray(u, np.float64)
    k = np.asarray(k, np.float64)
    v = np.asarray(v, np.float64)
    B, L, C = k.shape
    out = np.zeros((B, L, C))
    for b in range(B):
        for t in range(L):
            num = np.exp(u + k[b, t]) * v[b, t]
            den = np.exp(u + k[b, t])
            for i in range(t):
                wgt = np.exp(-(t - 1 - i) * w + k[b, i])
                num += wgt * v[b, i]
                den += wgt
            out[b, t] = num / den
    return out
