"""Selective state-space scan (Mamba-2 SSD), chunked for the MXU.

Equivalent of the reference's selective-scan CUDA kernels (upstream:
paddle/phi/kernels/fusion/gpu/ selective_scan / mamba-style ops vendored by
the PaddleNLP side; BASELINE.md lists Mamba-2 as a benchmark workload).

The recurrence (per head, scalar decay — the Mamba-2 "SSD" form):

    h_t = a_t * h_{t-1} + b_t ⊗ x_t        h: (P, N) state
    y_t = h_t · c_t                        y: (P,)

A naive scan is bandwidth-bound and serial in L.  The **chunked** algorithm
(the SSD paper's block decomposition) rewrites each length-Q chunk as three
matmul-shaped pieces — intra-chunk "attention with decay mask", chunk-state
accumulation, and state-to-output — plus a tiny ``lax.scan`` carrying the
(H, P, N) state across chunks.  Everything hot is an einsum on the MXU;
XLA fuses the decay-mask elementwise work into them, which is why this
needs no hand-written Pallas kernel to run at speed.

Shapes (grouped B/C like Mamba-2 / GQA):
    x: (B, L, H, P)   a: (B, L, H) in (0, 1]   b, c: (B, L, G, N), H % G == 0
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ssd_scan", "ssd_scan_reference"]


def ssd_scan_reference(x, a, b, c, h0=None):
    """Sequential oracle (lax.scan over every step).  fp32 state."""
    bsz, L, h, p = x.shape
    g, n = b.shape[2], b.shape[3]
    rep = h // g
    bf = jnp.repeat(b, rep, axis=2).astype(jnp.float32)  # (B, L, H, N)
    cf = jnp.repeat(c, rep, axis=2).astype(jnp.float32)
    xf = x.astype(jnp.float32)
    af = a.astype(jnp.float32)
    init = (jnp.zeros((bsz, h, p, n), jnp.float32) if h0 is None
            else h0.astype(jnp.float32))

    def step(hprev, t):
        xt, at, bt, ct = t
        hnew = at[..., None, None] * hprev \
            + xt[..., :, None] * bt[..., None, :]
        yt = jnp.einsum("bhpn,bhn->bhp", hnew, ct)
        return hnew, yt

    xs = (jnp.moveaxis(xf, 1, 0), jnp.moveaxis(af, 1, 0),
          jnp.moveaxis(bf, 1, 0), jnp.moveaxis(cf, 1, 0))
    hlast, ys = lax.scan(step, init, xs)
    return jnp.moveaxis(ys, 0, 1).astype(x.dtype), hlast


def ssd_scan(x, a, b, c, h0=None, chunk: int = 64
             ) -> Tuple[jax.Array, jax.Array]:
    """Chunked SSD.  Returns (y (B,L,H,P), final state (B,H,P,N))."""
    bsz, L, h, p = x.shape
    g, n = b.shape[2], b.shape[3]
    rep = h // g
    if L < chunk:
        chunk = L
    if L % chunk:
        # pad the tail up to a chunk multiple with identity steps
        # (a=1 keeps the state, x=b=0 contribute nothing, c=0 reads
        # nothing); padded outputs are sliced off at the end — so any L
        # runs at full chunk width instead of degrading the chunk size
        pad = chunk - L % chunk
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
        a = jnp.pad(a, ((0, 0), (0, pad), (0, 0)), constant_values=1.0)
        b = jnp.pad(b, ((0, 0), (0, pad), (0, 0), (0, 0)))
        c = jnp.pad(c, ((0, 0), (0, pad), (0, 0), (0, 0)))
    Lp = x.shape[1]
    nc = Lp // chunk

    xf = x.astype(jnp.float32).reshape(bsz, nc, chunk, h, p)
    af = a.astype(jnp.float32).reshape(bsz, nc, chunk, h)
    bf = jnp.repeat(b, rep, axis=2).astype(jnp.float32) \
        .reshape(bsz, nc, chunk, h, n)
    cf = jnp.repeat(c, rep, axis=2).astype(jnp.float32) \
        .reshape(bsz, nc, chunk, h, n)

    # cumulative log-decay within each chunk: la[..., t] = log prod a[..<=t]
    la = jnp.cumsum(jnp.log(jnp.maximum(af, 1e-37)), axis=2)  # (B,C,Q,H)

    # intra-chunk: y[i] += sum_{j<=i} (c_i·b_j) exp(la_i - la_j) x_j — the
    # SSD "L-mask"; b_j⊗x_j enters h_j undecayed, so the factor is
    # prod_{k=j+1..i} a_k = exp(la_i - la_j)
    scores = jnp.einsum("bkihn,bkjhn->bkhij", cf, bf)  # (B,C,H,Q,Q)
    li = la[..., :, None, :]                            # (B,C,Q,1,H)
    lj = la[..., None, :, :]                            # (B,C,1,Q,H)
    decay = jnp.exp(jnp.transpose(li - lj, (0, 1, 4, 2, 3)))  # (B,C,H,Q,Q)
    mask = jnp.tril(jnp.ones((chunk, chunk), bool))
    w = jnp.where(mask, scores * decay, 0.0)
    y_intra = jnp.einsum("bkhij,bkjhp->bkihp", w, xf)

    # chunk summaries: state contribution of each chunk at its last step
    # S_k = sum_j exp(la_last - la_j) * b_j ⊗ x_j
    tail = jnp.exp(la[:, :, -1:, :] - la)               # (B,C,Q,H)
    s_k = jnp.einsum("bkjh,bkjhp,bkjhn->bkhpn", tail, xf, bf)
    a_k = jnp.exp(la[:, :, -1, :])                      # (B,C,H) chunk decay

    # carry the state across chunks
    init = (jnp.zeros((bsz, h, p, n), jnp.float32) if h0 is None
            else h0.astype(jnp.float32))

    def carry(hprev, t):
        s, ak = t
        return ak[..., None, None] * hprev + s, hprev

    (hlast, hprevs) = lax.scan(
        carry, init, (jnp.moveaxis(s_k, 1, 0), jnp.moveaxis(a_k, 1, 0)))
    hprevs = jnp.moveaxis(hprevs, 0, 1)                 # (B,C,H,P,N)

    # inter-chunk: y[i] += c_i · (decay-to-i * h_prev_chunk)
    y_inter = jnp.einsum("bkihn,bkih,bkhpn->bkihp",
                         cf, jnp.exp(la), hprevs)
    y = (y_intra + y_inter).reshape(bsz, Lp, h, p)[:, :L].astype(x.dtype)
    return y, hlast
