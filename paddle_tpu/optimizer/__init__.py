"""Optimizers.

Parity with the reference's ``paddle.optimizer`` (upstream layout:
python/paddle/optimizer/ — optimizer.py, adamw.py, adam.py, momentum.py,
sgd.py) including multi-precision (fp32 master weights for bf16 params,
the reference's ``multi_precision`` flag) and grad clipping.

Design: a **functional core** — ``state = opt.init(params)``;
``new_params, new_state = opt.update(grads, state, params)`` — all jnp ops, so
the whole update lives inside the jit-compiled train step (the TPU replacement
for the reference's fused adamw CUDA kernel: XLA fuses the elementwise update
chain into a single kernel over each parameter).  An **imperative mirror**
(``opt.step(grads)`` bound to a Layer) preserves the reference's eager API.

Weight decay follows AdamW (decoupled); ``apply_decay_param_fun`` mirrors the
reference's knob for exempting bias/norm params by name.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from . import lr as lr_mod
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad",
           "Adamax", "RMSProp", "Lamb",
           "ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue", "lr"]

lr = lr_mod


def _lr_value(learning_rate, step):
    if isinstance(learning_rate, lr_mod.LRScheduler):
        return learning_rate.value(step)
    return jnp.asarray(learning_rate, jnp.float32)


class Optimizer:
    """Base optimizer.

    ``parameters`` may be a :class:`Layer` (imperative use) or omitted
    (functional use with explicit param pytrees).
    """

    def __init__(self, learning_rate=0.001, parameters: Optional[Layer] = None,
                 weight_decay: float = 0.0,
                 apply_decay_param_fun: Optional[Callable[[str], bool]] = None,
                 grad_clip=None, multi_precision: bool = True):
        self._lr = learning_rate
        self._model = parameters if isinstance(parameters, Layer) else None
        self.weight_decay = float(weight_decay)
        self.apply_decay_param_fun = apply_decay_param_fun
        self.grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._state = None

    # -- functional core ----------------------------------------------------

    def init(self, params: Dict[str, jax.Array]) -> Dict[str, Any]:
        state = {"step": jnp.zeros((), jnp.int32)}
        if self._multi_precision:
            # key always present when multi_precision, even if empty, so the
            # state treedef is identical across init/update (scan/jit carry)
            state["master"] = {
                k: v.astype(jnp.float32) for k, v in params.items()
                if v.dtype in (jnp.bfloat16, jnp.float16)}
        for slot in self._slot_names():
            state[slot] = {k: jnp.zeros(v.shape, jnp.float32)
                           for k, v in params.items()}
        return state

    def _slot_names(self):
        return ()

    def _decay_mask(self, params):
        if self.weight_decay == 0.0:
            return {k: 0.0 for k in params}
        if self.apply_decay_param_fun is None:
            return {k: 1.0 for k in params}
        return {k: (1.0 if self.apply_decay_param_fun(k) else 0.0)
                for k in params}

    def update(self, grads: Dict[str, jax.Array], state: Dict[str, Any],
               params: Dict[str, jax.Array]):
        """Returns (new_params, new_state).  Pure jnp; jit-safe, and the
        returned state has the same treedef as the input (scan-carry safe)."""
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)
        step = state["step"] + 1
        lr_t = _lr_value(self._lr, state["step"])
        master = state.get("master", {})
        decay = self._decay_mask(params)
        slot_names = self._slot_names()
        new_params = {}
        new_slots = {s: {} for s in slot_names}
        new_master = {}
        for k, p in params.items():
            g = grads.get(k)
            slots = {s: state[s][k] for s in slot_names}
            if g is None:
                new_params[k] = p
                for s in slot_names:
                    new_slots[s][k] = slots[s]
                if k in master:
                    new_master[k] = master[k]
                continue
            p32 = master.get(k, p).astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            p32_new, slots_new = self._apply_one(k, p32, g32, lr_t, step,
                                                 decay[k], slots)
            new_params[k] = p32_new.astype(p.dtype)
            for s in slot_names:
                new_slots[s][k] = slots_new[s]
            if k in master:
                new_master[k] = p32_new
        out_state = {"step": step, **new_slots}
        if "master" in state:
            out_state["master"] = new_master
        return new_params, out_state

    def _apply_one(self, name, p32, g32, lr_t, step, decay_on, slots):
        """Return (new_p32, new_slots_for_this_param)."""
        raise NotImplementedError

    # -- imperative mirror (reference API) -----------------------------------

    def _require_model(self):
        if self._model is None:
            raise RuntimeError(
                "imperative API needs Optimizer(parameters=<Layer>)")
        return self._model

    def step(self, grads: Dict[str, jax.Array]):
        """Apply one update to the bound model, in place."""
        model = self._require_model()
        params = model.trainable_state()
        if self._state is None:
            self._state = self.init(params)
        new_params, self._state = self.update(grads, self._state, params)
        model.set_state_dict(new_params, strict=False)
        if isinstance(self._lr, lr_mod.LRScheduler):
            pass  # scheduler advances via the traced step counter

    def clear_grad(self):  # parity no-op: grads are values, not fields
        pass

    def get_lr(self):
        step = self._state["step"] if self._state is not None else 0
        return float(_lr_value(self._lr, jnp.asarray(step)))

    def state_dict(self):
        return self._state

    def set_state_dict(self, state):
        self._state = state


class SGD(Optimizer):
    def _apply_one(self, name, p32, g32, lr_t, step, decay_on, slots):
        g32 = g32 + self.weight_decay * decay_on * p32
        return p32 - lr_t * g32, {}


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum: float = 0.9,
                 use_nesterov: bool = False, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = float(momentum)
        self.use_nesterov = use_nesterov

    def _slot_names(self):
        return ("velocity",)

    def _apply_one(self, name, p32, g32, lr_t, step, decay_on, slots):
        g32 = g32 + self.weight_decay * decay_on * p32
        vel = self.momentum * slots["velocity"] + g32
        if self.use_nesterov:
            p_new = p32 - lr_t * (g32 + self.momentum * vel)
        else:
            p_new = p32 - lr_t * vel
        return p_new, {"velocity": vel}


class Adam(Optimizer):
    """Adam with L2-style decay folded into the gradient (reference Adam
    semantics); see :class:`AdamW` for decoupled decay."""

    _decoupled = False

    def __init__(self, learning_rate=0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _slot_names(self):
        return ("moment1", "moment2")

    def _apply_one(self, name, p32, g32, lr_t, step, decay_on, slots):
        if not self._decoupled:
            g32 = g32 + self.weight_decay * decay_on * p32
        m = self.beta1 * slots["moment1"] + (1 - self.beta1) * g32
        v = self.beta2 * slots["moment2"] + (1 - self.beta2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + self.epsilon)
        if self._decoupled:
            upd = upd + self.weight_decay * decay_on * p32
        return p32 - lr_t * upd, {"moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (parity: ``paddle.optimizer.AdamW``,
    python/paddle/optimizer/adamw.py upstream layout; the reference's fused
    adamw CUDA kernel is replaced by XLA fusion of this update chain)."""

    _decoupled = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, weight_decay: float = 0.01, **kw):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, weight_decay=weight_decay, **kw)


class Adagrad(Optimizer):
    """Parity: ``paddle.optimizer.Adagrad`` (adagrad.py, upstream layout)."""

    def __init__(self, learning_rate=0.001, epsilon: float = 1e-6,
                 initial_accumulator_value: float = 0.0, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon = epsilon
        self.initial_accumulator_value = initial_accumulator_value

    def _slot_names(self):
        return ("moment",)

    def init(self, params):
        state = super().init(params)
        if self.initial_accumulator_value:
            state["moment"] = {k: jnp.full(v.shape,
                                           self.initial_accumulator_value,
                                           jnp.float32)
                               for k, v in params.items()}
        return state

    def _apply_one(self, name, p32, g32, lr_t, step, decay_on, slots):
        g32 = g32 + self.weight_decay * decay_on * p32
        acc = slots["moment"] + jnp.square(g32)
        return (p32 - lr_t * g32 / (jnp.sqrt(acc) + self.epsilon),
                {"moment": acc})


class Adamax(Optimizer):
    """Adam with the infinity norm (parity: ``paddle.optimizer.Adamax``)."""

    def __init__(self, learning_rate=0.001, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _slot_names(self):
        return ("moment", "inf_norm")

    def _apply_one(self, name, p32, g32, lr_t, step, decay_on, slots):
        g32 = g32 + self.weight_decay * decay_on * p32
        m = self.beta1 * slots["moment"] + (1 - self.beta1) * g32
        u = jnp.maximum(self.beta2 * slots["inf_norm"], jnp.abs(g32))
        t = step.astype(jnp.float32)
        p_new = p32 - (lr_t / (1 - self.beta1 ** t)) * m / (u + self.epsilon)
        return p_new, {"moment": m, "inf_norm": u}


class RMSProp(Optimizer):
    """Parity: ``paddle.optimizer.RMSProp`` (rho/momentum/centered knobs)."""

    def __init__(self, learning_rate=0.001, rho: float = 0.95,
                 epsilon: float = 1e-6, momentum: float = 0.0,
                 centered: bool = False, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon
        self.momentum, self.centered = momentum, centered

    def _slot_names(self):
        names = ["mean_square", "velocity"]
        if self.centered:
            names.append("mean_grad")
        return tuple(names)

    def _apply_one(self, name, p32, g32, lr_t, step, decay_on, slots):
        g32 = g32 + self.weight_decay * decay_on * p32
        ms = self.rho * slots["mean_square"] + (1 - self.rho) * jnp.square(g32)
        out = {"mean_square": ms}
        denom = ms
        if self.centered:
            mg = self.rho * slots["mean_grad"] + (1 - self.rho) * g32
            out["mean_grad"] = mg
            denom = ms - jnp.square(mg)
        upd = g32 / jnp.sqrt(denom + self.epsilon)
        vel = self.momentum * slots["velocity"] + lr_t * upd
        out["velocity"] = vel
        return p32 - vel, out


class Lamb(Optimizer):
    """Layer-wise adaptive large-batch optimizer (parity:
    ``paddle.optimizer.Lamb``; the LAMB paper's trust-ratio scaling of the
    AdamW update, per parameter tensor)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-6,
                 exclude_from_weight_decay_fn: Optional[
                     Callable[[str], bool]] = None, **kw):
        super().__init__(learning_rate, weight_decay=lamb_weight_decay, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self._exclude = exclude_from_weight_decay_fn

    def _slot_names(self):
        return ("moment1", "moment2")

    def _apply_one(self, name, p32, g32, lr_t, step, decay_on, slots):
        m = self.beta1 * slots["moment1"] + (1 - self.beta1) * g32
        v = self.beta2 * slots["moment2"] + (1 - self.beta2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - self.beta1 ** t)
        vhat = v / (1 - self.beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self.epsilon)
        # both exemption knobs respected: the LAMB-specific
        # exclude_from_weight_decay_fn and the base apply_decay_param_fun
        # mask (decay_on) every other optimizer honours
        if not (self._exclude is not None and self._exclude(name)):
            r = r + self.weight_decay * decay_on * p32
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        ratio = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return p32 - lr_t * ratio * r, {"moment1": m, "moment2": v}
