"""Gradient clipping.

Parity with the reference's clip classes (upstream layout:
python/paddle/nn/clip.py — ``ClipGradByGlobalNorm``, ``ClipGradByNorm``,
``ClipGradByValue``).  Each is a callable ``grads_tree -> grads_tree``.

``ClipGradByGlobalNorm`` optionally reduces the squared norm over mesh axes
(``psum_axes``) — the TPU-native version of the reference's hybrid-parallel
global-norm allreduce across mp/pp/sharding groups
(fleet/utils/hybrid_parallel_util.py + dygraph_sharding_optimizer, upstream
layout): inside ``shard_map`` the partial sum rides ICI via ``lax.psum``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue",
           "global_norm"]


def global_norm(grads, psum_axes: Optional[Sequence[str]] = None):
    leaves = jax.tree_util.tree_leaves(grads)
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    if psum_axes:
        sq = lax.psum(sq, tuple(psum_axes))
    return jnp.sqrt(sq)


class ClipGradByGlobalNorm:
    def __init__(self, clip_norm: float,
                 psum_axes: Optional[Sequence[str]] = None):
        self.clip_norm = float(clip_norm)
        self.psum_axes = psum_axes

    def __call__(self, grads):
        norm = global_norm(grads, self.psum_axes)
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(norm, 1e-12))
        return jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)


class ClipGradByNorm:
    """Per-tensor L2 clip."""

    def __init__(self, clip_norm: float):
        self.clip_norm = float(clip_norm)

    def __call__(self, grads):
        def clip(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            s = jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
            return (g.astype(jnp.float32) * s).astype(g.dtype)
        return jax.tree_util.tree_map(clip, grads)


class ClipGradByValue:
    def __init__(self, max_value: float, min_value: Optional[float] = None):
        self.max = float(max_value)
        self.min = float(min_value) if min_value is not None else -self.max

    def __call__(self, grads):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)
