"""Learning-rate schedulers.

Parity with the reference's ``paddle.optimizer.lr`` (upstream layout:
python/paddle/optimizer/lr.py).  Schedulers are *pure functions of the step
counter* — ``value(step)`` is built from jnp ops so it can live inside a
jit-compiled training step (the step counter is a traced int32 array in the
optimizer state), unlike the reference's Python-side ``LRScheduler.step()``.
An imperative ``step()/get_lr()`` mirror is kept for API parity.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

__all__ = ["LRScheduler", "ConstantLR", "LinearWarmup", "CosineAnnealingDecay",
           "StepDecay", "MultiStepDecay", "ExponentialDecay", "NoamDecay",
           "PolynomialDecay"]


class LRScheduler:
    def __init__(self, learning_rate: float = 0.1, last_epoch: int = -1):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.step()  # initialise to epoch 0 like the reference

    # -- pure form (used inside jit) ---------------------------------------
    def value(self, step):
        """lr at integer/array ``step`` — override in subclasses."""
        raise NotImplementedError

    # -- imperative mirror --------------------------------------------------
    def step(self, epoch=None):
        self.last_epoch = epoch if epoch is not None else self.last_epoch + 1

    def get_lr(self):
        return float(self.value(jnp.asarray(self.last_epoch, jnp.float32)))

    def state_dict(self):
        return {"last_epoch": self.last_epoch}

    def set_state_dict(self, state):
        self.last_epoch = state["last_epoch"]


class ConstantLR(LRScheduler):
    def value(self, step):
        return jnp.full((), self.base_lr, jnp.float32)


class LinearWarmup(LRScheduler):
    """Linear warmup into an inner scheduler (or a constant)."""

    def __init__(self, learning_rate, warmup_steps: int, start_lr: float = 0.0,
                 end_lr: float = None, last_epoch: int = -1):
        self.inner = learning_rate if isinstance(learning_rate, LRScheduler) \
            else None
        base = learning_rate.base_lr if self.inner else float(learning_rate)
        self.warmup_steps = int(warmup_steps)
        self.start_lr = float(start_lr)
        self.end_lr = float(end_lr) if end_lr is not None else base
        super().__init__(base, last_epoch)

    def value(self, step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(step / max(self.warmup_steps, 1), 0.0, 1.0)
        warm = self.start_lr + (self.end_lr - self.start_lr) * frac
        if self.inner is not None:
            after = self.inner.value(jnp.maximum(step - self.warmup_steps, 0))
            return jnp.where(step < self.warmup_steps, warm, after)
        return jnp.where(step < self.warmup_steps, warm,
                         jnp.full((), self.end_lr, jnp.float32))


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate: float, T_max: int, eta_min: float = 0.0,
                 last_epoch: int = -1):
        self.T_max = int(T_max)
        self.eta_min = float(eta_min)
        super().__init__(learning_rate, last_epoch)

    def value(self, step):
        step = jnp.asarray(step, jnp.float32)
        t = jnp.clip(step / self.T_max, 0.0, 1.0)
        return self.eta_min + 0.5 * (self.base_lr - self.eta_min) * (
            1.0 + jnp.cos(math.pi * t))


class StepDecay(LRScheduler):
    def __init__(self, learning_rate: float, step_size: int, gamma: float = 0.1,
                 last_epoch: int = -1):
        self.step_size = int(step_size)
        self.gamma = float(gamma)
        super().__init__(learning_rate, last_epoch)

    def value(self, step):
        k = jnp.floor(jnp.asarray(step, jnp.float32) / self.step_size)
        return self.base_lr * jnp.power(self.gamma, k)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate: float, milestones, gamma: float = 0.1,
                 last_epoch: int = -1):
        self.milestones = [int(m) for m in milestones]
        self.gamma = float(gamma)
        super().__init__(learning_rate, last_epoch)

    def value(self, step):
        step = jnp.asarray(step, jnp.float32)
        k = jnp.zeros((), jnp.float32)
        for m in self.milestones:
            k = k + (step >= m).astype(jnp.float32)
        return self.base_lr * jnp.power(self.gamma, k)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate: float, gamma: float,
                 last_epoch: int = -1):
        self.gamma = float(gamma)
        super().__init__(learning_rate, last_epoch)

    def value(self, step):
        return self.base_lr * jnp.power(self.gamma,
                                        jnp.asarray(step, jnp.float32))


class NoamDecay(LRScheduler):
    def __init__(self, d_model: int, warmup_steps: int,
                 learning_rate: float = 1.0, last_epoch: int = -1):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch)

    def value(self, step):
        s = jnp.maximum(jnp.asarray(step, jnp.float32), 1.0)
        return self.base_lr * (self.d_model ** -0.5) * jnp.minimum(
            s ** -0.5, s * (self.warmup_steps ** -1.5))


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate: float, decay_steps: int,
                 end_lr: float = 0.0001, power: float = 1.0,
                 last_epoch: int = -1):
        self.decay_steps = int(decay_steps)
        self.end_lr = float(end_lr)
        self.power = float(power)
        super().__init__(learning_rate, last_epoch)

    def value(self, step):
        t = jnp.clip(jnp.asarray(step, jnp.float32) / self.decay_steps, 0.0, 1.0)
        return (self.base_lr - self.end_lr) * jnp.power(1.0 - t,
                                                        self.power) + self.end_lr
