"""Profiler facade.

TPU-native equivalent of the reference's profiler (upstream layout:
python/paddle/profiler/profiler.py — ``Profiler``, ``make_scheduler``,
``export_chrome_tracing``, ``RecordEvent``; the C++ tracers at
paddle/fluid/platform/profiler/ are replaced by XLA's profiler, reached via
``jax.profiler`` — device traces come from the TPU runtime itself).

The scheduler-state machine (CLOSED/READY/RECORD) and the step() protocol
match the reference; traces land as TensorBoard/XPlane dumps (viewable in
TensorBoard's profile plugin or Perfetto, the successor of chrome://tracing
— the artifact the reference's ChromeTracingLogger produced).
"""

from __future__ import annotations

import enum
import os
import time
from typing import Callable, Iterable, Optional

import jax

__all__ = ["ProfilerState", "ProfilerTarget", "make_scheduler",
           "export_chrome_tracing", "Profiler", "RecordEvent"]


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3  # last record step of a cycle


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0
                   ) -> Callable[[int], ProfilerState]:
    """Step → state schedule (parity: paddle.profiler.make_scheduler)."""
    cycle = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None
                          ) -> Callable:
    """on_trace_ready callback directing trace output under ``dir_name``
    (parity: paddle.profiler.export_chrome_tracing; format note in module
    doc).  The Profiler reads ``handler.dir_name`` at construction, so the
    XLA trace dump actually lands where the exporter points."""
    def handler(prof: "Profiler"):
        prof._last_export = dir_name
    handler.dir_name = dir_name
    os.makedirs(dir_name, exist_ok=True)
    return handler


class RecordEvent:
    """User-scope annotation visible in the trace (parity:
    paddle.profiler.RecordEvent; ≙ jax.profiler.TraceAnnotation).

    Emits the scope TWICE so host and device views line up: as a jax
    TraceAnnotation (shows up inside the XLA/XPlane device dump) and as
    a host span in ``paddle_tpu.observability``'s tracer (shows up in
    the Chrome-trace/Perfetto export next to the serving scheduler's
    spans) — the same labelled region in both timelines."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = jax.profiler.TraceAnnotation(name)
        self._span = None

    def begin(self):
        from .. import observability
        self._span = observability.get_tracer().start(self.name, cat="user")
        self._ann.__enter__()

    def end(self):
        from .. import observability
        self._ann.__exit__(None, None, None)
        observability.get_tracer().finish(self._span)
        self._span = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


class Profiler:
    """Parity: paddle.profiler.Profiler.

    with Profiler(scheduler=make_scheduler(closed=1, ready=1, record=3),
                  on_trace_ready=export_chrome_tracing("./prof")) as p:
        for batch in loader:
            train_step(...)
            p.step()
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 log_dir: str = "./profiler_log", timer_only: bool = False):
        del targets  # one backend: whatever jax runs on
        if isinstance(scheduler, tuple):  # (start, stop) parity form
            lo, hi = scheduler
            scheduler = make_scheduler(closed=max(0, lo), ready=0,
                                       record=hi - lo, repeat=1)
        self.scheduler = scheduler or (lambda step: ProfilerState.RECORD)
        self.on_trace_ready = on_trace_ready
        # an export_chrome_tracing handler declares where traces belong
        if on_trace_ready is not None and hasattr(on_trace_ready, "dir_name"):
            log_dir = on_trace_ready.dir_name
        self.log_dir = log_dir
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._tracing = False
        self._in_export = False
        self._step_times = []
        self._last_t: Optional[float] = None
        self._last_export: Optional[str] = None

    # -- state machine -------------------------------------------------------

    def _finish_trace(self):
        """Close the current trace segment and fire on_trace_ready
        EXACTLY once for it.  ``_tracing`` is cleared before anything
        else runs, so the method is idempotent per segment however
        ``stop()`` and scheduler transitions interleave (the historical
        double-export: ``stop()`` right after a RECORD_AND_RETURN
        transition re-ran the export path), and ``_in_export`` guards a
        handler that itself calls ``stop()`` from recursing back in."""
        if not self._tracing:
            return
        self._tracing = False
        jax.profiler.stop_trace()
        if self.on_trace_ready is not None and not self._in_export:
            self._in_export = True
            try:
                self.on_trace_ready(self)
            finally:
                self._in_export = False

    def _transition(self):
        new = self.scheduler(self.step_num)
        recording = new in (ProfilerState.RECORD,
                            ProfilerState.RECORD_AND_RETURN)
        # RECORD_AND_RETURN means "last record step of a cycle": leaving
        # it is a segment boundary even when the next state records again
        # (repeat cycles) — previously back-to-back cycles merged into
        # one ever-growing trace and only exported once at the very end
        if self.current_state is ProfilerState.RECORD_AND_RETURN:
            self._finish_trace()
        if recording and not self._tracing and not self.timer_only:
            jax.profiler.start_trace(self.log_dir)
            self._tracing = True
        if not recording:
            self._finish_trace()
        self.current_state = new

    def start(self):
        self._last_t = time.perf_counter()
        self._transition()
        return self

    def stop(self):
        self._finish_trace()
        self.current_state = ProfilerState.CLOSED

    def step(self):
        now = time.perf_counter()
        if self._last_t is not None:
            self._step_times.append(now - self._last_t)
        self._last_t = now
        self.step_num += 1
        self._transition()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- summaries -----------------------------------------------------------

    def step_info(self) -> str:
        if not self._step_times:
            return "no steps recorded"
        ts = self._step_times
        return (f"steps: {len(ts)}  avg: {sum(ts) / len(ts) * 1e3:.2f} ms  "
                f"min: {min(ts) * 1e3:.2f} ms  max: {max(ts) * 1e3:.2f} ms")

    def summary(self, sorted_by=None, op_detail: bool = False,
                thread_sep: bool = False, time_unit: str = "ms") -> str:
        return self.step_info()
