"""paddle_tpu.serving — continuous-batching inference runtime.

The whole-scan ``generate()`` path (models/generation.py) is the parity
benchmark: one compiled program per static (batch, prompt, max_new_tokens)
config, every row entering and leaving together.  Serving traffic is the
opposite shape — staggered arrivals, mixed lengths — and BENCH_DECODE.json
shows per-step decode already runs at the weight-stream bound, so the
remaining throughput lever is keeping batch slots FULL.  This package is
the Orca-style engine that does that: a fixed-slot KV cache, a step-level
decode function compiled exactly once, and a host-side scheduler that
admits queued requests into freed slots mid-flight.
"""

from .admission import HoldQueue, Verdict, place_verdict
from .autoscaler import ReplicaAutoscaler
from .drafter import Drafter, DraftModelDrafter, NgramDrafter
from .engine import Request, SamplingParams, ServingEngine
from .fleet_sim import FleetSim, SimEngine, SimSpec, run_fleet
from .kv_cache import BlockManager, init_paged_kv_cache
from .loadgen import LoadRequest, LoadSpec, generate_load, replay
from .router import ReplicaRouter

__all__ = ["ServingEngine", "SamplingParams", "Request", "BlockManager",
           "init_paged_kv_cache", "Drafter", "DraftModelDrafter",
           "NgramDrafter", "ReplicaRouter",
           "LoadRequest", "LoadSpec", "generate_load", "replay",
           "HoldQueue", "Verdict", "place_verdict", "ReplicaAutoscaler",
           "FleetSim", "SimEngine", "SimSpec", "run_fleet"]
