"""Predictive SLO admission primitives — the control plane's pricing
layer (ISSUE 17 tentpole a).

The reactive serving stack admits on queue depth alone and repairs
mistakes after the fact (preemption, PR 16).  This module turns the
calibrated roofline cost model (PR 15) into a *pre-placement* question:
"will admitting this prompt at this replica's current (occupancy,
depth, chunk backlog) blow the pooled TPOT/TTFT SLO?"  Two pieces:

* :func:`place_verdict` prices one candidate placement against the
  engine's :meth:`~paddle_tpu.serving.engine.ServingEngine.
  admission_probe` — verdict ``admit`` when the predicted post-
  admission tick (calibrated into wall ms through
  FLAGS_serving_admission_calib) fits every armed deadline with
  FLAGS_serving_admission_slack headroom, ``defer`` with a *price*
  (the worst predicted overage in ms) otherwise;

* :class:`HoldQueue` is the priced deferral queue the router parks
  deferred requests in instead of blindly rejecting them: entries pop
  by (aged-first, priority class, price, arrival) — the PR-16 priority
  classes outrank pricing, the cheapest-to-admit request within a
  class goes first, and any entry older than
  FLAGS_serving_admission_max_defer_ticks jumps the whole line
  (aging beats pricing: the queue can never starve).

Decisions are pure functions of scheduler state — no wall-clock input —
so twin replays of one deterministic trace hold and place identically
(the fleet simulator and the loadgen smoke gate both lean on this).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional

from .. import flags as _flags

__all__ = ["Verdict", "place_verdict", "HoldEntry", "HoldQueue"]


@dataclasses.dataclass(frozen=True)
class Verdict:
    """One priced placement decision.  ``verdict`` is ``admit`` or
    ``defer``; ``price`` is the worst predicted SLO overage in wall ms
    (0 for admit) — the hold queue orders by it within a priority
    class.  ``reason`` names the deciding rule."""

    verdict: str
    predicted_tpot_ms: float = 0.0
    predicted_ttft_ms: float = 0.0
    price: float = 0.0
    reason: str = ""


def place_verdict(engine, prompt_len: int, *,
                  ttft_slo_ms: float = 0.0,
                  tpot_slo_ms: float = 0.0) -> Verdict:
    """Price placing one more ``prompt_len``-token request on
    ``engine``.  Admits unconditionally when the engine has no cost
    model (FLAGS_perf_model off — today's reactive policy IS the
    fallback) or the request carries no armed deadline (nothing to
    protect, and batch traffic must not be starved by a gate it never
    asked for — the *pooled* guard lives in the engine's own
    ``_admission_defer``)."""
    probe = engine.admission_probe(int(prompt_len))
    if probe is None:
        return Verdict("admit", reason="no_model")
    calib = float(_flags.flag("serving_admission_calib"))
    tpot = probe["predicted_tpot_ms"] * calib
    ttft = probe["predicted_ttft_ms"] * calib
    if ttft_slo_ms <= 0 and tpot_slo_ms <= 0:
        return Verdict("admit", tpot, ttft, reason="no_deadline")
    slack = float(_flags.flag("serving_admission_slack"))
    price = 0.0
    if tpot_slo_ms > 0:
        price = max(price, tpot - tpot_slo_ms * slack)
    if ttft_slo_ms > 0:
        price = max(price, ttft - ttft_slo_ms * slack)
    if price > 0:
        return Verdict("defer", tpot, ttft, price, "predicted_slo")
    return Verdict("admit", tpot, ttft, reason="fits")


@dataclasses.dataclass(eq=False)
class HoldEntry:
    """One deferred request parked in the hold queue.  ``payload`` is
    the owner's placement closure state (the router keeps the prompt /
    sampling / session there); ``seq`` is the arrival tiebreak.
    Identity equality (``eq=False``): the queue removes entries by
    object identity and payloads may hold numpy arrays."""

    payload: Any
    priority: int = 0
    price: float = 0.0
    seq: int = 0
    defer_ticks: int = 0


class HoldQueue:
    """The priced deferral queue.  Pop order: aged entries first (in
    arrival order — FIFO among the starving), then by descending
    priority class, ascending price, arrival.  ``tick()`` ages every
    entry once per scheduler tick; the owner re-prices entries it
    fails to place (predicted state moved under them)."""

    def __init__(self, max_defer_ticks: Optional[int] = None) -> None:
        self._max_defer = max_defer_ticks
        self._entries: List[HoldEntry] = []
        self._seq = 0

    @property
    def max_defer_ticks(self) -> int:
        if self._max_defer is not None:
            return int(self._max_defer)
        return int(_flags.flag("serving_admission_max_defer_ticks"))

    def push(self, payload: Any, *, priority: int = 0,
             price: float = 0.0) -> HoldEntry:
        e = HoldEntry(payload, priority=int(priority), price=float(price),
                      seq=self._seq)
        self._seq += 1
        self._entries.append(e)
        return e

    def aged(self, e: HoldEntry) -> bool:
        """True once ``e`` has waited past the starvation bound — the
        owner must force-place it regardless of the SLO prediction."""
        maxd = self.max_defer_ticks
        return maxd > 0 and e.defer_ticks >= maxd

    def _key(self, e: HoldEntry):
        return (0 if self.aged(e) else 1,
                e.seq if self.aged(e) else 0,
                -e.priority, e.price, e.seq)

    def ordered(self) -> List[HoldEntry]:
        """Entries in pop order (non-destructive — the owner walks this
        each tick and removes what it managed to place)."""
        return sorted(self._entries, key=self._key)

    def remove(self, entry: HoldEntry) -> None:
        self._entries.remove(entry)

    def tick(self) -> None:
        for e in self._entries:
            e.defer_ticks += 1

    def __len__(self) -> int:
        return len(self._entries)

    def __iter__(self) -> Iterator[HoldEntry]:
        return iter(self._entries)

    def snapshot(self) -> List[Dict[str, Any]]:
        return [{"priority": e.priority, "price": round(e.price, 6),
                 "seq": e.seq, "defer_ticks": e.defer_ticks,
                 "aged": self.aged(e)} for e in self.ordered()]
