"""Replica autoscaling against the predictive control plane (ISSUE 17
tentpole b).

:class:`ReplicaAutoscaler` wraps a :class:`~paddle_tpu.serving.router.
ReplicaRouter` and turns the control plane's own pressure signals into
elastic dp-replica decisions:

* **scale up** when predicted-SLO pressure persists — the router's
  hold queue is non-empty (every candidate replica priced the next
  placement over the pooled TPOT/TTFT SLO: attained goodput is about
  to fall short of predicted) or fleet demand runs past the high
  utilization water mark;

* **scale down** when slack persists — demand would comfortably fit on
  one fewer replica.  Shrinking is drain-before-retire: the chosen
  replica stops taking NEW placements but keeps serving its queue and
  pinned sessions (sessions never migrate), and is retired only once
  empty.  Pressure arriving mid-drain undrains instead of building a
  new replica — the cheapest capacity is the capacity still running.

Hysteresis comes from FLAGS_serving_autoscale_min_ticks (a signal must
persist that many consecutive ``observe()`` ticks before acting) and
FLAGS_serving_autoscale_cooldown (minimum ticks between two actions in
either direction).  Decisions are pure functions of scheduler state —
no wall-clock input — so fleet-simulator replays of one trace scale
identically, and the whole loop runs on virtual CPU devices (the unit
tests drive it over :class:`~paddle_tpu.serving.fleet_sim.SimEngine`
replicas).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from .. import flags as _flags
from .. import observability as _obs
from .router import ReplicaRouter

__all__ = ["ReplicaAutoscaler"]


class ReplicaAutoscaler:
    """Drive ``router`` elastic from control-plane pressure/slack.

    Call :meth:`observe` once per router tick (after ``router.step()``).
    ``engine_factory`` builds one replica engine for scale-up; routers
    constructed from a model carry their own factory and can omit it.
    ``high`` / ``low`` are the demand-per-slot water marks (demand =
    active + queued + pending + preempted + held)."""

    def __init__(self, router: ReplicaRouter, *,
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 engine_factory: Optional[Callable[[], Any]] = None,
                 high: float = 0.9, low: float = 0.4) -> None:
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas is not None and max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if not 0.0 <= low < high:
            raise ValueError("need 0 <= low < high")
        self.router = router
        self.min_replicas = int(min_replicas)
        self.max_replicas = (int(max_replicas)
                             if max_replicas is not None else None)
        self._factory = engine_factory
        self.high = float(high)
        self.low = float(low)
        self._pressure_ticks = 0
        self._slack_ticks = 0
        self._since_action = 10 ** 9    # first decision is not damped
        self._actions: List[Dict[str, Any]] = []
        self._tick = 0
        reg = _obs.default_registry()
        self._f_actions = reg.counter(
            "autoscaler.actions",
            "ReplicaAutoscaler decisions by kind: add (new replica "
            "built), undrain (draining replica returned to service), "
            "drain (replica excluded from new placements), retire "
            "(empty drained replica left the tick loop)")

    # -- signals -----------------------------------------------------------

    def _serving(self) -> List[int]:
        """Replicas accepting NEW placements (live minus draining)."""
        return [i for i in self.router.live_replicas
                if i not in self.router._draining]

    def demand(self) -> int:
        """Fleet-wide work in flight or waiting: busy slots plus every
        queue the scheduler owns, plus the router's hold queue — the
        attained-vs-predicted shortfall shows up here first (holds ARE
        deferred goodput)."""
        n = 0
        for i in self.router.live_replicas:
            e = self.router.engines[i]
            n += (e.num_active + e.queue_depth + e.num_pending
                  + getattr(e, "num_preempted", 0))
        return n + self.router.pending_held

    def utilization(self) -> float:
        """Demand per serving slot (>1 = more work than the serving
        replicas can even hold resident)."""
        serving = self._serving()
        slots = sum(self.router.engines[i].num_slots for i in serving)
        return self.demand() / slots if slots else float("inf")

    # -- the decision loop -------------------------------------------------

    def observe(self) -> Optional[str]:
        """One hysteresis tick; returns the action taken (``"add"``,
        ``"undrain"``, ``"drain"``, ``"retire"``) or None.  Retirement
        of an empty draining replica completes an earlier drain
        decision and is exempt from the cooldown."""
        self._tick += 1
        self._since_action += 1
        # finish pending drains first: retire is the completion of a
        # decision already damped when it was made
        for i in sorted(self.router._draining):
            if (self.router.replica_empty(i)
                    and len(self.router.live_replicas) > max(
                        1, self.min_replicas)):
                self.router.retire_replica(i)
                return self._record("retire", i)
        util = self.utilization()
        pressure = self.router.pending_held > 0 or util > self.high
        slack = (self.router.pending_held == 0 and util < self.low)
        self._pressure_ticks = self._pressure_ticks + 1 if pressure else 0
        self._slack_ticks = self._slack_ticks + 1 if slack else 0
        min_ticks = int(_flags.flag("serving_autoscale_min_ticks"))
        cooldown = int(_flags.flag("serving_autoscale_cooldown"))
        if self._since_action < cooldown:
            return None
        if self._pressure_ticks >= min_ticks:
            return self._scale_up()
        if self._slack_ticks >= min_ticks:
            return self._scale_down()
        return None

    def _scale_up(self) -> Optional[str]:
        if self.router._draining:
            # cheapest capacity: a replica still running its tail
            i = min(self.router._draining)
            self.router.undrain_replica(i)
            return self._record("undrain", i)
        if (self.max_replicas is not None
                and len(self.router.live_replicas) >= self.max_replicas):
            return None
        engine = self._factory() if self._factory is not None else None
        try:
            i = self.router.add_replica(engine)
        except ValueError:
            # router over pre-built engines and no factory here: the
            # fleet cannot grow — keep serving, pressure stays visible
            return None
        return self._record("add", i)

    def _scale_down(self) -> Optional[str]:
        serving = self._serving()
        if len(serving) <= self.min_replicas:
            return None
        # drain the least-loaded serving replica: shortest tail to
        # retire, and the load it sheds redistributes the furthest
        i = min(serving,
                key=lambda j: (self.router._load(self.router.engines[j]),
                               j))
        self.router.drain_replica(i)
        return self._record("drain", i)

    def _record(self, kind: str, replica: int) -> str:
        self._since_action = 0
        self._pressure_ticks = 0
        self._slack_ticks = 0
        self._actions.append({"tick": self._tick, "action": kind,
                              "replica": int(replica)})
        self._f_actions.labels(action=kind).inc()
        return kind

    # -- telemetry ---------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        return {
            "tick": self._tick,
            "live_replicas": len(self.router.live_replicas),
            "serving_replicas": len(self._serving()),
            "draining": sorted(self.router._draining),
            "utilization": round(self.utilization(), 4),
            "held_requests": self.router.pending_held,
            "actions": list(self._actions),
        }
