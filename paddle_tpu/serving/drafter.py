"""Drafting proposers for speculative decoding, behind one ``Drafter``
interface.

The serving engine's spec-decode mode (engine.py) needs a source of
draft tokens: candidates the once-jitted verify step can score k at a
time through the q-tiled flash-decode path, so an accepted draft costs a
fraction of a weight pass instead of a whole one.  Two proposers:

  * :class:`NgramDrafter` — **prompt lookup / n-gram self-drafting**
    (the vLLM ``ngram`` speculator, PLD): pure host-side numpy over each
    slot's token history, free but unable to draft *novel* text — it
    only restates spans already present in the history.  Its proposal
    distribution is the one-hot at each drafted token (a deterministic
    proposer), which is what the rejection-sampling acceptance
    (models/generation.py ``accept_draft_tokens``) sees for it;
  * :class:`DraftModelDrafter` — a small draft **model** sharing the
    engine (Leviathan et al. 2023): a second param set placed by the
    same ``decode_mesh_specs`` machinery, its own tiny contiguous KV
    cache (fixed depth, no allocator) and its own once-jitted draft
    step at q-depth k.  It drafts novel text and emits the true
    proposal distribution q, so sampled rows speculate with the exact
    target distribution under rejection sampling.

A proposal is just data riding the verify step's static (num_slots, k)
draft operand (plus the (num_slots, k, V) proposal-distribution
operand), pad-masked where the drafter had nothing to say.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["Drafter", "NgramDrafter", "DraftModelDrafter"]


class Drafter:
    """Interface both proposers implement.  ``kind`` labels lifecycle
    events and the ``drafter=`` axis of the spec counters; host-side
    proposers implement :meth:`propose` (per slot), device-side ones
    implement :meth:`propose_batch` (whole slot batch, one compiled
    call) — the engine dispatches on ``uses_device``."""

    kind: str = "custom"
    uses_device: bool = False

    def propose(self, history) -> np.ndarray:
        """Draft tokens following ``history``: int32 (m,), 0 <= m <= k;
        empty means "no proposal — the row decodes plain"."""
        raise NotImplementedError

    def reset_slot(self, i: int) -> None:
        """Forget any per-slot state (slot ``i`` was (re)assigned)."""

    def rollback(self, i: int) -> None:
        """A verify step rejected drafts for slot ``i`` — stateful
        proposers drop anything speculated past the committed stream.
        (Both built-ins track committed history only, so this is a
        no-op hook.)"""


class NgramDrafter(Drafter):
    """Prompt-lookup proposer: match the history's tail n-gram against
    its own earlier occurrences and propose the tokens that followed.

    For ``n = max_ngram .. min_ngram`` (longest first — a longer context
    match is a stronger continuation signal), find the MOST RECENT prior
    occurrence of the last ``n`` tokens inside the history; on a hit,
    propose the (up to) ``k`` tokens that followed it.  No hit at any n
    ⇒ no proposal (the row rides the verify step as plain depth-1
    decode).  Proposals are never fabricated — every draft token is
    lifted verbatim from the history, which is what makes the scheme
    free: no model, no state, no trace.
    """

    kind = "ngram"

    def __init__(self, k: int, max_ngram: int = 3, min_ngram: int = 1):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]")
        self.k = int(k)
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, history) -> np.ndarray:
        """Draft tokens following ``history`` (prompt + generated so
        far, the last entry being the token about to be fed to the
        model).  Returns int32 (m,) with ``0 <= m <= k``; empty means
        "no match — decode plain"."""
        h = np.asarray(history, np.int64).ravel()
        n_hi = min(self.max_ngram, h.size - 1)
        for n in range(n_hi, self.min_ngram - 1, -1):
            tail = h[h.size - n:]
            # all length-n windows; the last one IS the tail, so a prior
            # occurrence is any earlier window — take the most recent
            win = np.lib.stride_tricks.sliding_window_view(h, n)
            hits = np.flatnonzero((win[:-1] == tail).all(axis=1))
            if hits.size:
                i = int(hits[-1])
                return h[i + n:i + n + self.k].astype(np.int32)
        return np.zeros((0,), np.int32)


class DraftModelDrafter(Drafter):
    """Draft-MODEL proposer (Leviathan et al. 2023): a small causal LM
    rides the engine and autoregressively proposes k tokens per slot per
    tick, emitting the proposal distribution q the rejection-sampling
    acceptance needs.

    Engine-shaped by construction:

      * its KV cache is one CONTIGUOUS stacked array
        ``(L_draft, 2, num_slots, max_length, Hkv, D)`` — fixed depth,
        no allocator, no block tables; a draft row only ever holds the
        committed stream plus this tick's in-flight speculation, and
        stale speculative cells are overwritten sequentially before any
        later query can attend them (the same scatter-then-read layer
        order the verify window relies on);
      * TWO once-jitted programs, each under its own retrace budget of
        1: the **draft step** (window of up to k+1 caught-up history
        tokens at per-row start positions, then k sampled continuations
        — greedy rows take the argmax, sampled rows draw from q =
        softmax of the draft logits, and q is returned per column) and
        the fixed-width **ingest step** that drains long backlogs
        (admission / resume / import hand the drafter a cold slot and
        the whole prompt catches up through it, ``ingest_width`` tokens
        per call);
      * idle or non-participating rows are steered to
        ``start = max_length`` so their cache scatters drop out of
        bounds — the engine's existing idle-row write convention;
      * per-slot ``consumed`` counters track COMMITTED history only, so
        verify-step rollback needs no draft-side undo: the next tick's
        window simply rewrites from the committed frontier.

    On a mesh engine the draft params/cache are placed by the same
    ``decode_mesh_specs`` machinery as the target's, and both programs
    jit with declared shardings (params/cache per spec, small operands
    replicated) under the engine's mesh scope.

    ``model``/``params`` default to the TARGET model acting as its own
    drafter ("self-drafting at full strength") — useful for tests and
    as the acceptance-rate ceiling; pass a truncated model from
    :func:`paddle_tpu.models.llama.draft_model_from` for a real draft.
    """

    kind = "model"
    uses_device = True

    def __init__(self, k: int, model, params, num_slots: int,
                 max_length: int, pad_token_id: int = 0, mesh=None,
                 engine_id: str = "0", ingest_width: int = 16):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.k = int(k)
        self.model = model
        self.num_slots = int(num_slots)
        self.max_length = int(max_length)
        self.pad_token_id = int(pad_token_id)
        self.mesh = mesh
        self.ingest_width = max(int(ingest_width), self.k + 1)
        self._eid = str(engine_id)
        self._bind = getattr(model, "unwrapped", model)
        self._prepare = getattr(model, "_prepare_params", lambda p: p)
        self._consumed = np.zeros((self.num_slots,), np.int64)
        self._params = params
        self._cache = None        # built (and mesh-placed) on first use
        self._draft_fn = None
        self._ingest_fn = None

    # -- per-slot lifecycle hooks (engine admission/retire/resume) ----
    def reset_slot(self, i: int) -> None:
        self._consumed[i] = 0

    @property
    def draft_traces(self) -> int:
        """Compilations of the draft step (jit.traces read-through; the
        budget, like the verify step's, is exactly 1)."""
        return (int(self._draft_fn.traces)
                if self._draft_fn is not None else 0)

    # -- jitted bodies ------------------------------------------------
    def _draft_impl(self, params, cache, window, start, nvalid, temps,
                    key):
        import jax
        import jax.numpy as jnp

        from ..nn.layer import bind_params

        with bind_params(self._bind, self._prepare(params)):
            logits, cache = self.model.decode_step(window, cache, start)
            last = jnp.take_along_axis(
                logits, jnp.maximum(nvalid - 1, 0)[:, None, None],
                axis=1)[:, 0]                              # (S, V)
            drafts, probs = [], []
            for j in range(self.k):
                lg = last.astype(jnp.float32)
                probs.append(jax.nn.softmax(lg, axis=-1))
                tok = jnp.where(
                    temps <= 0.0,
                    jnp.argmax(lg, axis=-1).astype(jnp.int32),
                    jax.random.categorical(
                        jax.random.fold_in(key, j), lg,
                        axis=-1).astype(jnp.int32))
                drafts.append(tok)
                if j < self.k - 1:
                    logits, cache = self.model.decode_step(
                        tok[:, None], cache, start + nvalid + j)
                    last = logits[:, 0]
            return (jnp.stack(drafts, axis=1),
                    jnp.stack(probs, axis=1), cache)

    def _ingest_impl(self, params, cache, window, start):
        from ..nn.layer import bind_params

        with bind_params(self._bind, self._prepare(params)):
            _, cache = self.model.decode_step(window, cache, start)
            return cache

    def _build(self):
        """First-use setup: allocate (and mesh-place) the draft cache,
        jit the two programs under their retrace budgets."""
        import jax.numpy as jnp

        from .. import observability as _obs
        from ..models.generation import _place_on_mesh, init_kv_cache

        self._cache = init_kv_cache(self.model.config, self.num_slots,
                                    self.max_length)
        self._params, self._cache, _ = _place_on_mesh(
            self._bind, self._params, self._cache,
            jnp.zeros((self.num_slots,), jnp.int32), mesh=self.mesh)
        lbl = {"engine": self._eid}
        dkw = {"donate_argnums": (1,)}
        ikw = {"donate_argnums": (1,)}
        if self.mesh is not None:
            dkw.update(self._jit_shardings(7, 3))
            ikw.update(self._jit_shardings(4, 1))
        self._draft_fn = _obs.track_retraces(
            self._under_mesh(self._draft_impl), "serving.draft_step",
            budget=1, labels=lbl, **dkw)
        self._ingest_fn = _obs.track_retraces(
            self._under_mesh(self._ingest_impl), "serving.draft_prefill",
            budget=1, labels=lbl, **ikw)

    def _under_mesh(self, impl):
        if self.mesh is None:
            return impl
        import functools

        from ..distributed import env as _denv

        @functools.wraps(impl)
        def traced_under_mesh(*args):
            with _denv.use_mesh(self.mesh):
                return impl(*args)
        return traced_under_mesh

    def _jit_shardings(self, n_args, n_out):
        """Declared shardings mirroring the engine's step programs:
        draft params/cache per ``decode_mesh_specs``, everything else
        replicated, the cache the trailing output."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ..models.generation import decode_mesh_specs

        param_specs, cache_spec, _ = decode_mesh_specs(
            self._bind, self._params, self.mesh.axis_names)

        def ns(spec):
            return NamedSharding(self.mesh, spec)

        repl = ns(P())
        in_sh = [repl] * n_args
        in_sh[0] = jax.tree_util.tree_map(ns, param_specs)
        in_sh[1] = ns(cache_spec)
        out_sh = (ns(cache_spec) if n_out == 1
                  else tuple([repl] * (n_out - 1) + [ns(cache_spec)]))
        return {"in_shardings": tuple(in_sh), "out_shardings": out_sh}

    # -- the engine-facing batched call -------------------------------
    def propose_batch(self, histories, temps, seed: int):
        """One tick's proposals for the slots in ``histories`` (dict
        ``slot -> int32 committed token stream``, last entry the token
        about to be fed).  Returns ``(drafts (S, k) int32, probs
        (S, k, V) f32)`` over the FULL slot batch — rows absent from
        ``histories`` are pad/zero and steered out of bounds on the
        device.  ``temps``: the engine's (S,) per-slot temperatures;
        ``seed``: the tick's deterministic draw."""
        import jax
        import jax.numpy as jnp

        if self._cache is None:
            self._build()
        s, k = self.num_slots, self.k
        # drain cold/long backlogs through the fixed-width ingest step
        while True:
            over = {i: h for i, h in histories.items()
                    if h.size - self._consumed[i] > k + 1}
            if not over:
                break
            iw = np.full((s, self.ingest_width), self.pad_token_id,
                         np.int32)
            ist = np.full((s,), self.max_length, np.int32)
            for i, h in over.items():
                c = int(self._consumed[i])
                n = min(self.ingest_width, h.size - c - (k + 1))
                iw[i, :n] = h[c:c + n]
                ist[i] = c
                self._consumed[i] = c + n
            self._cache = self._ingest_fn(
                self._params, self._cache, jnp.asarray(iw),
                jnp.asarray(ist))
        win = np.full((s, k + 1), self.pad_token_id, np.int32)
        start = np.full((s,), self.max_length, np.int32)
        nval = np.zeros((s,), np.int32)
        for i, h in histories.items():
            c = int(self._consumed[i])
            n = h.size - c                       # 1 .. k+1 by the drain
            win[i, :n] = h[c:]
            start[i] = c
            nval[i] = n
            self._consumed[i] = h.size
        drafts, probs, self._cache = self._draft_fn(
            self._params, self._cache, jnp.asarray(win),
            jnp.asarray(start), jnp.asarray(nval),
            jnp.asarray(temps, jnp.float32),
            jax.random.fold_in(jax.random.key(0), int(seed)))
        return np.asarray(drafts), np.asarray(probs, np.float32)
