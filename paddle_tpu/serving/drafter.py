"""Host-side self-drafting proposers for speculative decoding.

The serving engine's spec-decode mode (engine.py) needs a cheap source of
draft tokens: candidates the once-jitted verify step can score k at a
time through the q-tiled flash-decode path, so an accepted draft costs a
fraction of a weight pass instead of a whole one.  A second draft *model*
would buy the best acceptance rates (Leviathan et al. 2023) but drags in
a second set of weights, its own KV state and a second compiled program;
**prompt lookup / n-gram self-drafting** (the vLLM ``ngram`` speculator,
PLD) gets most of the win for free on the workloads speculative decoding
targets anyway — summarisation, code edits, RAG, chat with long shared
context — where the continuation frequently restates spans that already
appear in the prompt or in the tokens generated so far.

Everything here is pure host-side numpy over each slot's token history;
nothing touches the device or the compiled step (a proposal is just data
riding the verify step's static (num_slots, k) draft operand, pad-masked
where the drafter had nothing to say).
"""

from __future__ import annotations

import numpy as np

__all__ = ["NgramDrafter"]


class NgramDrafter:
    """Prompt-lookup proposer: match the history's tail n-gram against
    its own earlier occurrences and propose the tokens that followed.

    For ``n = max_ngram .. min_ngram`` (longest first — a longer context
    match is a stronger continuation signal), find the MOST RECENT prior
    occurrence of the last ``n`` tokens inside the history; on a hit,
    propose the (up to) ``k`` tokens that followed it.  No hit at any n
    ⇒ no proposal (the row rides the verify step as plain depth-1
    decode).  Proposals are never fabricated — every draft token is
    lifted verbatim from the history, which is what makes the scheme
    free: no model, no state, no trace.
    """

    def __init__(self, k: int, max_ngram: int = 3, min_ngram: int = 1):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]")
        self.k = int(k)
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, history) -> np.ndarray:
        """Draft tokens following ``history`` (prompt + generated so
        far, the last entry being the token about to be fed to the
        model).  Returns int32 (m,) with ``0 <= m <= k``; empty means
        "no match — decode plain"."""
        h = np.asarray(history, np.int64).ravel()
        n_hi = min(self.max_ngram, h.size - 1)
        for n in range(n_hi, self.min_ngram - 1, -1):
            tail = h[h.size - n:]
            # all length-n windows; the last one IS the tail, so a prior
            # occurrence is any earlier window — take the most recent
            win = np.lib.stride_tricks.sliding_window_view(h, n)
            hits = np.flatnonzero((win[:-1] == tail).all(axis=1))
            if hits.size:
                i = int(hits[-1])
                return h[i + n:i + n + self.k].astype(np.int32)
        return np.zeros((0,), np.int32)
