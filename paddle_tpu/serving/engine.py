"""Slot-based continuous-batching engine over the stacked KV cache.

Design (Orca-style iteration-level scheduling, expressed TPU-first):

  * the KV cache is ONE stacked array ``(L, 2, num_slots, max_length,
    Hkv, D)`` — the ``generate()`` cache with the batch axis reinterpreted
    as *slots*.  A slot is a lease on one cache row; requests come and go,
    the array never changes shape, so nothing ever recompiles;
  * the **step function** ``(params, cache, tokens, positions, slot_mask,
    sampling vectors, rng) -> (next_tokens, cache)`` is jitted ONCE for
    the slot count and reused for the engine's lifetime.  Per-slot
    position vectors (ops/attention.py cache masking, llama.py scatter
    writes) are what let one program serve rows at different depths, and
    per-slot sampling vectors (generation.py ``sample_tokens``, traced
    form) let greedy and sampled requests share a batch.  The same
    position vector doubles as the flash-decode kernel's live-prefix
    hint: at max_length >= FLAGS_decode_attention_min_len the attention
    dispatcher hands it to ops/pallas/decode_attention.py as a
    scalar-prefetch operand that clamps the KV-chunk reads, so each step
    streams only each slot's live cache prefix — slots at shallow,
    heterogeneous depths under a worst-case-sized max_length stop paying
    for the dead tail, with no retrace;
  * **prefill** reuses the existing static-``pos=0`` path — the one that
    routes through the Pallas flash kernel on TPU: admitted prompts are
    right-padded to a power-of-two bucket, run through ``decode_step`` on
    a fresh ``prefill_batch``-row cache, and the finished rows are
    scattered into their slots.  Padding is sound because attention is
    causal (pad queries influence nobody) and the cache mask never reads
    past the row's position, while decode overwrites each pad slot with
    fresh K/V before the mask can reach it.  One compiled prefill program
    per bucket length — short rows ride along via out-of-bounds slot ids,
    which the scatter drops;
  * the **host scheduler** owns admission and retirement: a FIFO queue,
    waves of batched prefill into free slots, EOS/max-token retirement,
    and per-request outputs returned in arrival order.  Device work per
    tick is one step-function call; the only host sync is fetching the
    (num_slots,) token vector the scheduler must branch on.

Relation to ``generate()``: same model code path (``decode_step``), same
sampling implementation, same cache layout — greedy engine outputs are
token-identical to ``greedy_generate`` (tests/test_serving.py asserts
this across admission orders).  ``generate()`` remains the right tool for
offline parity/eval batches; the engine is the right tool for traffic.

**Paged mode** (``paged=True`` / FLAGS_serving_paged_kv): the per-slot
cache rows are replaced by the kv_cache.py block pool — one
``(L, 2, num_blocks, block_len, Hkv, D)`` array plus a host-side
:class:`~paddle_tpu.serving.kv_cache.BlockManager`.  What changes and
what doesn't:

  * the step function signature gains one tiny traced input, the
    ``(num_slots, max_blocks)`` block table; it is still jitted ONCE —
    allocation churn moves data through that input, never a retrace;
  * HBM cost becomes live tokens + shared prefixes instead of
    ``num_slots × max_length``: blocks are allocated lazily as slots
    deepen (admission reserves the worst case so mid-flight allocation
    can't fail), retired prompt blocks stay cached for prefix hits until
    pool pressure evicts them LRU-first;
  * admission consults the prefix trie: a request whose prompt opens with
    already-cached full blocks adopts them (refcount, zero recompute) and
    prefill runs ONLY the suffix — a shared system prompt is computed and
    stored once, which the manager's hit counters prove;
  * prefill therefore runs as decode-at-depth on the pool itself (per-row
    ``pos`` = adopted prefix length) rather than on a fresh pos=0
    sub-cache — it takes the cached-attention path, not the flash-prefill
    kernel; the trade is recompute avoided vs kernel choice, and it wins
    whenever prefixes actually repeat.  Greedy outputs stay
    token-identical to the contiguous engine (tests/test_serving_paged.py).

**Chunked prefill** (``chunked=True`` / FLAGS_serving_chunked_prefill):
wave admission stalls every in-flight decode for a whole prompt's prefill
latency (~90 ms at b=8, prompt 1024 per BENCH_DECODE.json) — the classic
TPOT-spike / head-of-line-blocking failure Sarathi-Serve's chunked
prefill and Orca's iteration-level scheduling target.  Chunked mode
replaces the wave with a **token-budget scheduler**:

  * each admitted prompt becomes a cursor (:class:`_Prefill`), not a
    prefill dispatch; every tick runs ONE **mixed step** — all decode
    rows advance one token AND at most one ``prefill_chunk``-token slice
    of the prompt streams into its slot's cache (as decode-at-depth:
    per-row positions, the flash-decode kernel's chunked q mode at long
    caches).  The per-tick token budget is ``num_slots + prefill_chunk``,
    so TPOT degrades by a bounded, chunk-sized amount instead of a
    whole-prompt stall, and TTFT pipelines across ticks;
  * the mixed step is jitted ONCE (chunk size static, budget-1
    ``track_retraces`` site ``serving.step``); chunk-free ticks ride the
    same program with a dummy chunk whose writes are steered harmless
    (contiguous: positions past ``max_length`` drop out of the scatter;
    paged: the all-null table lands them in the null block);
  * ``chunk_policy`` trades the two SLOs: ``"prefill"`` (default) runs a
    pending chunk every tick, ``"decode"`` interleaves chunks with
    chunk-free ticks while decodes are active;
  * paged composition: admission adopts cached prefix blocks (the cursor
    starts past them), chains grow per chunk, and full prompt blocks are
    trie-registered only AFTER the chunk writing them is dispatched —
    an unwritten block can never satisfy a prefix lookup.

Greedy outputs remain token-identical to the wave engine (and therefore
to ``greedy_generate``) — tests/test_serving.py staggered traces with a
long prompt arriving mid-decode assert it for both cache layouts.

**Speculative decoding** (``spec_decode=True`` / FLAGS_serving_spec_decode):
at b=1 the decode step already sits AT the bf16 weight-stream floor
(BENCH_DECODE.json, 1.0–1.07x of bound), so no kernel tuning helps — the
only lever left is amortising each pass of the weights over MORE than one
token.  Spec mode does that without a second model:

  * a host-side **self-drafter** (drafter.py: prompt-lookup / n-gram
    match over each slot's prompt+generated history, the vLLM ``ngram``
    speculator scheme) proposes up to ``spec_k`` (FLAGS_serving_spec_k)
    tokens per greedy slot per tick;
  * ONE once-jitted **verify step** feeds every row its (k+1)-token
    window ``[current, d_1..d_k]`` at its own depth — exactly the
    q-tiled mode the flash-decode kernel grew for chunked prefill, with
    per-row positions riding scalar-prefetch as always — so all drafts
    of all slots are scored in a single pass of the weights
    (``ops.kernel_path{op="spec_verify"}`` counts the routing);
  * ``accept_draft_tokens`` (models/generation.py) keeps each row's
    longest verified prefix plus the bonus token — 1..k+1 tokens
    committed per step, token-identical to plain greedy decode; sampled
    rows accept one token (exact distribution, no approximation);
  * **rollback** of a rejected suffix is bookkeeping, not device work:
    contiguous rows simply don't advance past the accept point (stale
    K/V above it is overwritten before any mask can read it), paged rows
    additionally return draft-only blocks to the pool via
    ``BlockManager.truncate_to`` (refcount/COW-safe, reservation
    re-credited, trie invalidated past the cut);
  * rows with no draft hit ride the SAME program as depth-1 decode (k is
    static; absent drafts are pad columns masked out of acceptance, with
    their junk writes steered exactly like idle rows' — past max_length
    contiguous, into the null block paged), so the retrace budget stays
    1 and the graph lint stays green in every layout.  Chunked prefill
    composes: the mixed step's decode half becomes the verify window
    while a prefilling slot — inactive by construction — drafts nothing
    until its cursor completes.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import hashlib
import itertools
import json
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import flags as _flags
from .. import observability as _obs
from ..models.generation import (_place_on_mesh, accept_draft_tokens,
                                 decode_mesh_specs, init_kv_cache,
                                 sample_tokens)
from ..nn.layer import bind_params
from ..ops import _dispatch as _disp
from .drafter import DraftModelDrafter, NgramDrafter
from .kv_cache import BlockManager, init_paged_kv_cache

__all__ = ["ServingEngine", "SamplingParams", "Request"]

# engine instances share the default registry; the ``engine`` label keeps
# their series (and retrace budgets) independent
_ENGINE_IDS = itertools.count()

# one compiled prefill program per power-of-two bucket (plus the paged
# suffix buckets) — generous static ceiling for the prefill trace budget
_PREFILL_TRACE_BUDGET = 16


def _slot_row(cache, cslot):
    """One slot's row of the contiguous cache — batch is axis 2 in every
    leaf, for the plain array and the int8 {kv, scale} pytree alike."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, cslot, 1, axis=2), cache)


def _slot_row_update(cache, row, cslot):
    z = jnp.int32(0)
    return jax.tree_util.tree_map(
        lambda a, r: jax.lax.dynamic_update_slice(
            a, r, (z, z, cslot) + (z,) * (a.ndim - 3)), cache, row)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs.  These become traced (num_slots,)
    vectors inside the step function, so any mixture across the batch
    reuses the one compiled program.  Conventions: ``temperature <= 0``
    ⇒ greedy; ``top_k == 0`` ⇒ no top-k; ``top_p == 1.0`` ⇒ no top-p."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0


class _Rejected(Exception):
    """Internal admission rejection: pairs the user-facing ValueError
    message with a stable machine-readable reason for the lifecycle
    log (``rejected`` event / ``slo_violations{kind="rejected"}``)."""

    def __init__(self, reason: str, msg: str):
        super().__init__(msg)
        self.reason = reason


@dataclasses.dataclass(eq=False)
class Request:
    """A queued generation request (created by ``submit``).  Identity
    equality (``eq=False``): scheduler queues remove entries by object
    identity, and the numpy ``prompt`` field has no scalar ``==``."""

    request_id: int
    prompt: np.ndarray                 # (plen,) int32
    max_new_tokens: int
    sampling: SamplingParams
    t_submit: float = 0.0              # perf_counter at submit (SLO clock)
    uid: int = -1                      # RequestLog correlation uid
    t_admit: float = 0.0               # perf_counter at admission
    ttft_slo_ms: float = 0.0           # deadlines recorded at submit;
    tpot_slo_ms: float = 0.0           # 0 = that deadline disabled
    blocked_ticks: int = 0             # pool-full admission deferrals
    defer_ticks: int = 0               # predictive-admission deferrals
    priority: int = 0                  # preemption class (higher wins)
    preempt_count: int = 0             # times this request was preempted
    # per-request drafter override (spec mode): 'ngram' | 'model' | a
    # Drafter instance | None = the engine default
    drafter: Optional[object] = None
    # recompute-resume marker: set ONLY on the synthetic re-prefill
    # request a recompute preemption enqueues (see _do_preempt)
    resume: Optional["_ResumeInfo"] = None


@dataclasses.dataclass
class _Slot:
    rid: int
    remaining: int                     # new tokens still allowed
    t_first: float = 0.0               # perf_counter at first token (TPOT)
    # the request's prompt — the self-drafter's lookup corpus (spec mode)
    prompt: Optional[np.ndarray] = None
    # the originating request — retirement reads its uid + SLO deadlines
    req: Optional[Request] = None


@dataclasses.dataclass
class _Prefill:
    """A partially-prefilled request (chunked mode): admitted to a slot,
    its prompt streaming into the cache one chunk per mixed step."""

    req: Request
    slot: int
    cursor: int                        # prompt tokens already in the cache


@dataclasses.dataclass
class _ResumeInfo:
    """Recompute-resume bookkeeping, attached to the synthetic request a
    recompute preemption enqueues: the re-prefill covers the original
    prompt plus every committed token but the last; at slot re-creation
    the re-sampled token is DISCARDED and ``last_token`` forced back, so
    the resumed decode continues exactly where the victim stopped."""

    orig: Request                      # the preempted request
    last_token: int                    # last committed token (forced back)
    remaining: int                     # decode budget left at preemption
    t_first: float                     # original TTFT clock (preserved)


@dataclasses.dataclass
class _SwapResume:
    """A swapped-out (preempted) request parked on the host tier: the
    BlockManager swap record plus the exact host-mirror state needed to
    restore the slot bit-for-bit once pool space frees up."""

    req: Request
    record: Dict[str, object]          # BlockManager.swap_out record
    last_token: int
    position: int
    remaining: int
    t_first: float
    blocked_ticks: int = 0             # failed resume attempts


class ServingEngine:
    """Continuous-batching serving over a causal LM with the stacked KV
    cache (``decode_step`` + ``init_kv_cache`` layout; plain or
    ``quantize_for_decode``-wrapped models both work).

    ``submit()`` enqueues, ``step()`` runs one scheduler tick (admit →
    one jitted decode step → retire), ``drain()`` runs ticks until every
    request is finished and returns outputs in arrival order.
    """

    def __init__(self, model, num_slots: int = 8, max_length: int = 1024,
                 eos_token_id: Optional[int] = None, pad_token_id: int = 0,
                 prefill_batch: int = 4, seed: int = 0,
                 paged: Optional[bool] = None,
                 block_len: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 chunked: Optional[bool] = None,
                 prefill_chunk: Optional[int] = None,
                 chunk_policy: Optional[str] = None,
                 spec_decode: Optional[bool] = None,
                 spec_k: Optional[int] = None,
                 kv_cache_dtype: Optional[str] = None,
                 int8_weights: Optional[bool] = None,
                 mesh=None,
                 preempt: Optional[str] = None,
                 host_blocks: Optional[int] = None,
                 drafter=None,
                 draft_model=None):
        """``paged`` (default FLAGS_serving_paged_kv) selects the paged
        block-pool cache; ``block_len`` (FLAGS_kv_cache_block_len) and
        ``num_blocks`` (FLAGS_kv_cache_num_blocks; 0 derives the
        contiguous cache's footprint, num_slots·max_length/block_len,
        plus the null block) size it; ``prefix_cache``
        (FLAGS_serving_prefix_cache) toggles prompt-prefix sharing.

        ``chunked`` (default FLAGS_serving_chunked_prefill) selects
        chunked-prefill admission: prompts are split into
        ``prefill_chunk``-token chunks (FLAGS_serving_prefill_chunk)
        folded into the ONE mixed decode step, so a long prompt never
        stalls in-flight decodes for a whole-prompt prefill;
        ``chunk_policy`` (FLAGS_serving_chunk_policy): 'prefill' runs a
        pending chunk every tick, 'decode' interleaves chunks with
        chunk-free ticks while decodes are active (TPOT protection at
        half the prompt-ingest rate).

        ``spec_decode`` (default FLAGS_serving_spec_decode) selects
        speculative decoding: a drafter proposes up to ``spec_k``
        (FLAGS_serving_spec_k) tokens per slot per tick and one verify
        step commits the longest accepted prefix — greedy outputs
        token-identical to plain decode, sampled rows exact under
        rejection sampling, 1..k+1 tokens per step.  Composes with
        every cache layout and with chunked prefill (the verify window
        replaces the mixed step's decode half).

        ``drafter`` (default FLAGS_serving_spec_drafter) picks the
        proposer: ``'ngram'`` (host-side prompt lookup), ``'model'``
        (a draft model sharing the engine — see ``draft_model``), or a
        :class:`~paddle_tpu.serving.drafter.Drafter` instance.
        ``draft_model``: the draft model for kind ``'model'`` — a
        ``(model, params)`` pair, a bare model (its own state_dict is
        taken), or ``None`` for self-drafting with the TARGET model
        (zero extra weights; the acceptance-rate ceiling).
        ``submit(drafter=...)`` overrides per request, so one engine
        can mix drafter kinds across its slot batch.

        ``mesh`` (default FLAGS_serving_mesh) makes the engine
        MESH-NATIVE — the tensor-parallel execution path of ROADMAP
        item 1: a jax ``Mesh``, a ``HybridCommunicateGroup``, or a
        compact axis string like ``"mp2dp2"`` (resolved over the first
        matching prefix of ``jax.devices()``).  Params and the KV cache
        are placed per :func:`decode_mesh_specs` at construction
        (vocab-parallel lm_head on ``mp``, cache kv-heads mp-sharded —
        the paged block pool shards ONLY the head dim, so block tables
        stay per-replica logical and the BlockManager is untouched),
        and every step/prefill program is jitted ONCE with DECLARED
        ``in_shardings``/``out_shardings`` and the cache still donated.
        The Pallas decode kernel is gated off under a mesh (the XLA
        gather path partitions under GSPMD; see
        ``ops.attention._mesh_sharded_trace``); greedy outputs stay
        token-identical to the single-chip engine in every layout.

        ``kv_cache_dtype`` (default FLAGS_serving_kv_cache_dtype):
        ``'bf16'`` keeps the model-dtype cache; ``'int8'`` stores K/V as
        int8 with per-block(-granule)-per-kv-head symmetric scales —
        quantized at scatter time inside the step, dequantized inside
        the flash-decode chunk loop — halving the cache footprint and
        the per-step streamed cache bytes; ``'mixed'`` (paged only)
        writes blocks bf16 and demotes them to simulated int8 (an
        in-place quantize→dequantize device rewrite) when they register
        as cold full prefix blocks.  ``int8_weights`` (default
        FLAGS_serving_int8_weights) wraps the model with
        ``quantize_for_decode`` so the engine's linear layers run the
        weight-only int8 path.  Both compose with every layout above;
        every program stays jitted once."""
        if hasattr(model, "init_decode_state"):
            raise NotImplementedError(
                "ServingEngine requires the stacked KV cache; recurrent "
                "decode states (Mamba/RWKV) are not slot-addressable yet")
        limit = getattr(model.config, "max_position_embeddings", None)
        if limit is not None and max_length > limit:
            raise ValueError(
                f"max_length {max_length} exceeds the model's "
                f"max_position_embeddings ({limit})")
        self._int8_weights = bool(
            _flags.flag("serving_int8_weights")
            if int8_weights is None else int8_weights)
        if self._int8_weights and not hasattr(model, "unwrapped"):
            from ..models.quantized import quantize_for_decode
            model = quantize_for_decode(model)
        self.model = model
        self.config = model.config
        self.num_slots = int(num_slots)
        self.max_length = int(max_length)
        self.eos_token_id = eos_token_id
        self.pad_token_id = int(pad_token_id)
        self.prefill_batch = int(prefill_batch)
        self.paged = bool(_flags.flag("serving_paged_kv")
                          if paged is None else paged)
        self.kv_dtype = str(kv_cache_dtype
                            or _flags.flag("serving_kv_cache_dtype"))
        if self.kv_dtype not in ("bf16", "int8", "mixed"):
            raise ValueError(
                f"kv_cache_dtype must be bf16|int8|mixed, got "
                f"{self.kv_dtype!r}")
        if self.kv_dtype == "mixed" and not self.paged:
            raise ValueError(
                "kv_cache_dtype='mixed' requires the paged cache: "
                "demotion is per-block, and contiguous rows have no "
                "block registration point")
        # 'int8' quantizes the DEVICE pool (dict cache, scales as step
        # operands); 'mixed' keeps the device pool bf16 and simulates
        # int8 per demoted block, so only 'int8' changes program shapes
        self.quantized = self.kv_dtype == "int8"
        self.chunked = bool(_flags.flag("serving_chunked_prefill")
                            if chunked is None else chunked)
        self.prefill_chunk = int(prefill_chunk
                                 or _flags.flag("serving_prefill_chunk"))
        self._chunk_policy = str(chunk_policy
                                 or _flags.flag("serving_chunk_policy"))
        if self._chunk_policy not in ("prefill", "decode"):
            raise ValueError(
                f"chunk_policy must be 'prefill' or 'decode', got "
                f"{self._chunk_policy!r}")
        if self.chunked and self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {self.prefill_chunk}")
        self.spec = bool(_flags.flag("serving_spec_decode")
                         if spec_decode is None else spec_decode)
        self.spec_k = int(spec_k or _flags.flag("serving_spec_k"))
        if self.spec and self.spec_k < 1:
            raise ValueError(
                f"spec_k must be >= 1, got {self.spec_k}")
        # drafter construction is deferred past param placement (the
        # draft-model drafter aliases the PLACED params for self-draft)
        self._drafter_arg = drafter
        self._draft_model_arg = draft_model
        self._drafters: Dict[str, object] = {}
        self._drafter = None
        # preemptive scheduling + host KV tier (ISSUE 16).  'swap'
        # parks a victim's private blocks on the pinned host pool and
        # restores them verbatim; 'recompute' frees the chain and
        # re-prefills prompt+committed tokens through the prefix trie.
        # Both are host-side pool surgery + block-table updates — the
        # once-jitted step never sees a new trace.
        self.preempt = str(_flags.flag("serving_preempt")
                           if preempt is None else preempt)
        if self.preempt not in ("off", "swap", "recompute"):
            raise ValueError(
                f"preempt must be off|swap|recompute, got "
                f"{self.preempt!r}")
        if self.preempt != "off" and not self.paged:
            raise ValueError(
                "preemption requires the paged cache: victim block free "
                "and swap/recompute resume are BlockManager operations")
        self._preempt_after = int(_flags.flag("serving_preempt_after"))
        hb = int(_flags.flag("serving_host_blocks")
                 if host_blocks is None else host_blocks)
        if self.preempt == "swap" and hb < 1:
            raise ValueError(
                "preempt='swap' needs a host tier: pass host_blocks "
                "(or FLAGS_serving_host_blocks) >= 1")
        self._host_blocks = hb if self.paged else 0
        self.mesh = self._resolve_mesh(mesh)
        self._init_metrics()

        # quantized-decode hooks, exactly as models/generation.py binds
        self._bind = getattr(model, "unwrapped", model)
        self._prepare = getattr(model, "_prepare_params", lambda p: p)
        params = model.state_dict(include_buffers=True)
        if self.paged:
            bl = int(block_len or _flags.flag("kv_cache_block_len"))
            if self.max_length % bl:
                raise ValueError(
                    f"max_length {self.max_length} is not a multiple of "
                    f"block_len {bl}")
            self.block_len = bl
            self.max_blocks = self.max_length // bl
            nb = int(num_blocks or _flags.flag("kv_cache_num_blocks")
                     or self.num_slots * self.max_blocks + 1)
            self.kv = BlockManager(
                nb, bl,
                prefix_cache=bool(_flags.flag("serving_prefix_cache")
                                  if prefix_cache is None else prefix_cache),
                kv_dtype=self.kv_dtype,
                host_blocks=self._host_blocks)
            cache = init_paged_kv_cache(model.config, nb, bl,
                                        quantized=self.quantized)
            # arm the pool's bytes_by_dtype gauges with this model's
            # per-block costs (payload + the int8 block's scale row)
            c = model.config
            tok = (c.num_hidden_layers * 2 * c.num_key_value_heads
                   * c.head_dim)
            native = jnp.zeros((), c.dtype).dtype.itemsize
            self.kv.set_block_nbytes({
                "bf16": tok * bl * native,
                "int8": tok * bl
                + c.num_hidden_layers * 2 * c.num_key_value_heads * 4})
            self._tables = np.zeros((self.num_slots, self.max_blocks),
                                    np.int32)
        else:
            cache = init_kv_cache(model.config, self.num_slots,
                                  self.max_length,
                                  quantized=self.quantized)
        params, cache, _ = _place_on_mesh(
            self._bind, params, cache,
            jnp.zeros((self.num_slots, 1), jnp.int32),
            paged_cache=self.paged, mesh=self.mesh)
        self._params, self._cache = params, cache
        if self.spec:
            sel = (self._drafter_arg if self._drafter_arg is not None
                   else str(_flags.flag("serving_spec_drafter")))
            self._drafter = self._make_drafter(sel)
            self._drafters[getattr(self._drafter, "kind", "custom")] = \
                self._drafter
        self._pending_demote: List[int] = []
        if self.paged:
            # COW device copy (compiled once; only dispatched when a
            # shared block is about to be written — see kv_cache.py).
            # The pool is donated: the copy aliases it in place.  Under
            # a mesh the pool keeps its declared sharding through the
            # copy (the block axis is unsharded, so a block copy never
            # crosses devices).  The int8 pool copies the block's scale
            # row along with its payload — COW destinations inherit the
            # source's live quantization scale.
            if self.quantized:
                def _cow_impl(c, src, dst):
                    return {
                        "kv": c["kv"].at[:, :, dst].set(c["kv"][:, :, src]),
                        "scale": c["scale"].at[:, :, dst].set(
                            c["scale"][:, :, src])}
            else:
                def _cow_impl(c, src, dst):
                    return c.at[:, :, dst].set(c[:, :, src])
            self._cow_fn = _obs.track_retraces(
                _cow_impl,
                "serving.cow", labels={"engine": self._eid},
                donate_argnums=(0,),
                **(self._mesh_jit_shardings(3, 1, cache_argnum=0,
                                            with_params=False)
                   if self.mesh is not None else {}))
        if self.paged and self.quantized:
            # a reused block carries its previous tenant's scale row; the
            # running-max write path would inherit it and quantize the
            # new tenant too coarsely, so every block newly appended to a
            # chain (BlockManager.drain_fresh) gets its scale zeroed
            # before the next dispatch.  Mask form: one static shape, one
            # compile, and the scale tensor is tiny.
            def _reset_impl(c, mask):
                return {"kv": c["kv"],
                        "scale": jnp.where(mask[None, None, :, None],
                                           jnp.float32(0), c["scale"])}
            self._scale_reset_fn = _obs.track_retraces(
                _reset_impl, "serving.scale_reset",
                labels={"engine": self._eid}, donate_argnums=(0,),
                **(self._mesh_jit_shardings(2, 1, cache_argnum=0,
                                            with_params=False)
                   if self.mesh is not None else {}))
        if not self.paged and self.quantized:
            # contiguous slot reuse (chunked admission writes into a row
            # a retired request used): zero the row's granule scales
            def _row_reset_impl(c, slot):
                return {"kv": c["kv"],
                        "scale": c["scale"].at[:, :, slot].set(0.0)}
            self._row_reset_fn = _obs.track_retraces(
                _row_reset_impl, "serving.scale_reset",
                labels={"engine": self._eid}, donate_argnums=(0,),
                **(self._mesh_jit_shardings(2, 1, cache_argnum=0,
                                            with_params=False)
                   if self.mesh is not None else {}))
        if self.paged and self.kv_dtype == "mixed":
            # mixed mode: the pool stays bf16 (plain array, plain step
            # programs) and a block demoted by the BlockManager — cold
            # full prefix block at trie registration — is rewritten
            # in place through a quantize→dequantize round trip
            # (simulated int8: the precision of the quantized store, the
            # layout of the hot path).  Applied AFTER the dispatch that
            # writes the block's contents (registration precedes the
            # wave-prefill dispatch), via the _pending_demote queue.
            def _demote_impl(c, bid):
                blk = c[:, :, bid].astype(jnp.float32)  # (L,2,bl,Hkv,D)
                sc = jnp.max(jnp.abs(blk), axis=(2, 4),
                             keepdims=True) / 127.0
                safe = jnp.where(sc > 0, sc, 1.0)
                q = jnp.clip(jnp.round(blk / safe), -127, 127)
                return c.at[:, :, bid].set((q * safe).astype(c.dtype))
            self._demote_fn = _obs.track_retraces(
                _demote_impl, "serving.demote",
                labels={"engine": self._eid}, donate_argnums=(0,),
                **(self._mesh_jit_shardings(2, 1, cache_argnum=0,
                                            with_params=False)
                   if self.mesh is not None else {}))
            self.kv.on_demote = self._pending_demote.extend
        self._tick_swap_bytes = 0      # host<->HBM bytes moved this tick
        if self.paged:
            # block movers are built on first use (_block_movers): the
            # host tier's swap hooks AND the ISSUE-18 export/import
            # migration path share them, but an engine that never swaps
            # or migrates must not spend two jit.traces counter children
            # on them (per-engine label cardinality is capped)
            self._read_block_fn = None
            self._write_block_fn = None
            if self._host_blocks > 0:
                self.kv.on_swap_out = self._host_swap_out
                self.kv.on_swap_in = self._host_swap_in

        # host-side mirrors of the step inputs (tiny; re-uploaded per tick)
        s = self.num_slots
        self._tokens = np.zeros((s,), np.int32)
        self._positions = np.zeros((s,), np.int32)
        self._active = np.zeros((s,), bool)
        self._temps = np.zeros((s,), np.float32)
        self._topk = np.zeros((s,), np.int32)
        self._topp = np.ones((s,), np.float32)

        self._slots: List[Optional[_Slot]] = [None] * s
        self._prefill: Optional[_Prefill] = None   # chunked-mode cursor
        self._queue: Deque[Request] = deque()
        # preempted work awaiting resume, each kept sorted by
        # (-priority, request id) so resume order is deterministic
        self._swap_resume: List[_SwapResume] = []
        self._resume_q: Deque[Request] = deque()
        # every preemption decision, in order — preempt_signature()
        # hashes this list, the loadgen saturated gate replays it
        self._preempt_log: List[Dict[str, object]] = []
        self._results: Dict[int, List[int]] = {}
        self._next_rid = 0
        self._base_key = jax.random.key(seed)
        self._ticks = 0
        # the scheduler's time source: every SLO stamp (t_submit,
        # queue-wait, TTFT, TPOT) reads through this indirection, so the
        # fleet simulator (serving/fleet_sim.py) can drive the SAME
        # scheduler with a cost-model clock instead of the wall
        self._clock = time.perf_counter
        self._kernel_preflight_cache = None  # memoized kernel_preflight()
        # trace accounting rides the retrace watchdog
        # (observability/watchdog.py): the wrapper counts compilations —
        # python side effects fire at TRACE time only — into the shared
        # registry and BUDGETS them; the step function's budget of 1 is
        # the continuous-batching contract itself, enforced at the
        # moment a retrace happens instead of asserted after the fact.
        # ``step_traces``/``prefill_traces`` read through to the counters.
        lbl = {"engine": self._eid}
        # every step/prefill program takes the FULL cache as operand 1
        # and returns it: donating that operand lets XLA alias the
        # buffers in place, so a tick keeps ONE cache resident instead
        # of double-buffering the dominant HBM consumer (the engine
        # rebinds self._cache from the output immediately, so the
        # donated input is never read again).  The graph-lint donation
        # rule (paddle_tpu/static_analysis) verifies this stays true.
        donate = {"donate_argnums": (1,)}
        # mesh mode: the SAME once-jitted programs, now with DECLARED
        # shardings — params/cache per decode_mesh_specs, every small
        # operand (token/position/mask vectors, block tables, the PRNG
        # key) replicated, tokens replicated on the way out and the
        # cache keeping its spec.  Declaring both sides keeps the
        # donated cache aliasable in place (in/out layouts provably
        # match) and makes the step's sharding contract the same one
        # mesh_preflight lints abstractly.
        n_out = 2 + int(self.chunked) + int(self.spec)
        step_kwargs = dict(donate)
        if self.mesh is not None:
            step_kwargs.update(self._mesh_jit_shardings(
                len(self._lint_args()), n_out))
        if self.chunked:
            # chunked mode: ONE program serves every tick — num_slots
            # decode rows plus one (possibly empty) prompt chunk, chunk
            # size static.  The budget of 1 IS the token-budget
            # scheduler's contract: admission, chunk progress and
            # retirement all move through traced inputs.  Spec mode
            # swaps the decode half for the (k+1)-deep verify window —
            # still one static-shape program.
            if self.spec:
                impl = (self._spec_mixed_step_impl_paged if self.paged
                        else self._spec_mixed_step_impl)
            else:
                impl = (self._mixed_step_impl_paged if self.paged
                        else self._mixed_step_impl)
            self._step_fn = _obs.track_retraces(
                self._under_mesh(impl), "serving.step", budget=1,
                labels=lbl, **step_kwargs)
            self._prefill_fn = None
        else:
            if self.spec:
                impl = (self._spec_step_impl_paged if self.paged
                        else self._spec_step_impl)
            else:
                impl = (self._step_impl_paged if self.paged
                        else self._step_impl)
            self._step_fn = _obs.track_retraces(
                self._under_mesh(impl), "serving.step", budget=1,
                labels=lbl, **step_kwargs)
            prefill_kwargs = dict(donate)
            if self.mesh is not None:
                prefill_kwargs.update(self._mesh_jit_shardings(
                    10 if self.paged else 9, 2))
            self._prefill_fn = _obs.track_retraces(
                self._under_mesh(self._prefill_impl_paged if self.paged
                                 else self._prefill_impl),
                "serving.prefill",
                budget=_PREFILL_TRACE_BUDGET, labels=lbl,
                **prefill_kwargs)
        self._linted = False           # first-tick self-lint (graph_lint)
        # per-tick roofline cost model (ISSUE 15): predictions are
        # memoized host math, so the steady-state tick pays a dict
        # lookup; FLAGS_perf_model 'off' skips the layer entirely
        self._perf = (self._build_perf_model()
                      if _flags.flag("perf_model") == "on" else None)

    # -- cost model / perf attribution (ISSUE 15) --------------------------

    def _build_perf_model(self):
        """Compose the existing static models into the tick roofline:
        the params tree's actual bytes (int8 weights shrink the weight-
        stream term), the pool's dtype-aware per-token KV cost (the
        committed 0.254x int8 streamed-bytes ratio), and — under a mesh
        — comm_report's per-step collective bytes, evaluated lazily
        (one abstract trace) on the first prediction."""
        from ..observability import costmodel as _cm
        leaves = jax.tree_util.tree_leaves(self._params)
        weight_bytes = int(sum(leaf.nbytes for leaf in leaves))
        n_params = int(sum(leaf.size for leaf in leaves))
        # int8 scale amortization granule: the paged pool keeps one
        # scale row per block, the contiguous pool one per 128-token
        # granule (models/generation.init_kv_cache)
        kv_tok = _cm.kv_bytes_per_token(
            self.config, self.kv_dtype,
            block_len=self.block_len if self.paged else 128)
        comm_fn = None
        if self.mesh is not None:
            def comm_fn():
                comm = self.mesh_preflight()["comm"]
                return int(comm.get("total_bytes_per_step", 0))
        model = _cm.CostModel(
            _cm.resolve_profile(), weight_bytes=weight_bytes,
            n_params=n_params, kv_token_bytes=kv_tok,
            num_slots=self.num_slots, comm_bytes_fn=comm_fn)
        return _cm.TickAttribution(model, engine_id=self._eid)

    def _perf_tick(self, measured_ms: float, occ: int,
                   chunk_tokens: int = 0) -> None:
        """Stamp one measured tick with the model's prediction at the
        tick's ACTUAL occupancy / live depths / chunk state (positions
        are still pre-advance here — the depths the step just read).
        Host↔HBM bytes any swap/demotion moved since the last dispatch
        ride along — the roofline's swap term (costmodel.py) bounds the
        tick by host-link bandwidth when they dominate."""
        swap_bytes, self._tick_swap_bytes = self._tick_swap_bytes, 0
        if self._perf is None:
            return
        live = int(self._positions[self._active].sum()) if occ else 0
        self._perf.on_tick(
            measured_ms, occ=occ, live_tokens=live,
            chunk_tokens=chunk_tokens,
            window=self.spec_k + 1 if self.spec else 1,
            swap_bytes=swap_bytes)

    def perf_report(self) -> Dict[str, object]:
        """Predicted-vs-measured attribution for this engine: per-bound
        tick shares, per-term predicted totals, measured/predicted
        ratio percentiles, drift findings (static_analysis Finding
        shape) and anomaly counts.  The predicted side is a pure
        function of the deterministic schedule — loadgen's smoke gate
        checks it byte-stable across replays via
        observability.perf_signature."""
        if self._perf is None:
            return {"enabled": False}
        return dict(self._perf.report(), enabled=True)

    # -- predictive SLO admission (control plane) --------------------------

    def admission_armed(self) -> bool:
        """True when the predictive gate actively prices admissions on
        this engine: FLAGS_serving_admission is 'predictive', the cost
        model is built (FLAGS_perf_model on), and the model carries no
        drift finding — a model that has left its calibrated band must
        not gate admission (ISSUE 17: fall back conservative)."""
        return (self._perf is not None
                and str(_flags.flag("serving_admission")) == "predictive"
                and not self._perf.has_drift())

    def admission_probe(self, prompt_len: int) -> Optional[Dict[str, float]]:
        """Price admitting ONE more request at this engine's current
        (occupancy, queue depth, chunk backlog) — the control-plane
        placement question the router asks before placing.  Returns the
        predicted post-admission tick time (which is the per-slot TPOT:
        decode emits one token per tick) and a coarse TTFT estimate
        (ticks to drain the backlog ahead, one admission wave per tick,
        times the predicted tick), or None when FLAGS_perf_model is off.
        Predictions are in the cost model's domain — compare against
        wall deadlines through FLAGS_serving_admission_calib."""
        if self._perf is None:
            return None
        occ_now = self.num_active
        backlog = self.queue_depth + self.num_pending + self.num_preempted
        occ_after = min(self.num_slots, occ_now + backlog + 1)
        live = int(self._positions[self._active].sum()) if occ_now else 0
        chunk = (getattr(self, "prefill_chunk", 0)
                 if self.chunked and (backlog or self._prefill is not None)
                 else 0)
        pred = self._perf.model.predicted_tick_ms(
            occ_after, live + int(prompt_len), chunk_tokens=chunk,
            window=self.spec_k + 1 if self.spec else 1)
        waves = 1 + backlog // max(1, self.prefill_batch)
        return {"predicted_tick_ms": pred,
                "predicted_tpot_ms": pred,
                "predicted_ttft_ms": pred * waves,
                "occupancy_after": float(occ_after),
                "backlog": float(backlog)}

    def _admission_defer(self, req: Request, occ_after: int,
                         live_after: int, chunk_tokens: int = 0) -> bool:
        """The gate itself: True holds ``req`` in the submit queue this
        tick.  Pure function of scheduler state (occupancy, live depth,
        SLO fields, defer age) — NO wall-clock input, so twin replays of
        one trace make byte-identical decisions.  Never defers into an
        empty engine (progress guarantee), never defers a recompute
        resume (its admission was already paid before preemption), and
        ages out after FLAGS_serving_admission_max_defer_ticks."""
        if req.resume is not None or not self.admission_armed():
            return False
        if occ_after <= 1:
            return False
        maxd = int(_flags.flag("serving_admission_max_defer_ticks"))
        if maxd > 0 and req.defer_ticks >= maxd:
            return False
        # the pooled guard: the tightest TPOT deadline among running
        # slots and the candidate itself — admitting a deadline-free
        # batch request must not blow a resident interactive SLO
        guards = [s.req.tpot_slo_ms for s in self._slots
                  if s is not None and s.req is not None
                  and s.req.tpot_slo_ms > 0]
        if req.tpot_slo_ms > 0:
            guards.append(req.tpot_slo_ms)
        if not guards:
            return False
        pred = self._perf.model.predicted_tick_ms(
            occ_after, live_after, chunk_tokens=chunk_tokens,
            window=self.spec_k + 1 if self.spec else 1)
        calib = float(_flags.flag("serving_admission_calib"))
        slack = float(_flags.flag("serving_admission_slack"))
        return pred * calib > min(guards) * slack

    def _defer(self, req: Request) -> None:
        """Account one predictive deferral: the submit queue IS the
        engine-level hold queue (head-of-line order preserved), the
        request just does not enter a slot this tick."""
        req.defer_ticks += 1
        self._m_deferred.inc()
        self._tracer.instant("serving.admission_deferred",
                             rid=req.request_id)
        if req.defer_ticks == 1:
            self._rlog.event(req.uid, "admission_deferred",
                             engine=self._eid, reason="predicted_slo")

    # -- mesh execution (ISSUE 9) ------------------------------------------

    @staticmethod
    def _resolve_mesh(mesh):
        """Normalise the ``mesh`` constructor argument to a concrete jax
        ``Mesh`` or ``None`` (single-chip): ``None`` consults
        FLAGS_serving_mesh; a ``HybridCommunicateGroup`` contributes its
        mesh; a compact axis string like ``"mp2dp2"`` is laid over the
        first matching prefix of ``jax.devices()``.  An all-ones mesh
        collapses to ``None`` — placement would be a no-op."""
        if mesh is None:
            mesh = str(_flags.flag("serving_mesh"))
        if mesh is None or mesh == "":
            return None
        m = getattr(mesh, "mesh", mesh)        # HybridCommunicateGroup
        if isinstance(m, str):
            from jax.sharding import Mesh

            from ..static_analysis import MeshInfo
            minfo = MeshInfo.of(m)
            shape = tuple(n for _, n in minfo.axes)
            need = int(np.prod(shape))
            devs = jax.devices()
            if need > len(devs):
                raise ValueError(
                    f"mesh {m!r} needs {need} devices; only "
                    f"{len(devs)} available on this host")
            m = Mesh(np.asarray(devs[:need]).reshape(shape), minfo.names)
        if all(m.shape[a] == 1 for a in m.axis_names):
            return None
        return m

    def _make_drafter(self, sel):
        """Build a drafter from a selector: a Drafter instance passes
        through; ``'ngram'``/``'model'`` build the corresponding
        proposer (the model drafter aliases the engine's placed params
        when no ``draft_model`` was given — self-drafting)."""
        if not isinstance(sel, str):
            return sel
        if sel == "ngram":
            return NgramDrafter(
                self.spec_k,
                max_ngram=int(_flags.flag("serving_spec_ngram")))
        if sel == "model":
            src = self._draft_model_arg
            if src is None:
                dm, dp = self.model, self._params
            elif isinstance(src, (tuple, list)):
                dm, dp = src
            else:
                dm, dp = src, src.state_dict(include_buffers=True)
            return DraftModelDrafter(
                self.spec_k, dm, dp, self.num_slots, self.max_length,
                pad_token_id=self.pad_token_id, mesh=self.mesh,
                engine_id=self._eid)
        raise ValueError(
            f"drafter must be 'ngram', 'model' or a Drafter instance, "
            f"got {sel!r}")

    def _drafter_for(self, sel):
        """Resolve a request's drafter override (``None`` = the engine
        default); string kinds are built once and shared."""
        if sel is None:
            return self._drafter
        if isinstance(sel, str):
            d = self._drafters.get(sel)
            if d is None:
                d = self._drafters[sel] = self._make_drafter(sel)
            return d
        return sel

    def _drafter_reset(self, i: int):
        """Slot (re)assignment/teardown: clear per-slot drafter state
        (the draft model's consumed-history counter)."""
        if not self.spec:
            return
        seen = []
        for d in [self._drafter] + list(self._drafters.values()):
            if d is not None and d not in seen:
                seen.append(d)
                rs = getattr(d, "reset_slot", None)
                if rs is not None:
                    rs(i)

    def _under_mesh(self, impl):
        """Trace-time mesh scope for a step/prefill body: the model's
        internal sharding constraints (``mp_layers.constrain``) and the
        shard_map vocab lookup resolve against ``env.active_mesh()``, so
        a mesh given only to THIS engine must be installed around the
        trace — python bodies run at trace time only, so this costs
        nothing per call.  Single-chip engines pass through untouched."""
        if self.mesh is None:
            return impl
        from ..distributed import env as _denv

        @functools.wraps(impl)
        def traced_under_mesh(*args):
            with _denv.use_mesh(self.mesh):
                return impl(*args)
        return traced_under_mesh

    def _mesh_jit_shardings(self, n_args, n_out, cache_argnum=1,
                            with_params=True):
        """The DECLARED jit shardings of a mesh engine's program: params
        and cache per :func:`decode_mesh_specs`, every other operand
        replicated (token/position/mask vectors, block tables and chunk
        scalars are tiny and every device needs them whole), sampled
        tokens replicated on the way out with the cache keeping its
        spec (the trailing output by convention; ``n_out == 1`` is the
        cache-only COW copy)."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        param_specs, cache_spec, _ = decode_mesh_specs(
            self._bind, self._params, self.mesh.axis_names,
            paged_cache=self.paged, quantized_cache=self.quantized)

        def ns(spec):
            return NamedSharding(self.mesh, spec)

        def ns_cache(spec):
            # int8 cache spec is a {kv, scale} pytree of PartitionSpecs
            # (tuple subclasses — tree_map must not descend into them)
            return jax.tree_util.tree_map(
                ns, spec, is_leaf=lambda x: isinstance(x, P))

        repl = ns(P())
        in_sh = [repl] * n_args
        in_sh[cache_argnum] = ns_cache(cache_spec)
        if with_params:
            in_sh[0] = jax.tree_util.tree_map(ns, param_specs)
        if n_out == 1:
            out_sh = ns_cache(cache_spec)
        else:
            out_sh = tuple([repl] * (n_out - 1) + [ns_cache(cache_spec)])
        return {"in_shardings": tuple(in_sh), "out_shardings": out_sh}

    def _init_metrics(self):
        """Declare this engine's series in the shared registry (metric
        name conventions: README "Observability").  One ``engine=<id>``
        label keeps concurrent engines' series and retrace budgets
        independent; every hot-path update below is O(1) host work."""
        reg = _obs.default_registry()
        self._eid = str(next(_ENGINE_IDS))
        self._tracer = _obs.get_tracer()
        self._rlog = _obs.get_request_log()
        self._uids: Dict[int, int] = {}    # engine rid -> lifecycle uid
        lbl = {"engine": self._eid}
        hist, ctr, gauge = reg.histogram, reg.counter, reg.gauge
        self._m_queue_wait = hist(
            "serving.queue_wait_ms",
            "submit → admission wait per request").labels(**lbl)
        self._m_ttft = hist(
            "serving.ttft_ms",
            "time to first token: submit → first sampled token "
            "fetched").labels(**lbl)
        self._m_tpot = hist(
            "serving.tpot_ms",
            "per-token decode latency per finished request: "
            "(t_last - t_first) / (tokens - 1)").labels(**lbl)
        self._m_step_ms = hist(
            "serving.decode_step_ms",
            "wall time of one jitted decode step incl. the (num_slots,) "
            "token fetch").labels(**lbl)
        self._m_active = gauge(
            "serving.active_slots",
            "busy slots at the last scheduler tick").labels(**lbl)
        self._m_occ = gauge(
            "serving.slot_occupancy",
            "active_slots / num_slots at the last tick").labels(**lbl)
        self._m_submitted = ctr(
            "serving.requests_submitted", "submit() calls").labels(**lbl)
        self._m_finished = ctr(
            "serving.requests_finished",
            "requests retired (all reasons)").labels(**lbl)
        self._f_retired = ctr(
            "serving.retired",
            "retirements by reason: eos | max_new_tokens | max_length")
        self._f_slo_viol = ctr(
            "serving.slo_violations",
            "requests that missed their recorded TTFT/TPOT deadline, by "
            "attributed cause: rejected (admission refused) | queue_wait "
            "| prefill (missed TTFT, split by larger segment) | decode "
            "(missed TPOT); BASELINE.md 'SLO accounting conventions'")
        self._m_tokens = ctr(
            "serving.tokens_generated",
            "sampled tokens returned to requests (prefill first tokens "
            "included)").labels(**lbl)
        self._f_bucket = ctr(
            "serving.prefill_bucket",
            "admission waves per padded prefill bucket length (paged: "
            "suffix bucket)")
        self._m_waves = ctr(
            "serving.prefill_waves", "batched prefill waves").labels(**lbl)
        self._m_blocked = ctr(
            "serving.admission_blocked",
            "admission attempts deferred because the paged pool could "
            "not cover the request yet").labels(**lbl)
        self._m_deferred = ctr(
            "serving.admission_deferred",
            "admission attempts held back by the predictive SLO gate "
            "(serving_admission='predictive'): the cost model priced "
            "the post-admission tick over the pooled TPOT deadline")\
            .labels(**lbl)
        self._m_prefill_computed = ctr(
            "serving.prefill_tokens_computed",
            "prompt tokens actually prefilled (pads excluded; prefix "
            "hits skip these)").labels(**lbl)
        self._m_prefill_total = ctr(
            "serving.prefill_tokens_total",
            "prompt tokens submitted across admitted requests").labels(
                **lbl)
        self._m_chunks = ctr(
            "serving.prefill_chunks",
            "prompt chunks folded into mixed steps (chunked "
            "admission)").labels(**lbl)
        self._m_chunk_tokens = ctr(
            "serving.prefill_chunk_tokens",
            "real prompt tokens carried by mixed-step chunks (chunk "
            "padding excluded)").labels(**lbl)
        self._m_chunk_queue = hist(
            "serving.chunk_queue_depth",
            "pending prefill chunks at each scheduler tick: the active "
            "prompt's remaining chunks plus every queued prompt's",
            buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512)).labels(
                **lbl)
        # speculative decoding (serving.spec* conventions: BASELINE.md) —
        # accounting is in COMMITTED tokens; drafted/rejected tokens
        # never reach serving.tokens_generated or any tok/s number.
        # Every spec series carries a ``drafter=`` label (kind of the
        # proposer that drafted the row — per-request overrides can mix
        # kinds in one engine); labeled children are built lazily per
        # kind via _spec_m.
        self._f_drafted = ctr(
            "serving.spec_drafted_tokens",
            "draft tokens the drafter proposed (sent to verification)")
        self._f_draft_hits = ctr(
            "serving.spec_draft_hit_tokens",
            "proposed draft tokens verified AND committed")
        self._f_draft_miss = ctr(
            "serving.spec_draft_miss_tokens",
            "proposed draft tokens rejected by verification (rolled "
            "back)")
        self._f_rollbacks = ctr(
            "serving.spec_rollbacks",
            "row-steps whose rejected draft suffix was rolled back "
            "(position pinned at the accept point; paged: draft-only "
            "blocks returned via truncate_to)")
        self._f_spec_accept = hist(
            "serving.spec_accepted_per_step",
            "tokens committed per active slot per verify step (1 = no "
            "speculative win that step; k+1 = whole window accepted)",
            buckets=(1, 2, 3, 4, 5, 6, 7, 8, 16))
        # engine-total children (the pre-drafter-label series, kept for
        # dashboards and the metrics() rollup) + lazily-built per-kind
        # children carrying the drafter= label
        self._m_drafted = self._f_drafted.labels(**lbl)
        self._m_draft_hits = self._f_draft_hits.labels(**lbl)
        self._m_draft_miss = self._f_draft_miss.labels(**lbl)
        self._m_rollbacks = self._f_rollbacks.labels(**lbl)
        self._m_spec_accept = self._f_spec_accept.labels(**lbl)
        self._spec_children: Dict[str, tuple] = {}
        # int8 KV cache (quantization accounting conventions: BASELINE.md)
        self._m_demoted = ctr(
            "serving.kv_demoted_blocks",
            "mixed-mode blocks rewritten to simulated int8 at trie "
            "registration").labels(**lbl)
        self._m_dequant_err = hist(
            "serving.kv_dequant_error",
            "max |logit(bf16) - logit(int8-KV)| observed by a parity "
            "oracle (tests / bench feed this; the engine never computes "
            "it on the hot path)",
            buckets=(1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 1e-1, 3e-1,
                     1.0)).labels(**lbl)
        self._m_step_traces = ctr(
            "jit.traces", "").labels(site="serving.step", **lbl)
        self._m_prefill_traces = ctr(
            "jit.traces", "").labels(site="serving.prefill", **lbl)
        # preemptive scheduling + host KV tier (ISSUE 16; BASELINE.md
        # "Preemption accounting conventions": swap bytes are pool
        # traffic, NEVER streamed-KV bytes)
        self._f_preempt = ctr(
            "serving.preemptions",
            "running slots evicted at blocked admission, by resume "
            "mode: swap (chain parked on the host tier) | recompute "
            "(chain freed, re-prefilled through the prefix trie)")
        self._f_resumed = ctr(
            "serving.resumes",
            "preempted requests restored to a slot, by mode")
        self._m_swap_out_bytes = ctr(
            "serving.swap_out_bytes",
            "HBM→host bytes moved by swap-outs and trie demotions "
            "(pool traffic, not streamed KV bytes)").labels(**lbl)
        self._m_swap_in_bytes = ctr(
            "serving.swap_in_bytes",
            "host→HBM bytes moved by swap-ins and trie "
            "promotions").labels(**lbl)
        self._m_cancelled = ctr(
            "serving.cancelled",
            "cancel() calls that found and tore down a live "
            "request").labels(**lbl)
        # cross-worker KV migration (ISSUE 18; BASELINE.md "Multi-host
        # accounting conventions": migration bytes are pool traffic over
        # the transport, NEVER streamed-KV bytes and NEVER swap bytes)
        self._m_mig_out = ctr(
            "migration.requests_out",
            "requests exported for cross-worker migration").labels(**lbl)
        self._m_mig_in = ctr(
            "migration.requests_in",
            "migration records imported into this engine").labels(**lbl)
        self._m_mig_bytes_out = ctr(
            "migration.bytes_out",
            "KV payload bytes serialized out by export_request "
            "(block payloads + scale rows)").labels(**lbl)
        self._m_mig_bytes_in = ctr(
            "migration.bytes_in",
            "KV payload bytes written into the pool by "
            "import_request").labels(**lbl)

    def _spec_m(self, kind: str):
        """The drafter-labeled spec-series children for one drafter
        kind: (drafted, hits, miss, rollbacks, accept_hist).  Built
        lazily — kinds are a tiny closed set (ngram/model/custom), so
        cardinality stays bounded."""
        m = self._spec_children.get(kind)
        if m is None:
            lbl = {"engine": self._eid, "drafter": kind}
            m = self._spec_children[kind] = (
                self._f_drafted.labels(**lbl),
                self._f_draft_hits.labels(**lbl),
                self._f_draft_miss.labels(**lbl),
                self._f_rollbacks.labels(**lbl),
                self._f_spec_accept.labels(**lbl))
        return m

    # -- jitted device programs -------------------------------------------

    def _step_impl(self, params, cache, tokens, positions, slot_mask,
                   temps, topk, topp, key):
        """One decode step for ALL slots: row i holds request state at
        position ``positions[i]``.  Compiled exactly once."""
        with bind_params(self._bind, self._prepare(params)):
            logits, cache = self.model.decode_step(
                tokens[:, None], cache, positions)
        nxt = sample_tokens(logits[:, -1], key, temps, topk, topp)
        nxt = jnp.where(slot_mask, nxt, jnp.int32(self.pad_token_id))
        return nxt, cache

    def _prefill_impl(self, params, cache, ids, plens, slot_ids,
                      temps, topk, topp, key):
        """Batched prefill of one admission wave: run the prompts through
        the static-``pos=0`` path (flash-eligible) on a fresh
        ``prefill_batch``-row cache, sample each row's first token from
        the logits at its LAST REAL position, then scatter the finished
        cache rows into their slots.  Dummy rows carry ``slot_id ==
        num_slots``; the ``mode="drop"`` scatter discards them.  One
        compilation per padded prompt-bucket length."""
        nb = ids.shape[0]
        sub = init_kv_cache(self.config, nb, self.max_length,
                            quantized=self.quantized)
        with bind_params(self._bind, self._prepare(params)):
            logits, sub = self.model.decode_step(ids, sub, 0)
        last = logits[jnp.arange(nb), plens - 1]           # (nb, vocab)
        tok = sample_tokens(last, key, temps, topk, topp)
        # leaf-wise slot scatter (the int8 cache is a {kv, scale} pytree
        # with batch at axis 2 in both leaves; the fresh sub-cache's zero
        # scales reset the reused rows' quantization state for free)
        cache = jax.tree_util.tree_map(
            lambda c, s: c.at[:, :, slot_ids].set(s, mode="drop"),
            cache, sub)
        return tok, cache

    def _step_impl_paged(self, params, cache, tokens, positions, tables,
                         slot_mask, temps, topk, topp, key):
        """Paged twin of ``_step_impl``: identical but the block table
        rides along as a traced input, so allocation changes (slots
        deepening into fresh blocks, prefix adoptions, evictions) reach
        the device as data.  Compiled exactly once."""
        with bind_params(self._bind, self._prepare(params)):
            logits, cache = self.model.decode_step(
                tokens[:, None], cache, positions, block_tables=tables)
        nxt = sample_tokens(logits[:, -1], key, temps, topk, topp)
        nxt = jnp.where(slot_mask, nxt, jnp.int32(self.pad_token_id))
        return nxt, cache

    def _prefill_impl_paged(self, params, cache, ids, prefix_lens,
                            suffix_lens, tables, temps, topk, topp, key):
        """Paged prefill of one admission wave: each row computes ONLY
        its prompt suffix — the tokens its prefix-cache match did not
        cover — as a decode-at-depth over the pool (per-row ``pos`` =
        adopted prefix length; the adopted blocks are read, not
        recomputed).  Writes scatter straight into the rows' own blocks
        (kv_cache.py's null-block convention absorbs bucket padding, and
        rows admitted in the same wave see each other's writes because
        every layer's scatter precedes its attention read).  The first
        token samples from the logits at each row's last REAL suffix
        position.  One compilation per padded suffix-bucket length."""
        nb = ids.shape[0]
        with bind_params(self._bind, self._prepare(params)):
            logits, cache = self.model.decode_step(
                ids, cache, prefix_lens, block_tables=tables)
        last = logits[jnp.arange(nb), suffix_lens - 1]     # (nb, vocab)
        tok = sample_tokens(last, key, temps, topk, topp)
        return tok, cache

    def _mixed_step_impl(self, params, cache, tokens, positions, slot_mask,
                         temps, topk, topp, cids, cpos, clen, cslot,
                         ctemp, ctopk, ctopp, key):
        """One MIXED step (chunked mode, contiguous cache): the decode
        rows advance one token each AND one prompt chunk streams into its
        slot's cache row — a single program, compiled exactly once, whose
        token budget is ``num_slots + prefill_chunk`` every tick.

        Decode part: identical math to ``_step_impl``, but the host
        steers every NON-decoding row's position to ``max_length`` so its
        K/V scatter drops out of bounds instead of clobbering a row that
        chunked prefill is mid-way through writing (the wave engine could
        write junk at position 0 of idle rows because wave prefill
        rebuilt the whole row afterwards; chunked prefill builds the row
        incrementally, so idle writes must be dropped, not absorbed).

        Chunk part: decode-at-depth of ``cids`` (one (1, chunk) row,
        chunk size static) over the ``cslot`` cache row pulled out with a
        dynamic slice and scattered back — per-row positions
        ``cpos..cpos+chunk-1``, so pad-tail writes past the prompt land
        at positions decode will overwrite before the mask can read them
        (the wave-prefill padding argument), and a chunk-free tick rides
        the same program with ``cpos = max_length`` (every write drops,
        the row round-trips bit-identical).  The sampled ``ctok`` is the
        request's FIRST token when this chunk completes the prompt; the
        host discards it otherwise."""
        prep = self._prepare(params)
        with bind_params(self._bind, prep):
            logits, cache = self.model.decode_step(
                tokens[:, None], cache, positions)
        nxt = sample_tokens(logits[:, -1], key, temps, topk, topp)
        nxt = jnp.where(slot_mask, nxt, jnp.int32(self.pad_token_id))
        row = _slot_row(cache, cslot)
        with bind_params(self._bind, prep):
            clogits, row = self.model.decode_step(
                cids, row, cpos[None])          # (1,) per-row position
        ctok = sample_tokens(clogits[0, clen - 1][None],
                             jax.random.fold_in(key, 1),
                             ctemp, ctopk, ctopp)[0]
        cache = _slot_row_update(cache, row, cslot)
        return nxt, ctok, cache

    def _mixed_step_impl_paged(self, params, cache, tokens, positions,
                               tables, slot_mask, temps, topk, topp,
                               cids, cpos, clen, ctable,
                               ctemp, ctopk, ctopp, key):
        """Paged twin of ``_mixed_step_impl``: the chunk writes scatter
        straight into the slot's blocks through its own (1, max_blocks)
        table row (the decode part sees the prefilling slot as an
        all-null-table row, so its idle write lands in the null block),
        and a chunk-free tick passes the all-null table itself.  No
        row slicing — the pool IS the cache for both parts."""
        prep = self._prepare(params)
        with bind_params(self._bind, prep):
            logits, cache = self.model.decode_step(
                tokens[:, None], cache, positions, block_tables=tables)
        nxt = sample_tokens(logits[:, -1], key, temps, topk, topp)
        nxt = jnp.where(slot_mask, nxt, jnp.int32(self.pad_token_id))
        with bind_params(self._bind, prep):
            clogits, cache = self.model.decode_step(
                cids, cache, cpos[None], block_tables=ctable)
        ctok = sample_tokens(clogits[0, clen - 1][None],
                             jax.random.fold_in(key, 1),
                             ctemp, ctopk, ctopp)[0]
        return nxt, ctok, cache

    # -- jitted device programs: speculative decoding ----------------------

    def _verify_window(self, params, cache, tokens, positions, draft_ok,
                       draft_probs, temps, topk, topp, key,
                       block_tables=None):
        """The shared verify core of every spec step: score each row's
        (k+1)-token window ``[current, d_1..d_k]`` at its own depth in
        ONE forward — q-depth k+1 rides the q-tiled flash-decode path,
        per-row positions as scalar-prefetch, so all drafts of all slots
        cost a single pass of the weights — then keep each row's longest
        verified prefix plus the bonus token (models/generation.py
        ``accept_draft_tokens``).  ``draft_probs`` is the (s, k, vocab)
        proposal-distribution stack q: greedy rows keep the exact
        prefix-match rule, sampled rows run the rejection-sampling
        acceptance against q (one-hot for deterministic proposers,
        the draft model's softmax otherwise) so every committed token
        is distributed exactly as plain sampling.  The
        kernel_path_hint relabels this trace's dispatch counts as
        ``op="spec_verify"``."""
        with bind_params(self._bind, self._prepare(params)):
            with _disp.kernel_path_hint("spec_verify"):
                logits, cache = self.model.decode_step(
                    tokens, cache, positions, block_tables=block_tables)
        out, n_acc = accept_draft_tokens(
            logits, tokens[:, 1:], draft_ok, key, temps, topk, topp,
            pad_token_id=self.pad_token_id, draft_probs=draft_probs)
        return out, n_acc, cache

    def _spec_step_impl(self, params, cache, tokens, positions, slot_mask,
                        draft_ok, draft_probs, temps, topk, topp, key):
        """Speculative twin of ``_step_impl``: ``tokens`` is the
        (num_slots, k+1) window matrix (pad columns where the drafter
        had nothing), ``draft_ok`` the (num_slots, k) real-proposal
        mask.  Row i writes K/V at ``positions[i]..positions[i]+k`` —
        the host commits only the accepted prefix and never advances
        past it, so rejected-suffix writes are dead cells the next steps
        overwrite before any mask can read them (the same stale-tail
        argument plain decode already relies on).  Compiled exactly
        once; a draft-free tick is the same program with all-pad
        windows."""
        out, n_acc, cache = self._verify_window(
            params, cache, tokens, positions, draft_ok, draft_probs,
            temps, topk, topp, key)
        out = jnp.where(slot_mask[:, None], out,
                        jnp.int32(self.pad_token_id))
        return out, n_acc, cache

    def _spec_step_impl_paged(self, params, cache, tokens, positions,
                              tables, slot_mask, draft_ok, draft_probs,
                              temps, topk, topp, key):
        """Paged twin of ``_spec_step_impl``: the block table rides
        along; the host pre-grows each row's chain over its REAL draft
        span (and COW-privatises it), while pad-column writes past the
        chain steer to the null block — so a row near its reservation
        ceiling never allocates for drafts it didn't propose."""
        out, n_acc, cache = self._verify_window(
            params, cache, tokens, positions, draft_ok, draft_probs,
            temps, topk, topp, key, block_tables=tables)
        out = jnp.where(slot_mask[:, None], out,
                        jnp.int32(self.pad_token_id))
        return out, n_acc, cache

    def _spec_mixed_step_impl(self, params, cache, tokens, positions,
                              slot_mask, draft_ok, draft_probs, temps,
                              topk, topp, cids, cpos, clen, cslot,
                              ctemp, ctopk, ctopp, key):
        """Chunked × speculative (contiguous): ``_mixed_step_impl`` with
        the decode half replaced by the verify window.  The chunk half
        is untouched — a prefilling slot is inactive (its spec window
        suspended) until its cursor completes, so the two halves never
        touch the same row."""
        out, n_acc, cache = self._verify_window(
            params, cache, tokens, positions, draft_ok, draft_probs,
            temps, topk, topp, key)
        out = jnp.where(slot_mask[:, None], out,
                        jnp.int32(self.pad_token_id))
        row = _slot_row(cache, cslot)
        with bind_params(self._bind, self._prepare(params)):
            clogits, row = self.model.decode_step(cids, row, cpos[None])
        ctok = sample_tokens(clogits[0, clen - 1][None],
                             jax.random.fold_in(key, 1),
                             ctemp, ctopk, ctopp)[0]
        cache = _slot_row_update(cache, row, cslot)
        return out, n_acc, ctok, cache

    def _spec_mixed_step_impl_paged(self, params, cache, tokens,
                                    positions, tables, slot_mask,
                                    draft_ok, draft_probs, temps, topk,
                                    topp, cids, cpos, clen, ctable,
                                    ctemp, ctopk, ctopp, key):
        """Chunked × speculative (paged): verify window over the pool,
        then the chunk half exactly as ``_mixed_step_impl_paged``."""
        out, n_acc, cache = self._verify_window(
            params, cache, tokens, positions, draft_ok, draft_probs,
            temps, topk, topp, key, block_tables=tables)
        out = jnp.where(slot_mask[:, None], out,
                        jnp.int32(self.pad_token_id))
        with bind_params(self._bind, self._prepare(params)):
            clogits, cache = self.model.decode_step(
                cids, cache, cpos[None], block_tables=ctable)
        ctok = sample_tokens(clogits[0, clen - 1][None],
                             jax.random.fold_in(key, 1),
                             ctemp, ctopk, ctopp)[0]
        return out, n_acc, ctok, cache

    # -- public API --------------------------------------------------------

    def submit(self, prompt: Sequence[int],
               max_new_tokens: int = 32,
               sampling: Optional[SamplingParams] = None,
               request_uid: Optional[int] = None,
               priority: int = 0,
               ttft_slo_ms: Optional[float] = None,
               tpot_slo_ms: Optional[float] = None,
               drafter=None) -> int:
        """Enqueue a request; returns its id.  Admission happens inside
        ``step()`` as slots free up (FIFO).

        ``request_uid`` threads an existing lifecycle uid through (a
        router minted it and already logged ``submitted``); direct
        callers leave it None and the engine mints one — either way the
        uid correlates every later lifecycle event, across replicas on
        failover included.

        ``ttft_slo_ms`` / ``tpot_slo_ms`` override the ambient SLO
        flags — the router captures a request's deadlines once at
        ROUTER submit time and threads them through here, so a request
        placed ticks later from the predictive hold queue still carries
        the class deadlines it arrived with (not whatever the flags say
        at placement time).  None reads the flags (direct callers).

        ``priority`` is the preemption class (higher wins; default 0).
        With ``preempt`` armed, the queue admits by priority class
        (stable FIFO within a class) and a blocked admission may evict
        a running lower-priority request — see ``_try_preempt`` for
        the victim selection contract.

        ``drafter`` overrides the engine's default drafter for THIS
        request (spec mode): ``"ngram"``, ``"model"``, or a Drafter
        instance — a router can mix n-gram and draft-model requests in
        one engine; string kinds are built lazily and memoized, and
        lifecycle ``spec_accept`` events record ``drafter_kind``."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if ttft_slo_ms is None:
            ttft_slo_ms = float(_flags.flag("serving_slo_ttft_ms"))
        if tpot_slo_ms is None:
            tpot_slo_ms = float(_flags.flag("serving_slo_tpot_ms"))
        if request_uid is None:
            uid = self._rlog.new_uid()
            self._rlog.event(
                uid, "submitted", engine=self._eid,
                prompt_len=int(prompt.size),
                max_new_tokens=int(max_new_tokens),
                ttft_slo_ms=float(ttft_slo_ms),
                tpot_slo_ms=float(tpot_slo_ms))
        else:
            uid = int(request_uid)
        try:
            if prompt.size < 1:
                raise _Rejected("bad_prompt",
                                "prompt must contain at least one token")
            if max_new_tokens < 1:
                raise _Rejected(
                    "bad_max_new_tokens",
                    f"max_new_tokens must be >= 1, got {max_new_tokens}")
            if prompt.size + max_new_tokens > self.max_length:
                raise _Rejected(
                    "too_long",
                    f"prompt ({prompt.size}) + max_new_tokens "
                    f"({max_new_tokens}) exceeds the engine's max_length "
                    f"({self.max_length})")
            if self.paged:
                need = self.kv.blocks_needed(prompt.size, max_new_tokens)
                if need > self.kv.usable_blocks:
                    raise _Rejected(
                        "pool_too_small",
                        f"request needs {need} KV blocks but the pool "
                        f"only has {self.kv.usable_blocks} usable blocks")
        except _Rejected as e:
            self._rlog.event(uid, "rejected", engine=self._eid,
                             reason=e.reason)
            self._f_slo_viol.labels(engine=self._eid,
                                    kind="rejected").inc()
            raise ValueError(str(e)) from None
        rid = self._next_rid
        self._next_rid += 1
        self._results[rid] = []
        self._uids[rid] = uid
        self._queue.append(Request(
            rid, prompt, int(max_new_tokens),
            sampling or SamplingParams(),
            t_submit=self._clock(), uid=uid,
            ttft_slo_ms=float(ttft_slo_ms),
            tpot_slo_ms=float(tpot_slo_ms),
            priority=int(priority), drafter=drafter))
        self._m_submitted.inc()
        return rid

    def request_uid(self, rid: int) -> int:
        """The lifecycle uid behind engine request ``rid`` — the key
        into :func:`paddle_tpu.observability.get_request_log`."""
        return self._uids[rid]

    def step(self) -> List[int]:
        """One scheduler tick: admit queued requests into free slots
        (batched prefill waves), then run ONE jitted decode step over the
        slot batch.  Returns the request ids finished this tick.

        Idle ticks (no queued work, no active slots — the poll loop of a
        server waiting for traffic) return immediately: no admission
        scan, no device dispatch of a fully-masked decode step."""
        if (not self._queue and not self._resume_q
                and not self._swap_resume and not self._active.any()
                and self._prefill is None):
            self._set_occupancy(0)
            return []
        if not self._linted:
            # first real tick: self-lint the once-jitted step under
            # FLAGS_graph_lint (one abstract trace, no compile) — the
            # donation/dtype/const/host-sync/retrace rules fail loudly
            # here, BEFORE the first device dispatch, when armed
            self._linted = True
            if _flags.flag("graph_lint") != "off":
                from .. import static_analysis as _sa
                _sa.enforce(self.lint_step(),
                            context=f"serving.step engine={self._eid}")
        with self._tracer.span("serving.step", tick=self._ticks):
            if self.chunked:
                return self._step_inner_chunked()
            if self.spec:
                return self._step_inner_spec()
            return self._step_inner()

    def _grow_row_for_writes(self, i: int, last_pos: int):
        """Paged pre-dispatch bookkeeping for one slot about to write K/V
        at ``positions[i]..last_pos``: grow the chain over every block
        boundary in the span and COW-privatise each block in it (no-ops
        unless a forking feature shared them), refreshing the uploaded
        table row when anything changed.  Plain decode spans one
        position; a spec verify step spans the row's real draft window."""
        pos = int(self._positions[i])
        changed = self.kv.ensure_capacity(i, last_pos)
        for lb in range(pos // self.block_len,
                        last_pos // self.block_len + 1):
            cow = self.kv.ensure_writable(i, lb)
            if cow is not None:
                self._cache = self._cow_fn(self._cache, jnp.int32(cow[0]),
                                           jnp.int32(cow[1]))
                changed = True
        if changed:
            self._tables[i] = self.kv.table_row(i, self.max_blocks)

    def _flush_fresh_scales(self):
        """int8 pool pre-dispatch hygiene: zero the device scale rows of
        every block newly appended to a chain since the last dispatch
        (see BlockManager.drain_fresh) so a reused block's stale scale
        never inflates its new tenant's quantization."""
        if not (self.paged and self.quantized):
            return
        fresh = self.kv.drain_fresh()
        if not fresh:
            return
        mask = np.zeros((self.kv.num_blocks,), bool)
        mask[fresh] = True
        self._cache = self._scale_reset_fn(self._cache, jnp.asarray(mask))

    def _apply_demotions(self):
        """Mixed-mode post-dispatch hygiene: run the queued simulated-
        int8 block rewrites.  Queued at trie registration, applied only
        after the dispatch that wrote the blocks' contents (wave
        registration precedes its prefill; chunked registration follows
        its chunk) — a demotion must never be overwritten by the prefill
        it raced."""
        if not self._pending_demote:
            return
        # drain in place: kv.on_demote holds a bound ``extend`` of THIS
        # list, so rebinding the attribute would orphan the hook
        pending = list(self._pending_demote)
        self._pending_demote.clear()
        for bid in pending:
            self._cache = self._demote_fn(self._cache, jnp.int32(bid))
        self._m_demoted.inc(len(pending))

    # -- host tier plumbing (swap hooks) -----------------------------------

    def _block_movers(self):
        """Build (once, lazily) the jitted one-block movers the swap
        hooks and the export/import migration path share.  Each is
        jitted ONCE with a traced block id — a different block is
        different DATA, not a different trace, so the retrace budget of
        1 holds for every swap/migration volume.  The read fn does NOT
        donate (the pool is read again); the write fn donates the pool
        and the engine rebinds it, the step's aliasing contract.  Both
        map over the cache pytree, so the int8 {kv, scale} pool moves a
        block's scale row together with its payload — a round trip
        restores quantized blocks bit-for-bit."""
        if self._read_block_fn is not None:
            return
        def _read_block_impl(c, bid):
            return jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_slice_in_dim(
                    a, bid, 1, axis=2), c)

        def _write_block_impl(c, payload, bid):
            return jax.tree_util.tree_map(
                lambda a, p: jax.lax.dynamic_update_slice_in_dim(
                    a, p, bid, axis=2), c, payload)
        read_kwargs, write_kwargs = {}, {}
        if self.mesh is not None:
            # the one-block payload keeps the pool's per-leaf specs
            # (only the head dim is sharded; the block axis never is,
            # so a single-block slice stays on-device-local)
            sh = self._mesh_jit_shardings(2, 1, cache_argnum=0,
                                          with_params=False)
            read_kwargs = dict(in_shardings=sh["in_shardings"],
                               out_shardings=sh["out_shardings"])
            write_kwargs = dict(
                in_shardings=(sh["in_shardings"][0],
                              sh["out_shardings"],
                              sh["in_shardings"][1]),
                out_shardings=sh["out_shardings"])
        self._read_block_fn = _obs.track_retraces(
            _read_block_impl, "serving.swap_read", budget=1,
            labels={"engine": self._eid}, **read_kwargs)
        self._write_block_fn = _obs.track_retraces(
            _write_block_impl, "serving.swap_write", budget=1,
            labels={"engine": self._eid}, donate_argnums=(0,),
            **write_kwargs)

    def _host_swap_out(self, pairs):
        """BlockManager ``on_swap_out`` hook: copy each ``(bid, hid)``
        pair's device block into its host buffer.  The ``device_get``
        is the synchronization point — the payload lands on the host
        BEFORE ``swap_out``/``_evict_one`` returns the physical block to
        the free list, so a re-allocation can never race the copy."""
        self._block_movers()
        tier = self.kv.host_tier
        for bid, hid in pairs:
            payload = jax.device_get(
                self._read_block_fn(self._cache, jnp.int32(bid)))
            tier.put(hid, payload)
            nbytes = sum(int(a.nbytes) for a in
                         jax.tree_util.tree_leaves(payload))
            self._tick_swap_bytes += nbytes
            self._m_swap_out_bytes.inc(nbytes)

    def _host_swap_in(self, pairs):
        """BlockManager ``on_swap_in`` hook: write each ``(hid, bid)``
        pair's host payload back into its (re)allocated device block.
        The write fn donates the pool — same in-place aliasing contract
        as the step — and runs strictly between dispatches, so the
        once-jitted step never observes a swap as a new trace."""
        self._block_movers()
        tier = self.kv.host_tier
        for hid, bid in pairs:
            payload = jax.tree_util.tree_map(jnp.asarray, tier.get(hid))
            self._cache = self._write_block_fn(self._cache, payload,
                                               jnp.int32(bid))
            nbytes = sum(int(a.nbytes) for a in
                         jax.tree_util.tree_leaves(payload))
            self._tick_swap_bytes += nbytes
            self._m_swap_in_bytes.inc(nbytes)

    # -- preemptive scheduling (ISSUE 16) ----------------------------------

    def _try_preempt(self, *, priority: int, rid: int,
                     blocked_ticks: int) -> bool:
        """Pick and preempt ONE victim so the blocked waiter's admission
        can retry.  Victim selection is the BASELINE.md determinism
        contract — a pure function of schedule state, ranked by
        (priority ASC, loosest TTFT SLO first, shortest progress,
        youngest request, slot index).  The SLO key is the RELATIVE
        budget, deliberately not a submit-anchored absolute deadline:
        t_submit is wall clock, and ranking on it would make victim
        selection timing-dependent, breaking the byte-stable replay
        signature (no-SLO victims rank as infinitely loose, i.e. first):

          * a strictly-lower-priority victim is preempted immediately;
          * a same-priority victim only after the waiter has been
            blocked ``FLAGS_serving_preempt_after`` consecutive ticks,
            and never one that was itself already preempted once —
            together these stop two equal-priority requests from
            swapping each other forever.

        Returns True if a victim was preempted (the caller retries
        admission), False if nobody is eligible."""
        if self.preempt == "off":
            return False
        cands = []
        for i, slot in enumerate(self._slots):
            if slot is None or slot.req is None:
                continue
            vr = slot.req
            if vr.priority < priority:
                pass                       # strictly lower: immediate
            elif (vr.priority == priority
                  and blocked_ticks >= self._preempt_after
                  and vr.preempt_count == 0):
                pass                       # FIFO fairness gate passed
            else:
                continue
            dl = vr.ttft_slo_ms if vr.ttft_slo_ms > 0 else float("inf")
            prog = len(self._results[vr.request_id])
            cands.append(((vr.priority, -dl, prog, -vr.request_id, i), i))
        if not cands:
            return False
        _, victim = min(cands)
        self._do_preempt(victim, waiter_rid=rid)
        return True

    def _do_preempt(self, i: int, waiter_rid: int):
        """Evict slot ``i``'s request: swap its private blocks to the
        host tier (falling back to recompute if the tier can't take
        them) or free the chain for recompute-from-prefix, then park the
        request on the matching resume queue."""
        slot = self._slots[i]
        req = slot.req
        mode = self.preempt
        record = None
        if mode == "swap":
            record = self.kv.swap_out(i)
            if record is None:
                mode = "recompute"  # host tier full: degrade gracefully
        if mode == "recompute":
            self.kv.preempt_free(i)
        req.preempt_count += 1
        gen = self._results[req.request_id]
        self._preempt_log.append({
            "tick": self._ticks, "victim_rid": req.request_id,
            "waiter_rid": waiter_rid, "mode": mode, "slot": i,
            "progress": len(gen)})
        self._f_preempt.labels(engine=self._eid, mode=mode).inc()
        self._tracer.instant("serving.preempted", rid=req.request_id,
                             mode=mode, slot=i)
        self._rlog.event(req.uid, "preempted", engine=self._eid,
                         mode=mode, slot=int(i), tokens=len(gen),
                         waiter=int(waiter_rid))
        if mode == "swap":
            n_host = sum(1 for e in record["entries"] if e[0] == "host")
            self._rlog.event(req.uid, "swapped_out", engine=self._eid,
                             blocks=len(record["entries"]),
                             host_blocks=int(n_host))
            self._push_swap_resume(_SwapResume(
                req=req, record=record,
                last_token=int(self._tokens[i]),
                position=int(self._positions[i]),
                remaining=slot.remaining, t_first=slot.t_first))
        else:
            # recompute: the synthetic resume request re-prefills the
            # prompt plus every committed token but the last through the
            # prefix trie.  The cache covered positions
            # [0, plen + n_gen - 1) at preemption, which is EXACTLY
            # len(prompt ++ gen[:-1]) — and blocks_needed(plen2, rem+1)
            # equals the original reservation, so resume admission can
            # never demand more blocks than first admission did.
            prompt2 = (np.concatenate(
                [req.prompt, np.asarray(gen[:-1], np.int32)])
                if len(gen) > 1 else req.prompt)
            self._push_resume_q(dataclasses.replace(
                req, prompt=prompt2, max_new_tokens=slot.remaining + 1,
                blocked_ticks=0,
                resume=_ResumeInfo(orig=req, last_token=int(gen[-1]),
                                   remaining=slot.remaining,
                                   t_first=slot.t_first)))
        self._clear_slot(i)

    def _push_swap_resume(self, entry: _SwapResume):
        self._swap_resume.append(entry)
        self._swap_resume.sort(
            key=lambda e: (-e.req.priority, e.req.request_id))

    def _push_resume_q(self, req: Request):
        # re-order IN PLACE: admission may hold a reference to this
        # deque across a preemption that pushes here (the retry loop)
        self._resume_q.append(req)
        if len(self._resume_q) > 1:
            items = sorted(self._resume_q,
                           key=lambda r: (-r.priority, r.request_id))
            self._resume_q.clear()
            self._resume_q.extend(items)

    def _next_admit(self) -> Tuple[Deque, Request]:
        """Pick the next request to admit and the queue it lives in.

        With preemption off and the predictive gate disarmed: resume
        entries (there are none unless preemption ran) then strict
        submit FIFO.  With preemption armed — or the predictive
        admission gate armed — the choice spans BOTH queues by
        ``(-priority, request_id)``: a priority submit is a scheduling
        request; parking it behind a blocked lower-priority
        recompute-resume head would undo the victim selector's work one
        queue position earlier (and vice versa, a resume entry never
        jumps a higher-priority submit).  The predictive control plane
        needs the same order for a different reason: its gate DEFERS
        over-SLO batch work at the queue head, and strict FIFO would
        let that deferred head keep head-of-line-blocking the
        interactive class whose deadline the deferral protects.
        Scanning the resume queue first makes resume entries win exact
        ties, though ids are unique so ties cannot actually occur."""
        if self.preempt == "off" and not self.admission_armed():
            src = self._resume_q if self._resume_q else self._queue
            return src, src[0]
        best: Optional[Tuple[Tuple[int, int], Deque, Request]] = None
        for q in (self._resume_q, self._queue):
            for r in q:
                key = (-r.priority, r.request_id)
                if best is None or key < best[0]:
                    best = (key, q, r)
        assert best is not None
        return best[1], best[2]

    def _service_swap_resumes(self):
        """Admission preamble: restore swapped-out requests (highest
        priority, then oldest, first) into free slots whenever the pool
        can hold their chain again.  A blocked high-priority resume may
        itself preempt a running lower-priority slot — swap-out and
        swap-in compose without ever touching the step program."""
        while self._swap_resume:
            entry = self._swap_resume[0]
            free = [i for i, s in enumerate(self._slots) if s is None]
            if self._prefill is not None:
                # chunked mode: the mid-prefill slot owns a kv chain but
                # no _Slot yet — it is NOT free
                free = [i for i in free if i != self._prefill.slot]
            if not free:
                return
            si = free[0]
            got = self.kv.resume_swapped(si, entry.record)
            if got is None:
                entry.blocked_ticks += 1
                if not self._try_preempt(priority=entry.req.priority,
                                         rid=entry.req.request_id,
                                         blocked_ticks=entry.blocked_ticks):
                    return
                continue                   # a victim freed room: retry
            self._swap_resume.pop(0)
            req = entry.req
            # restore the EXACT pre-preemption slot state: mirrors,
            # table row, decode budget, original TTFT clock
            self._drafter_reset(si)
            self._slots[si] = _Slot(req.request_id, entry.remaining,
                                    t_first=entry.t_first,
                                    prompt=req.prompt, req=req)
            self._active[si] = True
            self._tokens[si] = entry.last_token
            self._positions[si] = entry.position
            self._temps[si] = req.sampling.temperature
            self._topk[si] = req.sampling.top_k
            self._topp[si] = req.sampling.top_p
            self._tables[si] = self.kv.table_row(si, self.max_blocks)
            # re-register the prompt so prefix sharing resumes (the
            # round trip preserved per-block dtype tags, so mixed-mode
            # re-registration never re-demotes an int8 block)
            self.kv.register_prompt_upto(si, req.prompt,
                                         int(req.prompt.size))
            self._rlog.event(req.uid, "swapped_in", engine=self._eid,
                             slot=int(si), blocks=int(got))
            self._rlog.event(req.uid, "resumed", engine=self._eid,
                             mode="swap", slot=int(si))
            self._f_resumed.labels(engine=self._eid, mode="swap").inc()
            self._tracer.instant("serving.resumed", rid=req.request_id,
                                 mode="swap", slot=int(si))

    def preempt_signature(self) -> str:
        """SHA-256 over the ordered preemption-decision log (victim,
        waiter, tick, mode, progress per decision) — the byte-stability
        gate loadgen's saturated smoke replays: identical traffic must
        reproduce identical victim selection."""
        blob = json.dumps(self._preempt_log, sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()

    @property
    def preempt_decisions(self) -> List[Dict[str, object]]:
        return list(self._preempt_log)

    # -- cross-worker migration (ISSUE 18) ---------------------------------

    def export_request(self, rid: int,
                       release: bool = True) -> Optional[Dict[str, object]]:
        """Serialize an ACTIVELY DECODING request for migration to
        another engine: the exact slot state a swap-resume would restore
        (position, last token, decode budget, sampling knobs, SLO
        deadlines, lifecycle uid) plus the request's KV chain by value
        (``BlockManager.export_blocks`` with the jitted one-block reader
        — scale rows travel with their payloads, so quantized blocks
        migrate bit-for-bit).  Returns ``None`` when ``rid`` is not in a
        decode slot (queued / mid-prefill / preempted requests are not
        exportable — migrate them by resubmission instead).

        ``release=True`` (the default) frees the slot and its blocks
        after the copy — the request now lives wherever the record is
        imported; partial output stays readable via ``result()``.  The
        disaggregation flow is: prefill worker decodes the FIRST token,
        exports, decode worker imports and finishes the request."""
        if not self.paged:
            raise RuntimeError(
                "export_request requires the paged cache "
                "(ServingEngine(..., paged=True))")
        self._block_movers()
        for i, slot in enumerate(self._slots):
            if slot is None or slot.rid != rid:
                continue
            req = slot.req

            def _read(bid: int):
                return jax.device_get(
                    self._read_block_fn(self._cache, jnp.int32(bid)))

            blocks = self.kv.export_blocks(i, _read)
            nbytes = sum(
                int(a.nbytes) for e in blocks["entries"]
                for a in jax.tree_util.tree_leaves(e["payload"]))
            record = {
                "uid": int(req.uid),
                "prompt": [int(t) for t in req.prompt],
                "generated": list(self._results.get(rid, [])),
                "max_new_tokens": int(req.max_new_tokens),
                "remaining": int(slot.remaining),
                "position": int(self._positions[i]),
                "last_token": int(self._tokens[i]),
                "had_first": bool(slot.t_first > 0.0),
                "sampling": {"temperature": float(req.sampling.temperature),
                             "top_k": int(req.sampling.top_k),
                             "top_p": float(req.sampling.top_p)},
                "priority": int(req.priority),
                "ttft_slo_ms": float(req.ttft_slo_ms),
                "tpot_slo_ms": float(req.tpot_slo_ms),
                "blocks": blocks,
                "payload_bytes": int(nbytes),
            }
            self._m_mig_out.inc()
            self._m_mig_bytes_out.inc(nbytes)
            self._rlog.event(req.uid, "exported", engine=self._eid,
                             slot=int(i),
                             blocks=len(blocks["entries"]),
                             bytes=int(nbytes))
            self._tracer.instant("migration.export", rid=rid,
                                 blocks=len(blocks["entries"]),
                                 bytes=int(nbytes))
            if release:
                self._release(i)
            return record
        return None

    def import_request(self, record: Dict[str, object]) -> Optional[int]:
        """Materialise an exported request into a free slot of THIS
        engine and continue its decode exactly where the exporter
        stopped: blocks land via ``BlockManager.import_blocks`` + the
        jitted one-block writer, host mirrors restore the swap-resume
        way, and the prompt re-registers in the local prefix trie (dtype
        tags preserved — mixed mode never re-demotes).  Returns the
        LOCAL rid (the lifecycle uid in the record is adopted, so the
        request keeps ONE timeline across workers), or ``None`` when no
        free slot or pool room is available right now — the caller keeps
        the record and retries, nothing is consumed."""
        if not self.paged:
            raise RuntimeError(
                "import_request requires the paged cache "
                "(ServingEngine(..., paged=True))")
        self._block_movers()
        free = [i for i, s in enumerate(self._slots) if s is None]
        if self._prefill is not None:
            free = [i for i in free if i != self._prefill.slot]
        if not free:
            return None
        si = free[0]

        def _write(bid: int, payload):
            self._cache = self._write_block_fn(
                self._cache,
                jax.tree_util.tree_map(jnp.asarray, payload),
                jnp.int32(bid))

        got = self.kv.import_blocks(si, record["blocks"], _write)
        if got is None:
            return None
        uid = int(record["uid"])
        prompt = np.asarray(record["prompt"], np.int32)
        sp = record["sampling"]
        req = Request(
            self._next_rid, prompt, int(record["max_new_tokens"]),
            SamplingParams(temperature=float(sp["temperature"]),
                           top_k=int(sp["top_k"]),
                           top_p=float(sp["top_p"])),
            t_submit=self._clock(), uid=uid,
            ttft_slo_ms=float(record["ttft_slo_ms"]),
            tpot_slo_ms=float(record["tpot_slo_ms"]),
            priority=int(record["priority"]))
        rid = self._next_rid
        self._next_rid += 1
        self._results[rid] = list(record["generated"])
        self._uids[rid] = uid
        # restore the slot the swap-resume way: mirrors, table row,
        # decode budget; the TPOT clock restarts on this engine's clock
        # (cross-process wall clocks don't compare — BASELINE.md
        # "Multi-host accounting conventions")
        self._drafter_reset(si)
        self._slots[si] = _Slot(rid, int(record["remaining"]),
                                t_first=(self._clock()
                                         if record["had_first"] else 0.0),
                                prompt=prompt, req=req)
        self._active[si] = True
        self._tokens[si] = int(record["last_token"])
        self._positions[si] = int(record["position"])
        self._temps[si] = req.sampling.temperature
        self._topk[si] = req.sampling.top_k
        self._topp[si] = req.sampling.top_p
        self._tables[si] = self.kv.table_row(si, self.max_blocks)
        self.kv.register_prompt_upto(si, prompt, int(prompt.size))
        nbytes = int(record.get("payload_bytes", 0))
        self._m_mig_in.inc()
        self._m_mig_bytes_in.inc(nbytes)
        self._rlog.event(uid, "imported", engine=self._eid,
                         slot=int(si), blocks=int(got),
                         bytes=nbytes)
        self._tracer.instant("migration.import", rid=rid,
                             blocks=int(got), bytes=nbytes)
        return rid

    # -- cancellation (ISSUE 16 satellite) ---------------------------------

    def cancel(self, rid: int) -> bool:
        """Tear down request ``rid`` wherever it currently lives —
        queued, awaiting recompute-resume, swapped out on the host tier,
        mid-chunked-prefill, or actively decoding — with refcount-safe
        block free, a ``retired(reason="cancelled")`` lifecycle event
        and rejected-style SLO accounting.  Returns True if the request
        was found and torn down, False if unknown or already finished.
        Partial output (if any) stays readable via ``result()``."""
        for q in (self._queue, self._resume_q):
            for req in q:
                if req.request_id == rid:
                    q.remove(req)
                    self._finish_cancel(
                        req if req.resume is None else req.resume.orig)
                    return True
        for k, entry in enumerate(self._swap_resume):
            if entry.req.request_id == rid:
                self._swap_resume.pop(k)
                self.kv.drop_swap_record(entry.record)
                self._finish_cancel(entry.req)
                return True
        pf = self._prefill
        if pf is not None and pf.req.request_id == rid:
            # mid-chunked-prefill: the slot owns a kv chain (admission
            # reserved it) but no _Slot/mirror state yet
            self._prefill = None
            if self.paged:
                self.kv.release(pf.slot)
                self._tables[pf.slot] = 0
            self._finish_cancel(
                pf.req if pf.req.resume is None else pf.req.resume.orig)
            return True
        for i, slot in enumerate(self._slots):
            if slot is not None and slot.rid == rid:
                req = slot.req
                self._release(i)
                self._finish_cancel(req)
                return True
        return False

    def _finish_cancel(self, req: Request):
        tokens = len(self._results.get(req.request_id, []))
        self._m_finished.inc()
        self._m_cancelled.inc()
        self._f_retired.labels(engine=self._eid, reason="cancelled").inc()
        self._f_slo_viol.labels(engine=self._eid, kind="cancelled").inc()
        self._rlog.event(req.uid, "retired", engine=self._eid,
                         reason="cancelled", tokens=int(tokens),
                         violation="cancelled")
        self._tracer.instant("serving.cancelled", rid=req.request_id)

    def _step_inner(self) -> List[int]:
        finished = self._admit()
        occ = int(self._active.sum())
        self._set_occupancy(occ)
        if not occ:
            return finished
        self._ticks += 1
        key = jax.random.fold_in(self._base_key, self._ticks)
        t0 = self._clock()
        with self._tracer.span("serving.decode", slots=occ):
            if self.paged:
                for i, slot in enumerate(self._slots):
                    if slot is None:
                        continue
                    # this tick writes K/V at positions[i]
                    self._grow_row_for_writes(i, int(self._positions[i]))
                self._flush_fresh_scales()
                nxt, self._cache = self._step_fn(
                    self._params, self._cache,
                    jnp.asarray(self._tokens), jnp.asarray(self._positions),
                    jnp.asarray(self._tables), jnp.asarray(self._active),
                    jnp.asarray(self._temps), jnp.asarray(self._topk),
                    jnp.asarray(self._topp), key)
            else:
                nxt, self._cache = self._step_fn(
                    self._params, self._cache,
                    jnp.asarray(self._tokens), jnp.asarray(self._positions),
                    jnp.asarray(self._active), jnp.asarray(self._temps),
                    jnp.asarray(self._topk), jnp.asarray(self._topp), key)
            nxt = np.asarray(nxt)        # the tick's one host sync
        now = self._clock()
        self._m_step_ms.observe((now - t0) * 1e3)
        self._perf_tick((now - t0) * 1e3, occ)
        finished.extend(self._advance_decode(nxt, now))
        return finished

    def _advance_decode(self, nxt: np.ndarray, now: float) -> List[int]:
        """Per-slot bookkeeping after a decode/mixed step's token fetch."""
        finished: List[int] = []
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            tok = int(nxt[i])
            self._positions[i] += 1
            self._tokens[i] = tok
            self._results[slot.rid].append(tok)
            slot.remaining -= 1
            self._m_tokens.inc()
            reason = self._finish_reason(tok, slot, i)
            if reason is not None:
                finished.append(slot.rid)
                self._retire(slot, i, reason, now)
        return finished

    # -- speculative-decode scheduler (verify steps) -----------------------

    def _propose_drafts(self) -> Tuple[np.ndarray, np.ndarray,
                                       np.ndarray]:
        """The draft phase: ask each slot's drafter (engine default or
        the request's ``submit(drafter=...)`` override) for up to
        ``spec_k`` tokens, capped so an accepted window can never
        overrun the row's token budget (``remaining - 1`` drafts ⇒ at
        most ``remaining`` commits) or ``max_length - 1`` (every window
        write stays in bounds).

        Host proposers (n-gram and injected scripted drafters) run per
        slot and carry ONE-HOT proposal distributions — deterministic
        q, so sampled rows accept draft d w.p. p_target(d) and greedy
        rows keep the exact prefix-match rule.  Device proposers (the
        draft model) run ONE batched draft step per tick across all
        their slots and return the true proposal softmax q.  Returns
        the (num_slots, k) draft matrix (pad-filled), the bool
        real-proposal mask, and the (num_slots, k, vocab) f32 q stack
        (all-zero rows at non-proposed columns — the acceptance treats
        those residuals as the plain target distribution)."""
        s, k = self.num_slots, self.spec_k
        vocab = self.config.vocab_size
        drafts = np.full((s, k), self.pad_token_id, np.int32)
        ok = np.zeros((s, k), bool)
        probs = np.zeros((s, k, vocab), np.float32)
        kinds: List[Optional[str]] = [None] * s
        caps = np.zeros((s,), np.int32)
        device_jobs: Dict[int, Dict[int, np.ndarray]] = {}
        device_objs: Dict[int, object] = {}
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            d = self._drafter_for(slot.req.drafter
                                  if slot.req is not None else None)
            if d is None:
                continue
            cap = min(k, slot.remaining - 1,
                      self.max_length - 1 - int(self._positions[i]))
            if cap < 1:
                continue
            caps[i] = cap
            kinds[i] = str(getattr(d, "kind", "custom"))
            hist = np.concatenate(
                [slot.prompt,
                 np.asarray(self._results[slot.rid], np.int32)])
            if getattr(d, "uses_device", False):
                # batch every draft-model row into one device step
                device_objs.setdefault(id(d), d)
                device_jobs.setdefault(id(d), {})[i] = hist
                continue
            prop = np.asarray(d.propose(hist), np.int32)[:cap]
            if prop.size:
                m = int(prop.size)
                drafts[i, :m] = prop
                ok[i, :m] = True
                probs[i, np.arange(m), prop] = 1.0
                self._m_drafted.inc(m)
                self._spec_m(kinds[i])[0].inc(m)
        for did, rows in device_jobs.items():
            dd, dp = device_objs[did].propose_batch(
                rows, self._temps, seed=self._ticks)
            for i in rows:
                m = int(caps[i])
                drafts[i, :m] = dd[i, :m]
                ok[i, :m] = True
                probs[i, :m] = dp[i, :m]
                self._m_drafted.inc(m)
                self._spec_m(kinds[i])[0].inc(m)
        self._tick_drafter_kind = kinds
        return drafts, ok, probs

    def _step_inner_spec(self) -> List[int]:
        """One speculative tick: wave admission unchanged, then draft
        (host n-gram per slot, or ONE batched draft-model step) and run
        ONE verify step over every slot's (k+1)-token window.  Each row
        commits 1..k+1 tokens; the weight stream — the b=1 bound
        BENCH_DECODE.json proves — is paid once either way."""
        finished = self._admit()
        occ = int(self._active.sum())
        self._set_occupancy(occ)
        if not occ:
            return finished
        with self._tracer.span("serving.draft"):
            drafts, draft_ok, draft_probs = self._propose_drafts()
        window = np.concatenate([self._tokens[:, None], drafts], axis=1)
        self._ticks += 1
        key = jax.random.fold_in(self._base_key, self._ticks)
        t0 = self._clock()
        with self._tracer.span("serving.verify", slots=occ,
                               drafted=int(draft_ok.sum())):
            if self.paged:
                for i, slot in enumerate(self._slots):
                    if slot is None:
                        continue
                    # grow/privatise over the row's REAL draft span only:
                    # pad-column writes past the chain steer to the null
                    # block, so no block is ever allocated for a draft
                    # that was never proposed
                    self._grow_row_for_writes(
                        i, int(self._positions[i])
                        + int(draft_ok[i].sum()))
                self._flush_fresh_scales()
                out, n_acc, self._cache = self._step_fn(
                    self._params, self._cache, jnp.asarray(window),
                    jnp.asarray(self._positions), jnp.asarray(self._tables),
                    jnp.asarray(self._active), jnp.asarray(draft_ok),
                    jnp.asarray(draft_probs),
                    jnp.asarray(self._temps), jnp.asarray(self._topk),
                    jnp.asarray(self._topp), key)
            else:
                out, n_acc, self._cache = self._step_fn(
                    self._params, self._cache, jnp.asarray(window),
                    jnp.asarray(self._positions),
                    jnp.asarray(self._active), jnp.asarray(draft_ok),
                    jnp.asarray(draft_probs),
                    jnp.asarray(self._temps), jnp.asarray(self._topk),
                    jnp.asarray(self._topp), key)
            out, n_acc = jax.device_get((out, n_acc))  # the one host sync
        now = self._clock()
        self._m_step_ms.observe((now - t0) * 1e3)
        self._perf_tick((now - t0) * 1e3, occ)
        finished.extend(self._advance_decode_spec(
            np.asarray(out), np.asarray(n_acc), draft_ok, now))
        return finished

    def _advance_decode_spec(self, out: np.ndarray, n_acc: np.ndarray,
                             draft_ok: np.ndarray, now: float
                             ) -> List[int]:
        """Per-slot bookkeeping after a verify step: commit each row's
        accepted prefix — stopping AT an EOS inside the window — and
        roll the rejected suffix back.  A multi-token accept is N tokens
        in ONE step everywhere: ``tokens_generated`` += N, ONE
        accepted-per-step observation, ONE retirement, and TPOT stays a
        per-request retirement-time readout (never per-token)."""
        finished: List[int] = []
        kinds = getattr(self, "_tick_drafter_kind", [None] * len(out))
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            n = int(n_acc[i])
            drafted = int(draft_ok[i].sum())
            kind = kinds[i] if i < len(kinds) else None
            km = self._spec_m(kind) if (drafted and kind) else None
            take, reason = n, None
            if self.eos_token_id is not None:
                hits = np.where(out[i, :n] == self.eos_token_id)[0]
                if hits.size:
                    take, reason = int(hits[0]) + 1, "eos"
            toks = [int(t) for t in out[i, :take]]
            self._results[slot.rid].extend(toks)
            self._positions[i] += take
            self._tokens[i] = toks[-1]
            slot.remaining -= take
            self._m_tokens.inc(take)
            self._m_spec_accept.observe(take)
            if km is not None:
                km[4].observe(take)
            if drafted and slot.req is not None:
                self._rlog.event(slot.req.uid, "spec_accept",
                                 engine=self._eid, tokens=int(take),
                                 drafted=int(drafted),
                                 drafter_kind=kind or "custom")
            if drafted:
                # hits = committed draft tokens (the bonus token is free
                # either way); misses = drafts verification rejected —
                # an EOS cut discards verified drafts without counting
                # them on either side
                self._m_draft_hits.inc(take - 1)
                self._m_draft_miss.inc(drafted - (n - 1))
                if km is not None:
                    km[1].inc(take - 1)
                    km[2].inc(drafted - (n - 1))
            if take <= drafted:
                # the row wrote K/V past its accept point: pin the
                # position (contiguous rollback is exactly that — the
                # stale cells above it are rewritten before any mask
                # reads them) and, paged, return draft-only blocks
                self._m_rollbacks.inc()
                if km is not None:
                    km[3].inc()
                if self.paged:
                    self.kv.truncate_to(i, int(self._positions[i]))
                    self._tables[i] = self.kv.table_row(i,
                                                        self.max_blocks)
            if reason is None:
                reason = self._finish_reason(toks[-1], slot, i)
            if reason is not None:
                finished.append(slot.rid)
                self._retire(slot, i, reason, now)
        return finished

    # -- chunked-prefill scheduler (mixed steps) ---------------------------

    def _step_inner_chunked(self) -> List[int]:
        """One token-budget tick: admit the FIFO head into a free slot
        (no prefill dispatched yet — just a cursor), then run ONE mixed
        step carrying every decode row plus at most one
        ``prefill_chunk``-token slice of the admitted prompt.  A long
        prompt therefore costs a bounded latency bump per tick instead
        of stalling every in-flight decode for its whole prefill."""
        finished = self._admit_chunked()
        occ = int(self._active.sum())
        self._set_occupancy(occ)
        pf = self._prefill
        self._m_chunk_queue.observe(self._pending_chunks())
        # decode-priority policy: while decodes are active, pending
        # chunks run on alternate ticks only (odd _ticks), halving the
        # prompt-ingest rate to shave the mixed-step TPOT bump
        do_chunk = pf is not None and (
            self._chunk_policy == "prefill" or occ == 0
            or self._ticks % 2 == 1)
        if not occ and not do_chunk:
            return finished
        self._ticks += 1
        key = jax.random.fold_in(self._base_key, self._ticks)
        ch = self.prefill_chunk
        cids = np.full((1, ch), self.pad_token_id, np.int32)
        ctemp = np.zeros((1,), np.float32)
        ctopk = np.zeros((1,), np.int32)
        ctopp = np.ones((1,), np.float32)
        if do_chunk:
            clen = min(ch, pf.req.prompt.size - pf.cursor)
            cids[0, :clen] = pf.req.prompt[pf.cursor:pf.cursor + clen]
            cpos, cslot = pf.cursor, pf.slot
            sp = pf.req.sampling
            ctemp[0], ctopk[0], ctopp[0] = (sp.temperature, sp.top_k,
                                            sp.top_p)
        else:
            # chunk-free tick, same compiled program: contiguous writes
            # drop past max_length, paged writes land in the null block
            clen, cslot = 1, 0
            cpos = 0 if self.paged else self.max_length
        if self.spec:
            # spec × chunked: the decode half becomes the verify window.
            # A prefilling slot is inactive until its cursor completes,
            # so its spec window is suspended by construction.
            with self._tracer.span("serving.draft"):
                drafts, draft_ok, draft_probs = self._propose_drafts()
            window = np.concatenate([self._tokens[:, None], drafts],
                                    axis=1)
        t0 = self._clock()
        chunk_span = (self._tracer.span("serving.chunk", slot=cslot,
                                        start=cpos, tokens=clen)
                      if do_chunk else contextlib.nullcontext())
        decode_span = self._tracer.span(
            "serving.verify" if self.spec else "serving.decode",
            slots=occ)
        with decode_span, chunk_span:
            if self.paged:
                for i, slot in enumerate(self._slots):
                    if slot is None:
                        continue
                    last = int(self._positions[i])
                    if self.spec:
                        last += int(draft_ok[i].sum())
                    self._grow_row_for_writes(i, last)
                if do_chunk:
                    # grow the chain to cover this chunk's real tokens;
                    # pad-tail positions fall past the chain and steer to
                    # the null block (the admission reservation makes the
                    # growth infallible)
                    self.kv.ensure_capacity(cslot, cpos + clen - 1)
                    ctable = self.kv.table_row(cslot,
                                               self.max_blocks)[None]
                else:
                    ctable = np.zeros((1, self.max_blocks), np.int32)
                self._flush_fresh_scales()
                head = ((jnp.asarray(window), jnp.asarray(self._positions),
                         jnp.asarray(self._tables),
                         jnp.asarray(self._active), jnp.asarray(draft_ok),
                         jnp.asarray(draft_probs))
                        if self.spec else
                        (jnp.asarray(self._tokens),
                         jnp.asarray(self._positions),
                         jnp.asarray(self._tables),
                         jnp.asarray(self._active)))
                res = self._step_fn(
                    self._params, self._cache, *head,
                    jnp.asarray(self._temps), jnp.asarray(self._topk),
                    jnp.asarray(self._topp),
                    jnp.asarray(cids), jnp.int32(cpos), jnp.int32(clen),
                    jnp.asarray(ctable), jnp.asarray(ctemp),
                    jnp.asarray(ctopk), jnp.asarray(ctopp), key)
            else:
                # non-decoding rows (idle or mid-prefill) write at
                # max_length so the scatter drops them — chunked prefill
                # owns those rows' contents now
                dev_pos = np.where(self._active, self._positions,
                                   self.max_length).astype(np.int32)
                head = ((jnp.asarray(window), jnp.asarray(dev_pos),
                         jnp.asarray(self._active), jnp.asarray(draft_ok),
                         jnp.asarray(draft_probs))
                        if self.spec else
                        (jnp.asarray(self._tokens), jnp.asarray(dev_pos),
                         jnp.asarray(self._active)))
                res = self._step_fn(
                    self._params, self._cache, *head,
                    jnp.asarray(self._temps), jnp.asarray(self._topk),
                    jnp.asarray(self._topp),
                    jnp.asarray(cids), jnp.int32(cpos), jnp.int32(clen),
                    jnp.int32(cslot), jnp.asarray(ctemp),
                    jnp.asarray(ctopk), jnp.asarray(ctopp), key)
            if self.spec:
                out, n_acc, ctok, self._cache = res
                out, n_acc, ctok = jax.device_get((out, n_acc, ctok))
            else:
                nxt, ctok, self._cache = res
                nxt, ctok = jax.device_get((nxt, ctok))  # the one sync
        now = self._clock()
        self._m_step_ms.observe((now - t0) * 1e3)
        self._perf_tick((now - t0) * 1e3, occ,
                        chunk_tokens=clen if do_chunk else 0)
        if self.spec:
            finished.extend(self._advance_decode_spec(
                np.asarray(out), np.asarray(n_acc), draft_ok, now))
        else:
            finished.extend(self._advance_decode(np.asarray(nxt), now))
        if do_chunk:
            finished.extend(self._advance_chunk(pf, clen, int(ctok), now))
        self._apply_demotions()
        return finished

    def _admit_chunked(self) -> List[int]:
        """Move the FIFO head into a free slot as a partially-prefilled
        request — a cursor, not a prefill dispatch.  One prompt streams
        at a time (FIFO order; the chunk operand is single-slot by
        construction).  Queue-wait is recorded ONCE here — a request
        admitted at tick t waits zero extra queue time for its chunks."""
        if self.paged:
            self._service_swap_resumes()
        if (self._prefill is not None
                or not (self._resume_q or self._queue)):
            return []
        free = [i for i, s in enumerate(self._slots) if s is None]
        if not free:
            return []
        src, req = self._next_admit()
        occ = self.num_slots - len(free)
        live = int(self._positions[self._active].sum()) if occ else 0
        if self._admission_defer(req, occ + 1,
                                 live + int(req.prompt.size),
                                 chunk_tokens=self.prefill_chunk):
            self._defer(req)
            return []
        si = free[0]
        m = 0
        if self.paged:
            got = self.kv.admit(si, req.prompt, req.prompt.size,
                                req.max_new_tokens, chunked=True)
            while got is None and self._try_preempt(
                    priority=req.priority, rid=req.request_id,
                    blocked_ticks=req.blocked_ticks):
                got = self.kv.admit(si, req.prompt, req.prompt.size,
                                    req.max_new_tokens, chunked=True)
            if got is None:          # pool full: wait for retirements
                self._m_blocked.inc()
                self._tracer.instant("serving.admission_blocked",
                                     rid=req.request_id)
                req.blocked_ticks += 1
                if req.blocked_ticks == 1:
                    # the preemption-relevant wait: log once per wait
                    # episode, not per blocked tick
                    self._rlog.event(req.uid, "admission_wait",
                                     engine=self._eid, reason="pool_full")
                return []
            m = got                  # adopted prefix tokens skip compute
        # remove by IDENTITY: a preemption inside the retry loop may
        # have re-ordered the resume queue under us
        src.remove(req)
        if self.quantized and not self.paged:
            # chunked admission streams into a reused row: drop the
            # previous tenant's granule scales before the first chunk
            self._cache = self._row_reset_fn(self._cache, jnp.int32(si))
        now = self._clock()
        self._m_prefill_total.inc(int(req.prompt.size))
        if req.resume is None:
            req.t_admit = now
            self._m_queue_wait.observe((now - req.t_submit) * 1e3)
            self._rlog.event(req.uid, "admitted", engine=self._eid,
                             slot=int(si),
                             queue_wait_ms=(now - req.t_submit) * 1e3,
                             blocked_ticks=int(req.blocked_ticks),
                             prefix_hit_tokens=int(m))
        self._prefill = _Prefill(req, si, int(m))
        return []

    def _advance_chunk(self, pf: _Prefill, clen: int, ctok: int,
                       now: float) -> List[int]:
        """Account one ingested chunk; when it completes the prompt, the
        sampled ``ctok`` is the request's first token and the slot flips
        from prefilling to decoding."""
        pf.cursor += clen
        self._m_chunks.inc()
        self._m_chunk_tokens.inc(clen)
        self._m_prefill_computed.inc(clen)
        self._rlog.event(pf.req.uid, "prefill_chunk", engine=self._eid,
                         tokens=int(clen), cursor=int(pf.cursor))
        if self.paged:
            # register the now-written full blocks for prefix sharing —
            # never earlier: an unwritten block must not satisfy a lookup
            self.kv.register_prompt_upto(pf.slot, pf.req.prompt, pf.cursor)
        plen = int(pf.req.prompt.size)
        if pf.cursor < plen:
            return []
        si, req = pf.slot, pf.req
        self._prefill = None
        ri = req.resume
        if ri is not None:
            # recompute resume (chunked): discard the re-sampled token,
            # force the last committed one back, restore the original
            # decode budget / TTFT clock — see _prefill_wave_paged
            first = ri.last_token
            slot = _Slot(req.request_id, ri.remaining, t_first=ri.t_first,
                         prompt=ri.orig.prompt, req=ri.orig)
        else:
            first = ctok
            slot = _Slot(req.request_id, req.max_new_tokens - 1,
                         t_first=now, prompt=req.prompt, req=req)
        self._drafter_reset(si)
        self._slots[si] = slot
        self._active[si] = True
        self._tokens[si] = first
        self._positions[si] = plen
        self._temps[si] = req.sampling.temperature
        self._topk[si] = req.sampling.top_k
        self._topp[si] = req.sampling.top_p
        if self.paged:
            self._tables[si] = self.kv.table_row(si, self.max_blocks)
        if ri is not None:
            self._rlog.event(req.uid, "resumed", engine=self._eid,
                             mode="recompute", slot=int(si))
            self._f_resumed.labels(engine=self._eid,
                                   mode="recompute").inc()
            self._tracer.instant("serving.resumed", rid=req.request_id,
                                 mode="recompute", slot=int(si))
            return []
        self._results[req.request_id].append(first)
        self._m_tokens.inc()
        self._m_ttft.observe((now - req.t_submit) * 1e3)
        if self._perf is not None:
            self._perf.on_ttft((now - req.t_submit) * 1e3)
        self._rlog.event(req.uid, "first_token", engine=self._eid,
                         ttft_ms=(now - req.t_submit) * 1e3)
        reason = self._finish_reason(first, slot, si)
        if reason is not None:
            self._retire(slot, si, reason, now)
            return [req.request_id]
        return []

    def _pending_chunks(self) -> int:
        """Chunks still to ingest: the active prompt's remainder plus
        every queued prompt's worth (the chunk-queue depth histogram)."""
        ch = self.prefill_chunk
        n = 0
        if self._prefill is not None:
            n += -(-(self._prefill.req.prompt.size
                     - self._prefill.cursor) // ch)
        for req in itertools.chain(self._resume_q, self._queue):
            n += -(-req.prompt.size // ch)
        return n

    def drain(self) -> List[Tuple[int, List[int]]]:
        """Run ticks until every submitted request completes; returns
        ``[(request_id, generated_tokens)]`` in arrival order (outputs end
        at EOS inclusive — no pad tail, unlike the fixed-shape
        ``generate()`` rows)."""
        while (self._queue or self._resume_q or self._swap_resume
               or self._prefill is not None
               or any(s is not None for s in self._slots)):
            self.step()
        return [(rid, list(toks))
                for rid, toks in sorted(self._results.items())]

    def result(self, rid: int) -> List[int]:
        """Tokens generated so far for ``rid`` (complete once finished)."""
        return list(self._results[rid])

    @property
    def num_active(self) -> int:
        # _slots and _active are kept in lockstep (_clear_slot /
        # admission); list.count beats a numpy reduction at this size,
        # and the router's least-loaded probe calls this per replica
        # per submit — 1.6M times in a 100k-request fleet replay
        return self.num_slots - self._slots.count(None)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def num_pending(self) -> int:
        """Requests admitted but still prefilling (chunked mode: the
        prompt whose chunks are streaming in; wave mode: always 0 —
        admission prefills in the same tick)."""
        return int(self._prefill is not None)

    @property
    def num_preempted(self) -> int:
        """Preempted requests awaiting resume (swapped-out chains parked
        on the host tier plus recompute re-prefills still queued)."""
        return len(self._swap_resume) + len(self._resume_q)

    @property
    def pending_chunks(self) -> int:
        """Prompt chunks still to ingest (chunked mode; wave mode: 0) —
        the capacity signal BASELINE.md names, and the load term the dp
        replica router ranks engines by."""
        return self._pending_chunks() if self.chunked else 0

    # -- static analysis (graph lint) --------------------------------------

    def _lint_args(self) -> Tuple:
        """Representative step-function arguments for an ABSTRACT trace:
        zero-valued, but exactly the shapes/dtypes every real tick
        passes (strong-typed vectors, jnp.int32 chunk scalars, a typed
        PRNG key) — the lint sees the program the scheduler runs."""
        s = self.num_slots
        toks = jnp.zeros((s,), jnp.int32)
        pos = jnp.zeros((s,), jnp.int32)
        mask = jnp.zeros((s,), bool)
        temps = jnp.zeros((s,), jnp.float32)
        topk = jnp.zeros((s,), jnp.int32)
        topp = jnp.ones((s,), jnp.float32)
        key = jax.random.fold_in(self._base_key, 0)
        if self.spec:
            # the verify step's window matrix + real-proposal mask +
            # proposal-distribution stack ride in place of the (s,)
            # token vector
            head = (jnp.zeros((s, self.spec_k + 1), jnp.int32), pos)
            tail_mask = (mask, jnp.zeros((s, self.spec_k), bool),
                         jnp.zeros((s, self.spec_k,
                                    self.config.vocab_size), jnp.float32))
        else:
            head, tail_mask = (toks, pos), (mask,)
        if self.chunked:
            cids = jnp.zeros((1, self.prefill_chunk), jnp.int32)
            cpos, clen = jnp.int32(0), jnp.int32(1)
            ctemp = jnp.zeros((1,), jnp.float32)
            ctopk = jnp.zeros((1,), jnp.int32)
            ctopp = jnp.ones((1,), jnp.float32)
            if self.paged:
                tables = jnp.zeros((s, self.max_blocks), jnp.int32)
                ctable = jnp.zeros((1, self.max_blocks), jnp.int32)
                return (self._params, self._cache, *head, tables,
                        *tail_mask, temps, topk, topp, cids, cpos, clen,
                        ctable, ctemp, ctopk, ctopp, key)
            return (self._params, self._cache, *head, *tail_mask, temps,
                    topk, topp, cids, cpos, clen, jnp.int32(0), ctemp,
                    ctopk, ctopp, key)
        if self.paged:
            tables = jnp.zeros((s, self.max_blocks), jnp.int32)
            return (self._params, self._cache, *head, tables, *tail_mask,
                    temps, topk, topp, key)
        return (self._params, self._cache, *head, *tail_mask, temps,
                topk, topp, key)

    def lint_step(self, mesh=None):
        """Graph-lint this engine's once-jitted step function (one
        abstract trace; the TrackedFunction's stored donate_argnums are
        honoured).  Returns the finding list — the serving contract is
        that it is EMPTY; ``FLAGS_graph_lint`` arms the same check at
        the first scheduler tick.

        ``mesh`` (a jax Mesh/AbstractMesh, ``{axis: size}`` dict, or a
        string like ``"mp2dp2"``) adds the mesh rule set, linting the
        step under this engine's DECLARED shardings
        (:func:`~paddle_tpu.models.generation.decode_mesh_specs`) —
        the same layout ``_place_on_mesh`` commits when a hybrid mesh
        is active, checked without any devices.

        The KERNEL pre-flight (ISSUE 14) rides the same call: the
        findings of :meth:`kernel_preflight` — the Pallas kernels this
        engine's dispatch would select at TPU scale — merge into the
        returned list under the shared deterministic ordering."""
        from .. import static_analysis as _sa
        if mesh is None:
            graph = _sa.analyze(self._step_fn, *self._lint_args())
        else:
            minfo = _sa.MeshInfo.of(mesh)
            graph = _sa.analyze(
                self._step_fn, *self._lint_args(), mesh=minfo,
                in_shardings=self._mesh_step_shardings(minfo))
        findings = list(graph) + list(self.kernel_preflight()["findings"])
        return _sa._sort_findings(findings)

    def _kernel_specs(self):
        """The KernelSpecs this engine's dispatch would select, PROJECTED
        to the Pallas-eligible regime.  Test configs run tiny CPU
        geometry (head_dim 16, max_length 64) that dispatch routes to
        XLA math; the kernels only ever see TPU-scale shapes, so the
        pre-flight analyzes this engine's LAYOUT (paged/contiguous,
        chunked/spec q shapes, kv dtype, block structure) at the
        smallest geometry the kernel would actually accept: head_dim
        rounded up to one lane tile, cache length up to
        FLAGS_decode_attention_min_len, paged block_len up to 128.
        A 'mixed' pool keeps bf16 device blocks (only 'int8' changes
        program shapes), so mixed engines get the bf16 specs.

        On a model-parallel mesh the kernel runs PER SHARD under
        shard_map — kv-heads are mp-sharded — so the pre-flighted
        geometry divides both head counts by the mp degree (that is
        the program each device actually compiles; whole-model heads
        would overstate VMEM by mp×)."""
        from .. import static_analysis as _sa
        lanes = 128
        c = self.config
        hkv = int(c.num_key_value_heads)
        hq = int(c.num_attention_heads)
        mp = (dict(getattr(self.mesh, "shape", {})).get("mp", 1)
              if self.mesh is not None else 1)
        shard = ""
        if mp > 1 and hq % mp == 0 and hkv % mp == 0:
            hq, hkv = hq // mp, hkv // mp
            shard = f",mp{mp}-shard"
        d_p = max(lanes, -(-int(c.head_dim) // lanes) * lanes)
        min_len = int(_flags.flag("decode_attention_min_len"))
        quantized = self.quantized
        layout = "paged" if self.paged else "contiguous"
        # q shapes per step mode: the decode rows (or the spec-verify
        # window), plus the chunked-prefill q chunk when armed
        shapes = [(self.num_slots, self.spec_k + 1, "spec_verify")
                  if self.spec else (self.num_slots, 1, "decode")]
        if self.chunked:
            shapes.append((1, self.prefill_chunk, "chunked_prefill"))
        specs = []
        for b, s, label in shapes:
            tag = (f"{layout}{'+int8' if quantized else ''},"
                   f"{label},s={s}{shard}")
            if self.paged:
                bl_p = max(lanes, -(-self.block_len // lanes) * lanes)
                mb_p = max(self.max_blocks, -(-min_len // bl_p))
                specs.append(_sa.decode_attention_spec(
                    b, s, hq, hkv, d_p, block_len=bl_p,
                    max_blocks=mb_p,
                    num_blocks=self.num_slots * mb_p + 1,
                    quantized=quantized, variant=tag))
            else:
                kv_p = max(min_len,
                           -(-self.max_length // lanes) * lanes)
                specs.append(_sa.decode_attention_spec(
                    b, s, hq, hkv, d_p, kv_len=kv_p,
                    quantized=quantized,
                    # init_kv_cache's granule layout: one scale per
                    # 128-token granule (kv_p is lane-aligned above)
                    n_granules=kv_p // lanes if quantized else None,
                    variant=tag))
        return specs

    def kernel_preflight(self, rules=None) -> Dict[str, object]:
        """Static pre-flight of the Pallas kernels this engine's
        dispatch would select (ISSUE 14): per-kernel VMEM footprint,
        index-map bounds, alignment, and streamed-bytes checks — no
        compile, no device.  Returns ``{"findings", "kernels",
        "vmem_bytes" (max over kernels), "vmem_budget_bytes",
        "vmem_budget_frac", "streamed_bytes" (sum)}`` and publishes the
        ``kernels.predicted_*`` gauges.  Memoized for the default rule
        set (the specs depend only on ctor config)."""
        from .. import static_analysis as _sa
        if rules is None and self._kernel_preflight_cache is not None:
            return self._kernel_preflight_cache
        specs = self._kernel_specs()
        findings = _sa.analyze_kernels(specs, rules=rules)
        reports = [_sa.kernel_report(s, rules=rules) for s in specs]
        budget = int(_flags.flag("kernel_lint_vmem_bytes"))
        vmem = max((r["vmem_bytes"] for r in reports), default=0)
        streamed = sum(r["streamed_bytes"] for r in reports)
        out = {
            "findings": findings,
            "kernels": reports,
            "vmem_bytes": int(vmem),
            "vmem_budget_bytes": budget,
            "vmem_budget_frac": (vmem / budget) if budget else 0.0,
            "streamed_bytes": int(streamed),
        }
        reg = _obs.default_registry()
        reg.gauge("kernels.predicted_vmem_bytes",
                  "max per-grid-step VMEM footprint over the engine's "
                  "pre-flighted kernels").labels(
                      engine=self._eid).set(float(vmem))
        reg.gauge("kernels.predicted_streamed_bytes",
                  "summed per-call streamed-bytes model over the "
                  "engine's pre-flighted kernels").labels(
                      engine=self._eid).set(float(streamed))
        if rules is None:
            self._kernel_preflight_cache = out
        return out

    def _mesh_step_shardings(self, minfo):
        """Per-arg declared shardings for the step signature: params and
        cache per :func:`decode_mesh_specs`, everything else (token/
        position/mask vectors, block tables, the PRNG key) replicated —
        they are tiny and every device needs them whole."""
        param_specs, cache_spec, _ = decode_mesh_specs(
            self._bind, self._params, minfo.names,
            paged_cache=self.paged, quantized_cache=self.quantized)
        args = self._lint_args()
        specs = [None] * len(args)
        specs[0], specs[1] = param_specs, cache_spec
        return tuple(specs)

    def mesh_preflight(self, mesh=None, rules=None) -> Dict[str, object]:
        """Mesh pre-flight of the once-jitted step (ISSUE 8): findings
        (graph-lint + mesh rules), the per-axis collective-cost report,
        and the per-device HBM-liveness estimate, all from ONE abstract
        trace under this engine's declared shardings — run BEFORE any
        mesh compile, on a host that need not have the devices.

        The HBM estimate is cross-checked against ``cache_hbm_bytes``:
        the predicted per-device cache bytes, scaled back by the
        cache's shard count, must match within
        ``FLAGS_graph_lint_hbm_tol`` or an ``hbm-liveness`` error
        finding is appended (``cache_check`` carries the numbers).
        Predicted comm bytes per axis and predicted peak HBM land in
        the observability registry as ``mesh.predicted_comm_bytes`` /
        ``mesh.predicted_peak_hbm_bytes`` gauges, and in the serving
        bench rows as ``mesh_preflight``."""
        from .. import static_analysis as _sa
        if mesh is None:
            mesh = self.mesh
        if mesh is None:
            from ..distributed import env as _denv
            mesh = _denv.active_mesh()
            if mesh is None:
                raise ValueError(
                    "mesh_preflight needs a mesh: pass one (e.g. "
                    "'mp2dp2'), construct the engine with mesh=..., or "
                    "activate a hybrid group")
        minfo = _sa.MeshInfo.of(mesh)
        pf = _sa.preflight(self._step_fn, *self._lint_args(),
                           mesh=minfo, rules=rules,
                           in_shardings=self._mesh_step_shardings(minfo))
        hbm = pf["hbm"]
        cb = self.cache_hbm_bytes
        predicted = hbm["cache_bytes_per_device"] * hbm["cache_shards"]
        tol = float(_flags.flag("graph_lint_hbm_tol"))
        rel = abs(predicted - cb) / cb if cb else 0.0
        pf["cache_check"] = {
            "engine_cache_hbm_bytes": int(cb),
            "predicted_cache_bytes": int(predicted),
            "cache_bytes_per_device": int(hbm["cache_bytes_per_device"]),
            # informational: the KV tier's pinned host-RAM entitlement
            # — host-side by design, so it never enters the HBM
            # liveness comparison above
            "host_tier_bytes": int(self.host_cache_bytes),
            "rel_err": round(rel, 6), "tol": tol, "ok": rel <= tol}
        if rel > tol:
            pf["findings"].append(_sa.Finding(
                "hbm-liveness", "error", "",
                f"liveness estimate of the cache operand "
                f"({predicted} bytes over {hbm['cache_shards']} "
                f"shard(s)) disagrees with cache_hbm_bytes ({cb}) "
                f"beyond tol {tol} — the step signature and the "
                f"engine's cache accounting have drifted",
                bytes=int(abs(predicted - cb))))
        reg = _obs.default_registry()
        for axis, row in pf["comm"]["per_axis"].items():
            reg.gauge(
                "mesh.predicted_comm_bytes",
                "pre-flight predicted collective bytes per step, per "
                "mesh axis").labels(engine=self._eid, axis=axis).set(
                    row["bytes_per_step"])
        reg.gauge(
            "mesh.predicted_peak_hbm_bytes",
            "pre-flight predicted peak HBM per device for one step"
            ).labels(engine=self._eid).set(hbm["peak_bytes_per_device"])
        if (self.mesh is not None
                and minfo.axes == _sa.MeshInfo.of(self.mesh).axes):
            pf["placement_check"] = self.mesh_placement_check(pf)
        return pf

    def mesh_placement_check(self, pf) -> Dict[str, object]:
        """Predicted-vs-ACTUAL placement cross-check for a mesh engine
        (ISSUE 9 gauge hardening): the pre-flight's per-device HBM
        numbers are estimates from an abstract trace; this engine's
        params/cache are REAL ``device_put`` footprints.  Measured
        per-device cache bytes (max over mesh devices of the placed
        shards) must match ``hbm.cache_bytes_per_device`` within
        FLAGS_graph_lint_hbm_tol, and measured resident bytes
        (params + cache per device) must not exceed the predicted peak
        beyond the same tolerance.  Drift appends a structured
        ``hbm-liveness`` error finding to ``pf["findings"]`` — never a
        bare assert — and the measured number lands in the registry as
        ``mesh.measured_cache_bytes_per_device``."""
        from .. import static_analysis as _sa
        per_dev_cache: Dict[object, int] = {}
        per_dev_params: Dict[object, int] = {}
        for tree, acc in ((self._cache, per_dev_cache),
                          (self._params, per_dev_params)):
            for leaf in jax.tree_util.tree_leaves(tree):
                for sh in leaf.addressable_shards:
                    acc[sh.device] = (acc.get(sh.device, 0)
                                      + int(sh.data.nbytes))
        measured_cache = max(per_dev_cache.values())
        measured_resident = max(
            per_dev_cache.get(d, 0) + per_dev_params.get(d, 0)
            for d in per_dev_cache)
        hbm = pf["hbm"]
        predicted_cache = int(hbm["cache_bytes_per_device"])
        predicted_peak = int(hbm["peak_bytes_per_device"])
        tol = float(_flags.flag("graph_lint_hbm_tol"))
        rel = (abs(measured_cache - predicted_cache) / predicted_cache
               if predicted_cache else 0.0)
        cache_ok = rel <= tol
        peak_ok = measured_resident <= predicted_peak * (1.0 + tol)
        if not cache_ok:
            pf["findings"].append(_sa.Finding(
                "hbm-liveness", "error", "",
                f"placed cache footprint ({measured_cache} bytes on the "
                f"fullest device) drifts from the pre-flight prediction "
                f"({predicted_cache}) beyond tol {tol} — the declared "
                f"step shardings and the committed placement disagree",
                bytes=int(abs(measured_cache - predicted_cache))))
        if not peak_ok:
            pf["findings"].append(_sa.Finding(
                "hbm-liveness", "error", "",
                f"placed resident bytes (params+cache "
                f"{measured_resident}/device) exceed the pre-flight "
                f"peak prediction ({predicted_peak}) beyond tol {tol} — "
                f"the liveness estimator is missing real residency",
                bytes=int(measured_resident - predicted_peak)))
        _obs.default_registry().gauge(
            "mesh.measured_cache_bytes_per_device",
            "actual device_put cache footprint of a mesh-placed engine "
            "(max over mesh devices)").labels(engine=self._eid).set(
                measured_cache)
        return {"measured_cache_bytes_per_device": int(measured_cache),
                "predicted_cache_bytes_per_device": predicted_cache,
                "measured_resident_bytes_per_device":
                    int(measured_resident),
                "predicted_peak_hbm_bytes_per_device": predicted_peak,
                "rel_err": round(rel, 6), "tol": tol,
                "ok": bool(cache_ok and peak_ok)}

    def observe_dequant_error(self, max_abs_logit_delta: float):
        """Record one int8-KV parity-oracle observation — the max
        absolute logit delta vs a bf16 reference run on the same trace —
        into the ``serving.kv_dequant_error`` summary.  Called by the
        oracle tests and the ``int8_serving`` bench section; the serving
        hot path never computes logits twice."""
        self._m_dequant_err.observe(float(max_abs_logit_delta))

    @property
    def cache_hbm_bytes(self) -> int:
        """Bytes of the KV cache (contiguous rows or paged pool) this
        engine keeps resident on device.  With the step's cache operand
        donated, per-tick residency is 1x this; un-donated it would be
        2x (input + output live across the call) — the graph-lint
        donation rule's finding, and the bench rows' accounting."""
        return int(sum(leaf.nbytes
                       for leaf in jax.tree_util.tree_leaves(self._cache)))

    @property
    def host_cache_bytes(self) -> int:
        """Pinned host-RAM entitlement of the KV tier (0 without one).
        Kept OUT of ``cache_hbm_bytes`` and the HBM-liveness
        cross-check: swapped-out and demoted blocks are host-resident
        by design — that is the capacity multiplier."""
        if not self.paged:
            return 0
        return int(self.kv.host_cache_bytes())

    # -- telemetry (registry read-throughs + snapshot) ---------------------

    @property
    def step_traces(self) -> int:
        """Compilations of the step function (jit.traces read-through;
        the continuous-batching contract is exactly 1)."""
        return int(self._m_step_traces.value())

    @property
    def prefill_traces(self) -> int:
        """Compilations of the prefill function (one per padded bucket
        length actually seen)."""
        return int(self._m_prefill_traces.value())

    @property
    def last_occupancy(self) -> int:
        """Busy slots at the last scheduler tick (gauge read-through)."""
        return int(self._m_active.value())

    @property
    def prefill_tokens_computed(self) -> int:
        """Prompt tokens actually prefilled (pads excluded; paged prefix
        hits skip these — computed < total proves the cache worked)."""
        return int(self._m_prefill_computed.value())

    @property
    def prefill_tokens_total(self) -> int:
        return int(self._m_prefill_total.value())

    def metrics(self) -> Dict[str, object]:
        """This engine's serving-SLO metrics read from the shared
        registry: TTFT/TPOT/queue-wait/step-latency percentiles, slot
        occupancy, request/token counters, trace counts, and (paged) the
        pool's cache-accounting block.  ``bench.py --sections serving``
        embeds exactly this dict; ``observability.snapshot()`` is the
        full-process superset."""
        def hist(h):
            d = {"count": h.count}
            for q, k in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                p = h.percentile(q)
                if p is not None:
                    d[k] = round(p, 3)
            return d

        out = {"ttft_ms": hist(self._m_ttft),
               "tpot_ms": hist(self._m_tpot),
               "queue_wait_ms": hist(self._m_queue_wait),
               "decode_step_ms": hist(self._m_step_ms),
               "slot_occupancy": round(self._m_occ.value(), 3),
               "requests_submitted": int(self._m_submitted.value()),
               "requests_finished": int(self._m_finished.value()),
               "tokens_generated": int(self._m_tokens.value()),
               "prefill_waves": int(self._m_waves.value()),
               "step_traces": self.step_traces,
               "prefill_traces": self.prefill_traces,
               "slo_violations": {
                   str(c.labels["kind"]): int(c.value())
                   for c in self._f_slo_viol.children()
                   if c.labels.get("engine") == self._eid}}
        if self.chunked:
            out["chunked"] = {
                "prefill_chunk": self.prefill_chunk,
                "chunk_policy": self._chunk_policy,
                "prefill_chunks": int(self._m_chunks.value()),
                "prefill_chunk_tokens": int(self._m_chunk_tokens.value()),
                "chunk_queue_depth": hist(self._m_chunk_queue)}
        if self.spec:
            drafted = int(self._m_drafted.value())
            hits = int(self._m_draft_hits.value())
            acc = hist(self._m_spec_accept)
            if acc["count"]:
                acc["mean"] = round(
                    self._m_spec_accept.sum / acc["count"], 3)
            out["spec"] = {
                "spec_k": self.spec_k,
                "default_drafter": getattr(self._drafter, "kind",
                                           "custom"),
                "drafted_tokens": drafted,
                "draft_hit_tokens": hits,
                "draft_miss_tokens": int(self._m_draft_miss.value()),
                "draft_hit_rate": (round(hits / drafted, 3) if drafted
                                   else 0.0),
                "rollbacks": int(self._m_rollbacks.value()),
                "accepted_per_step": acc}
            by_drafter = {}
            for kind, (md, mh, mm, mr, ma) in sorted(
                    self._spec_children.items()):
                kd, kh = int(md.value()), int(mh.value())
                kacc = hist(ma)
                if kacc["count"]:
                    kacc["mean"] = round(ma.sum / kacc["count"], 3)
                by_drafter[kind] = {
                    "drafted_tokens": kd,
                    "draft_hit_tokens": kh,
                    "draft_miss_tokens": int(mm.value()),
                    # per-kind denominator: THAT drafter's proposals
                    # only (BASELINE.md "Rejection-sampling accounting
                    # conventions")
                    "draft_hit_rate": (round(kh / kd, 3) if kd
                                       else 0.0),
                    "rollbacks": int(mr.value()),
                    "accepted_per_step": kacc}
            if by_drafter:
                out["spec"]["by_drafter"] = by_drafter
        if self.paged:
            st = self.kv.stats
            total = self.prefill_tokens_total
            out["kv_cache"] = {
                "kv_dtype": self.kv_dtype,
                "quantized_blocks": self.kv.quantized_blocks(),
                "bytes_by_dtype": {
                    d: int(g.value())
                    for d, g in self.kv._g_bytes.items()},
                "blocks_in_use": self.kv.blocks_in_use(),
                "peak_blocks_in_use": st["peak_blocks_in_use"],
                "peak_pool_occupancy": round(
                    st["peak_blocks_in_use"] / self.kv.usable_blocks, 3),
                "prefix_hit_tokens": st["prefix_hit_tokens"],
                "prefix_hit_rate": round(st["prefix_hit_tokens"] / total,
                                         3) if total else 0.0,
                "evictions": st["evictions"],
                "cow_copies": st["cow_copies"],
                "admission_blocked": int(self._m_blocked.value())}
            if self._host_blocks > 0:
                out["kv_cache"]["host_tier"] = {
                    "host_blocks": self._host_blocks,
                    "host_blocks_used": self.kv.host_blocks_used(),
                    "host_trie_blocks": self.kv.host_trie_blocks(),
                    "host_demotions": st["host_demotions"],
                    "host_promotions": st["host_promotions"],
                    "swapped_out_blocks": st["swapped_out_blocks"],
                    "swapped_in_blocks": st["swapped_in_blocks"],
                    "swap_out_bytes": int(self._m_swap_out_bytes.value()),
                    "swap_in_bytes": int(self._m_swap_in_bytes.value())}
        if self.paged and self.preempt != "off":
            def by_mode(fam):
                return {str(c.labels["mode"]): int(c.value())
                        for c in fam.children()
                        if c.labels.get("engine") == self._eid}
            out["preempt"] = {
                "mode": self.preempt,
                "preemptions": by_mode(self._f_preempt),
                "resumes": by_mode(self._f_resumed),
                "awaiting_resume": self.num_preempted,
                "decisions": len(self._preempt_log),
                "signature": self.preempt_signature()}
        out["cancelled"] = int(self._m_cancelled.value())
        return out

    def _set_occupancy(self, n: int):
        self._m_active.set(n)
        self._m_occ.set(n / self.num_slots if self.num_slots else 0.0)

    def _retire(self, slot: _Slot, i: int, reason: str, now: float):
        """Per-request SLO readout at retirement, then release the slot.
        TPOT = decode time per token after the first (prefill excluded),
        the complement of TTFT in the usual serving-latency split."""
        n = len(self._results[slot.rid])
        tpot = None
        if n > 1 and slot.t_first > 0.0:
            tpot = (now - slot.t_first) * 1e3 / (n - 1)
            self._m_tpot.observe(tpot)
            if self._perf is not None:
                self._perf.on_tpot(tpot)
        self._m_finished.inc()
        self._f_retired.labels(engine=self._eid, reason=reason).inc()
        req = slot.req
        if req is not None:
            ttft = ((slot.t_first - req.t_submit) * 1e3
                    if slot.t_first > 0.0 else None)
            kind = self._slo_violation(req, ttft, tpot)
            if kind is not None:
                self._f_slo_viol.labels(engine=self._eid, kind=kind).inc()
            self._rlog.event(
                req.uid, "retired", engine=self._eid, reason=reason,
                tokens=int(n),
                ttft_ms=(round(ttft, 6) if ttft is not None else None),
                tpot_ms=(round(tpot, 6) if tpot is not None else None),
                violation=kind or "none")
        self._release(i)

    @staticmethod
    def _slo_violation(req: Request, ttft: Optional[float],
                       tpot: Optional[float]) -> Optional[str]:
        """Attribute a retired request's SLO miss to ONE cause
        (BASELINE.md "SLO accounting conventions"): a missed TTFT
        (measured from SUBMIT, not admit) splits by the larger segment
        — ``queue_wait`` (submit → admission) vs ``prefill`` (admission
        → first token); otherwise a missed TPOT is ``decode``.  A
        disabled deadline (target 0) never violates."""
        if req.ttft_slo_ms > 0 and ttft is not None \
                and ttft > req.ttft_slo_ms:
            qw = ((req.t_admit - req.t_submit) * 1e3
                  if req.t_admit > 0.0 else 0.0)
            return "queue_wait" if qw >= ttft - qw else "prefill"
        if req.tpot_slo_ms > 0 and tpot is not None \
                and tpot > req.tpot_slo_ms:
            return "decode"
        return None

    # -- scheduler internals ----------------------------------------------

    @staticmethod
    def _bucket(plen: int) -> int:
        """Padded prefill length: next power of two (floor 8) — bounds the
        number of compiled prefill programs at log2(max_length)."""
        b = 8
        while b < plen:
            b *= 2
        return b

    def _admit(self) -> List[int]:
        """Move queued requests into free slots, one batched-prefill wave
        per contiguous FIFO run sharing a bucket.  Returns ids that
        finished AT admission (first token was EOS / max_new_tokens=1)."""
        if self.paged:
            return self._admit_paged()
        finished: List[int] = []
        deferred = False
        while self._queue and not deferred:
            free = [i for i, s in enumerate(self._slots) if s is None]
            if not free:
                break
            occ = self.num_slots - len(free)
            live = int(self._positions[self._active].sum()) if occ else 0
            bucket = min(self._bucket(len(self._queue[0].prompt)),
                         self.max_length)
            wave: List[Request] = []
            wave_tokens = 0
            while (self._queue
                   and len(wave) < min(self.prefill_batch, len(free))
                   and min(self._bucket(len(self._queue[0].prompt)),
                           self.max_length) == bucket):
                head = self._queue[0]
                if self._admission_defer(
                        head, occ + len(wave) + 1,
                        live + wave_tokens + int(head.prompt.size)):
                    self._defer(head)
                    deferred = True
                    break
                wave.append(self._queue.popleft())
                wave_tokens += int(head.prompt.size)
            if not wave:
                break
            finished.extend(self._prefill_wave(wave, free[:len(wave)],
                                               bucket))
        return finished

    def _admit_paged(self) -> List[int]:
        """Paged admission: FIFO requests enter free slots once the block
        pool covers their worst case (kv_cache.py reservations), adopting
        any cached prompt prefix on the way in.  A wave shares one padded
        SUFFIX bucket (prefix-hit rows only compute what the cache
        missed).  The FIFO head blocking on pool space blocks the queue —
        head-of-line order is the contiguous engine's contract too.

        With preemption on, admission drains BOTH the recompute-resume
        queue and the submit queue by priority class (stable FIFO
        within a class — _next_admit, resume entries winning ties) and
        a pool-full head may instead evict a running victim (see
        _try_preempt) and retry; swapped chains are restored first of
        all."""
        self._service_swap_resumes()
        finished: List[int] = []
        deferred = False
        while (self._resume_q or self._queue) and not deferred:
            free = [i for i, s in enumerate(self._slots) if s is None]
            if not free:
                break
            occ = self.num_slots - len(free)
            live = int(self._positions[self._active].sum()) if occ else 0
            wave: List[Tuple[Request, int, int]] = []
            wave_tokens = 0
            while ((self._resume_q or self._queue)
                   and len(wave) < min(self.prefill_batch, len(free))):
                src, req = self._next_admit()
                if self._admission_defer(
                        req, occ + len(wave) + 1,
                        live + wave_tokens + int(req.prompt.size)):
                    self._defer(req)
                    deferred = True
                    break
                si = free[len(wave)]
                m = self.kv.admit(si, req.prompt, req.prompt.size,
                                  req.max_new_tokens)
                while m is None and self._try_preempt(
                        priority=req.priority, rid=req.request_id,
                        blocked_ticks=req.blocked_ticks):
                    m = self.kv.admit(si, req.prompt, req.prompt.size,
                                      req.max_new_tokens)
                if m is None:          # pool full: wait for retirements
                    self._m_blocked.inc()
                    self._tracer.instant("serving.admission_blocked",
                                         rid=req.request_id)
                    req.blocked_ticks += 1
                    if req.blocked_ticks == 1:
                        self._rlog.event(req.uid, "admission_wait",
                                         engine=self._eid,
                                         reason="pool_full")
                    break
                # remove by IDENTITY: a preemption inside the retry loop
                # may have pushed a new resume entry ahead of req
                src.remove(req)
                self._tables[si] = self.kv.table_row(si, self.max_blocks)
                wave.append((req, si, m))
                wave_tokens += int(req.prompt.size)
            if not wave:
                break
            finished.extend(self._prefill_wave_paged(wave))
        return finished

    def _prefill_wave_paged(self, wave: List[Tuple[Request, int, int]]
                            ) -> List[int]:
        t_adm = self._clock()
        nb = self.prefill_batch
        bucket = min(max(self._bucket(req.prompt.size - m)
                         for req, _, m in wave), self.max_length)
        ids = np.full((nb, bucket), self.pad_token_id, np.int32)
        prefix = np.zeros((nb,), np.int32)
        slens = np.ones((nb,), np.int32)
        # dummy rows keep all-null tables: their writes land in the
        # scratch block and their sampled token is discarded
        tables = np.zeros((nb, self.max_blocks), np.int32)
        temps = np.zeros((nb,), np.float32)
        topk = np.zeros((nb,), np.int32)
        topp = np.ones((nb,), np.float32)
        for r, (req, si, m) in enumerate(wave):
            suffix = req.prompt[m:]
            ids[r, :suffix.size] = suffix
            prefix[r] = m
            slens[r] = suffix.size
            tables[r] = self._tables[si]
            temps[r] = req.sampling.temperature
            topk[r] = req.sampling.top_k
            topp[r] = req.sampling.top_p
            self._m_prefill_computed.inc(int(suffix.size))
            self._m_prefill_total.inc(int(req.prompt.size))
            if req.resume is None:
                self._m_queue_wait.observe((t_adm - req.t_submit) * 1e3)
                req.t_admit = t_adm
                self._rlog.event(req.uid, "admitted", engine=self._eid,
                                 slot=int(si),
                                 queue_wait_ms=(t_adm - req.t_submit)
                                 * 1e3,
                                 blocked_ticks=int(req.blocked_ticks),
                                 prefix_hit_tokens=int(m))
            self._rlog.event(req.uid, "prefill", engine=self._eid,
                             bucket=int(bucket),
                             tokens=int(suffix.size))
        self._m_waves.inc()
        self._f_bucket.labels(engine=self._eid, bucket=str(bucket)).inc()
        self._ticks += 1
        key = jax.random.fold_in(self._base_key, self._ticks)
        self._flush_fresh_scales()
        with self._tracer.span("serving.prefill", bucket=bucket,
                               rows=len(wave)):
            tok, self._cache = self._prefill_fn(
                self._params, self._cache, jnp.asarray(ids),
                jnp.asarray(prefix), jnp.asarray(slens),
                jnp.asarray(tables), jnp.asarray(temps),
                jnp.asarray(topk), jnp.asarray(topp), key)
            tok = np.asarray(tok)
        self._apply_demotions()
        t_tok = self._clock()
        finished: List[int] = []
        for r, (req, si, m) in enumerate(wave):
            ri = req.resume
            if ri is not None:
                # recompute resume: the re-sampled token re-derives the
                # last committed one (greedy: identical); it is DISCARDED
                # and the committed token forced back, so the resumed
                # decode replays no token and drops none
                first = ri.last_token
                slot = _Slot(req.request_id, ri.remaining,
                             t_first=ri.t_first, prompt=ri.orig.prompt,
                             req=ri.orig)
            else:
                first = int(tok[r])
                slot = _Slot(req.request_id, req.max_new_tokens - 1,
                             t_first=t_tok, prompt=req.prompt, req=req)
            self._drafter_reset(si)
            self._slots[si] = slot
            self._active[si] = True
            self._tokens[si] = first
            self._positions[si] = req.prompt.size
            self._temps[si] = temps[r]
            self._topk[si] = topk[r]
            self._topp[si] = topp[r]
            if ri is not None:
                self._rlog.event(req.uid, "resumed", engine=self._eid,
                                 mode="recompute", slot=int(si))
                self._f_resumed.labels(engine=self._eid,
                                       mode="recompute").inc()
                self._tracer.instant("serving.resumed",
                                     rid=req.request_id,
                                     mode="recompute", slot=int(si))
                continue
            self._results[req.request_id].append(first)
            self._m_tokens.inc()
            self._m_ttft.observe((t_tok - req.t_submit) * 1e3)
            if self._perf is not None:
                self._perf.on_ttft((t_tok - req.t_submit) * 1e3)
            self._rlog.event(req.uid, "first_token", engine=self._eid,
                             ttft_ms=(t_tok - req.t_submit) * 1e3)
            reason = self._finish_reason(first, slot, si)
            if reason is not None:
                finished.append(req.request_id)
                self._retire(slot, si, reason, t_tok)
        return finished

    def _prefill_wave(self, wave: List[Request], slots: List[int],
                      bucket: int) -> List[int]:
        t_adm = self._clock()
        nb = self.prefill_batch
        ids = np.full((nb, bucket), self.pad_token_id, np.int32)
        plens = np.ones((nb,), np.int32)
        # dummy rows scatter to the out-of-bounds slot id and are dropped
        slot_ids = np.full((nb,), self.num_slots, np.int32)
        temps = np.zeros((nb,), np.float32)
        topk = np.zeros((nb,), np.int32)
        topp = np.ones((nb,), np.float32)
        for r, (req, si) in enumerate(zip(wave, slots)):
            ids[r, :req.prompt.size] = req.prompt
            plens[r] = req.prompt.size
            slot_ids[r] = si
            temps[r] = req.sampling.temperature
            topk[r] = req.sampling.top_k
            topp[r] = req.sampling.top_p
            self._m_queue_wait.observe((t_adm - req.t_submit) * 1e3)
            self._m_prefill_computed.inc(int(req.prompt.size))
            self._m_prefill_total.inc(int(req.prompt.size))
            req.t_admit = t_adm
            self._rlog.event(req.uid, "admitted", engine=self._eid,
                             slot=int(si),
                             queue_wait_ms=(t_adm - req.t_submit) * 1e3,
                             blocked_ticks=int(req.blocked_ticks),
                             prefix_hit_tokens=0)
            self._rlog.event(req.uid, "prefill", engine=self._eid,
                             bucket=int(bucket),
                             tokens=int(req.prompt.size))
        self._m_waves.inc()
        self._f_bucket.labels(engine=self._eid, bucket=str(bucket)).inc()
        self._ticks += 1
        key = jax.random.fold_in(self._base_key, self._ticks)
        with self._tracer.span("serving.prefill", bucket=bucket,
                               rows=len(wave)):
            tok, self._cache = self._prefill_fn(
                self._params, self._cache, jnp.asarray(ids),
                jnp.asarray(plens), jnp.asarray(slot_ids),
                jnp.asarray(temps), jnp.asarray(topk),
                jnp.asarray(topp), key)
            tok = np.asarray(tok)
        t_tok = self._clock()
        finished: List[int] = []
        for r, (req, si) in enumerate(zip(wave, slots)):
            slot = _Slot(req.request_id, req.max_new_tokens - 1,
                         t_first=t_tok, prompt=req.prompt, req=req)
            self._drafter_reset(si)
            self._slots[si] = slot
            self._active[si] = True
            self._tokens[si] = tok[r]
            self._positions[si] = plens[r]
            self._temps[si] = temps[r]
            self._topk[si] = topk[r]
            self._topp[si] = topp[r]
            self._results[req.request_id].append(int(tok[r]))
            self._m_tokens.inc()
            self._m_ttft.observe((t_tok - req.t_submit) * 1e3)
            if self._perf is not None:
                self._perf.on_ttft((t_tok - req.t_submit) * 1e3)
            self._rlog.event(req.uid, "first_token", engine=self._eid,
                             ttft_ms=(t_tok - req.t_submit) * 1e3)
            reason = self._finish_reason(int(tok[r]), slot, si)
            if reason is not None:
                finished.append(req.request_id)
                self._retire(slot, si, reason, t_tok)
        return finished

    def _finish_reason(self, tok: int, slot: _Slot,
                       i: int) -> Optional[str]:
        """None while the request keeps going, else the retirement
        reason (the ``serving.retired`` counter's label)."""
        if self.eos_token_id is not None and tok == self.eos_token_id:
            return "eos"
        if slot.remaining <= 0:
            return "max_new_tokens"
        if int(self._positions[i]) >= self.max_length:
            return "max_length"
        return None

    def _release(self, i: int):
        if self.paged:
            self.kv.release(i)
        self._clear_slot(i)

    def _clear_slot(self, i: int):
        """Reset slot ``i``'s host mirrors WITHOUT touching the block
        pool — preemption already moved/freed the chain through
        ``swap_out``/``preempt_free``; ``_release`` adds the
        ``kv.release`` for normal retirement."""
        if self.paged:
            self._tables[i] = 0
        self._drafter_reset(i)
        self._slots[i] = None
        self._active[i] = False
        self._tokens[i] = self.pad_token_id
        self._positions[i] = 0
        self._temps[i] = 0.0
        self._topk[i] = 0
        self._topp[i] = 1.0
