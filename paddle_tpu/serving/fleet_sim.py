"""Device-free fleet simulator: the REAL control plane on a cost-model
clock (ISSUE 17 tentpole c).

``SimEngine`` is a :class:`~paddle_tpu.serving.engine.ServingEngine`
with the device removed and NOTHING else replaced: the same ``submit``
/ ``step`` / ``drain`` scheduler, the same paged admission
(``_admit_paged``), the same :class:`~paddle_tpu.serving.kv_cache.
BlockManager` pool (prefix trie, COW, reservations, host tier), the
same preemption machinery and the same predictive-admission gate — but
every jitted dispatch is replaced by the roofline cost model's
prediction for that tick, and the engine's ``_clock`` indirection (the
one time source every SLO stamp reads through) returns a simulated
clock that those predictions advance.  Tokens are synthesized by a
deterministic hash, so a trace replays byte-identically however fast
the host runs it.

``FleetSim`` puts N SimEngines behind the REAL
:class:`~paddle_tpu.serving.router.ReplicaRouter` — predictive
admission, the priced hold queue and elastic add/drain/retire all
execute the production code paths — which is what lets a ≥100k-request,
≥16-replica heavy-tail scenario replay in seconds of CPU wall and
answer capacity questions (replica counts, admission policies, SLO
settings) without a device.

What the simulator deliberately does NOT model (BASELINE.md
"Simulated-clock accounting conventions"): compile/retrace time,
host-swap wall jitter, and any measured/predicted residual — measured
IS predicted here, so the perf layer sees ratio 1.0 everywhere and the
drift detectors stay quiet by construction.  Sim milliseconds are the
cost model's domain; never compare them against wall milliseconds
without the FLAGS_serving_admission_calib bridge.

Unsupported engine modes raise at construction: chunked prefill,
speculative decoding and meshes change the dispatch structure the
simulator replaces, and quantized caches only change device bytes the
sim spec already captures in ``kv_token_bytes``.

CLI::

    python -m paddle_tpu.serving.fleet_sim --requests 100000 \
        --replicas 16 --admission predictive

runs the heavy-tail scale scenario twice and gates the two runs'
signatures byte-identical (the determinism contract the bench row and
the loadgen ``fleet_sim`` smoke mode also enforce).
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .. import flags as _flags
from ..observability import costmodel as _cm
from ..observability import tracing as _obs
from . import loadgen as _loadgen
from .engine import ServingEngine, _Slot
from .kv_cache import BlockManager
from .router import ReplicaRouter

__all__ = ["SimSpec", "SimEngine", "FleetSim", "fleet_load_spec",
           "run_fleet", "fleet_signature", "main"]

#: synthesized-token alphabet (any fixed size works; matching a real
#: tokenizer's vocab keeps prompt/output token ids in a familiar range)
_SIM_VOCAB = 50257


@dataclasses.dataclass(frozen=True)
class SimSpec:
    """The simulated model: exactly the static byte/FLOP inputs the
    roofline :class:`~paddle_tpu.observability.costmodel.CostModel`
    needs — nothing else about the model matters to the scheduler."""

    name: str
    weight_bytes: int           # params footprint streamed per tick
    n_params: int               # dense FLOP model: 2*N per token
    kv_token_bytes: float       # HBM bytes one live context token costs

    @classmethod
    def default(cls) -> "SimSpec":
        """A ~940M-param bf16 decoder (the committed llama_940m bench
        shape): 24 layers x 2 (K+V) x 4 kv-heads x 64 head-dim = 12288
        cache elements per token at 2 bytes each."""
        return cls(name="sim_940m", weight_bytes=1_880_000_000,
                   n_params=940_000_000,
                   kv_token_bytes=float(24 * 2 * 4 * 64 * 2))

    @classmethod
    def from_engine(cls, engine: ServingEngine) -> "SimSpec":
        """Clone a live engine's cost-model inputs, so a SimEngine
        predicts exactly what the real engine's perf layer predicts —
        the sim-vs-engine agreement gate builds its twin this way."""
        if engine._perf is None:
            raise ValueError(
                "SimSpec.from_engine needs the engine's cost model: "
                "construct the engine with FLAGS_perf_model='on'")
        m = engine._perf.model
        return cls(name=f"from_engine_{engine._eid}",
                   weight_bytes=m.weight_bytes, n_params=m.n_params,
                   kv_token_bytes=m.kv_token_bytes)


class SimEngine(ServingEngine):
    """ServingEngine minus the device (see module docstring).

    The constructor deliberately does NOT chain to
    ``ServingEngine.__init__`` — there is no model, no params, no
    jitted program — but it builds the identical host-side state
    catalog, so every inherited scheduler method (``submit``, ``step``,
    ``_admit_paged``, preemption, cancel, metrics, the predictive
    admission gate) runs unmodified.  Only four methods are overridden:
    ``_step_inner`` and ``_prefill_wave_paged`` swap the dispatch for a
    cost-model prediction + simulated-clock advance, and the two host-
    tier hooks account swap bytes without moving payloads."""

    def __init__(self, spec: SimSpec, *, num_slots: int = 8,
                 max_length: int = 1024, prefill_batch: int = 4,
                 seed: int = 0, block_len: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 preempt: Optional[str] = None,
                 host_blocks: Optional[int] = None,
                 eos_token_id: Optional[int] = None,
                 pad_token_id: int = 0,
                 profile: Optional[_cm.HardwareProfile] = None):
        self.sim_spec = spec
        self.model = None
        self.config = None
        self.num_slots = int(num_slots)
        self.max_length = int(max_length)
        self.eos_token_id = eos_token_id
        self.pad_token_id = int(pad_token_id)
        self.prefill_batch = int(prefill_batch)
        self._int8_weights = False
        # the simulator is paged-only: the BlockManager IS the part of
        # the memory system worth simulating (admission blocking,
        # prefix hits, preemption, the host tier)
        self.paged = True
        self.kv_dtype = "bf16"
        self.quantized = False
        if bool(_flags.flag("serving_chunked_prefill")):
            raise NotImplementedError(
                "SimEngine does not model chunked prefill (the mixed "
                "step's chunk cursor is a dispatch-structure feature)")
        if bool(_flags.flag("serving_spec_decode")):
            raise NotImplementedError(
                "SimEngine does not model speculative decoding (accept "
                "rates depend on real logits)")
        self.chunked = False
        self.prefill_chunk = int(_flags.flag("serving_prefill_chunk"))
        self._chunk_policy = "prefill"
        self.spec = False
        self.spec_k = int(_flags.flag("serving_spec_k"))
        self.preempt = str(_flags.flag("serving_preempt")
                           if preempt is None else preempt)
        if self.preempt not in ("off", "swap", "recompute"):
            raise ValueError(
                f"preempt must be off|swap|recompute, got "
                f"{self.preempt!r}")
        self._preempt_after = int(_flags.flag("serving_preempt_after"))
        hb = int(_flags.flag("serving_host_blocks")
                 if host_blocks is None else host_blocks)
        if self.preempt == "swap" and hb < 1:
            raise ValueError(
                "preempt='swap' needs a host tier: pass host_blocks "
                "(or FLAGS_serving_host_blocks) >= 1")
        self._host_blocks = hb
        self.mesh = None
        self._init_metrics()
        bl = int(block_len or _flags.flag("kv_cache_block_len"))
        if self.max_length % bl:
            raise ValueError(
                f"max_length {self.max_length} is not a multiple of "
                f"block_len {bl}")
        self.block_len = bl
        self.max_blocks = self.max_length // bl
        nb = int(num_blocks or _flags.flag("kv_cache_num_blocks")
                 or self.num_slots * self.max_blocks + 1)
        self.kv = BlockManager(
            nb, bl,
            prefix_cache=bool(_flags.flag("serving_prefix_cache")
                              if prefix_cache is None else prefix_cache),
            kv_dtype=self.kv_dtype,
            host_blocks=self._host_blocks)
        self._sim_block_nbytes = int(round(spec.kv_token_bytes * bl))
        self.kv.set_block_nbytes({"bf16": self._sim_block_nbytes})
        self._tables = np.zeros((self.num_slots, self.max_blocks),
                                np.int32)
        self._params = None
        self._cache = None               # the pool has no device twin
        self._pending_demote: List[int] = []
        # COW privatisation is pool bookkeeping here; the device copy
        # the real engine dispatches has no simulated cost of its own
        # (it rides inside the tick the cost model already prices)
        self._cow_fn = lambda cache, src, dst: cache
        self._tick_swap_bytes = 0
        if self._host_blocks > 0:
            self.kv.on_swap_out = self._host_swap_out
            self.kv.on_swap_in = self._host_swap_in
        s = self.num_slots
        self._tokens = np.zeros((s,), np.int32)
        self._positions = np.zeros((s,), np.int32)
        self._active = np.zeros((s,), bool)
        self._temps = np.zeros((s,), np.float32)
        self._topk = np.zeros((s,), np.int32)
        self._topp = np.ones((s,), np.float32)
        self._slots: List[Optional[_Slot]] = [None] * s
        self._prefill = None
        self._queue = deque()
        self._swap_resume = []
        self._resume_q = deque()
        self._preempt_log: List[Dict[str, object]] = []
        self._results: Dict[int, List[int]] = {}
        self._next_rid = 0
        self._base_key = None            # tokens are hash-synthesized
        self._seed = int(seed)
        self._ticks = 0
        # the simulated clock: every SLO stamp reads _clock(), and the
        # overridden tick bodies advance _now_s by the model's
        # prediction — sim seconds ARE predicted milliseconds / 1e3
        self._now_s = 0.0
        self._clock = lambda: self._now_s
        self._kernel_preflight_cache = None
        self._step_fn = None
        self._prefill_fn = None
        self._linted = True              # no jitted program to lint
        self._cost = _cm.CostModel(
            profile or _cm.resolve_profile(),
            weight_bytes=spec.weight_bytes, n_params=spec.n_params,
            kv_token_bytes=spec.kv_token_bytes,
            num_slots=self.num_slots)
        self._perf = (_cm.TickAttribution(self._cost,
                                          engine_id=self._eid)
                      if _flags.flag("perf_model") == "on" else None)

    # -- simulated time ----------------------------------------------------

    @property
    def sim_time_s(self) -> float:
        """This replica's simulated clock (cost-model seconds)."""
        return self._now_s

    def _sim_token(self, slot: _Slot, i: int) -> int:
        """Deterministic token synthesis: a pure hash of (request id,
        position, seed), steered off the EOS id so the trace's
        max_new_tokens — not sampling luck — decides every length."""
        pos = int(self._positions[i])
        tok = (slot.rid * 1_000_003 + pos * 10_007
               + self._seed * 7_919) % _SIM_VOCAB
        if self.eos_token_id is not None and tok == self.eos_token_id:
            tok = (tok + 1) % _SIM_VOCAB
        return tok

    # -- overridden tick bodies --------------------------------------------

    def _step_inner(self) -> List[int]:
        """The real ``_step_inner`` with the jitted decode dispatch
        replaced by a cost-model prediction: identical admission,
        identical paged bookkeeping (chain growth, COW, tables),
        identical retirement — the simulated clock advances by the
        tick's predicted milliseconds and ``_perf_tick`` records
        measured == predicted (ratio 1.0, no drift, byte-stable
        perf signature)."""
        finished = self._admit()
        occ = int(self._active.sum())
        self._set_occupancy(occ)
        if not occ:
            return finished
        self._ticks += 1
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            self._grow_row_for_writes(i, int(self._positions[i]))
        # inactive rows hold position 0 (_clear_slot), so the full sum
        # IS the live-token depth — no boolean-mask temporary
        live = int(self._positions.sum())
        swap_bytes, self._tick_swap_bytes = self._tick_swap_bytes, 0
        pred = self._cost.predicted_tick_ms(occ, live,
                                            swap_bytes=swap_bytes)
        self._now_s += pred / 1e3
        now = self._clock()
        self._m_step_ms.observe(pred)
        if self._perf is not None:
            # same memo key as the prediction above: measured ==
            # predicted exactly, ratio 1.0, detectors quiet
            self._perf.on_tick(pred, occ=occ, live_tokens=live,
                               swap_bytes=swap_bytes)
        nxt = np.full((self.num_slots,), self.pad_token_id, np.int32)
        for i, slot in enumerate(self._slots):
            if slot is not None:
                nxt[i] = self._sim_token(slot, i)
        finished.extend(self._advance_decode(nxt, now))
        return finished

    def _prefill_wave_paged(self, wave) -> List[int]:
        """The real paged wave prefill minus the device: identical
        admission bookkeeping and lifecycle events, first tokens
        synthesized, and the simulated clock advanced by the wave's
        modeled cost — priced as one tick whose chunk term carries the
        computed suffix tokens (prefix hits ride free, exactly like the
        real wave's suffix-only compute)."""
        t_adm = self._clock()
        bucket = min(max(self._bucket(req.prompt.size - m)
                         for req, _, m in wave), self.max_length)
        suffix_tokens = 0
        for req, si, m in wave:
            suffix = int(req.prompt.size) - int(m)
            suffix_tokens += suffix
            self._m_prefill_computed.inc(suffix)
            self._m_prefill_total.inc(int(req.prompt.size))
            if req.resume is None:
                self._m_queue_wait.observe((t_adm - req.t_submit) * 1e3)
                req.t_admit = t_adm
                self._rlog.event(req.uid, "admitted", engine=self._eid,
                                 slot=int(si),
                                 queue_wait_ms=(t_adm - req.t_submit)
                                 * 1e3,
                                 blocked_ticks=int(req.blocked_ticks),
                                 prefix_hit_tokens=int(m))
            self._rlog.event(req.uid, "prefill", engine=self._eid,
                             bucket=int(bucket), tokens=suffix)
        self._m_waves.inc()
        self._f_bucket.labels(engine=self._eid, bucket=str(bucket)).inc()
        self._ticks += 1
        pred = self._cost.predicted_tick_ms(
            len(wave), suffix_tokens, chunk_tokens=suffix_tokens)
        self._now_s += pred / 1e3
        t_tok = self._clock()
        finished: List[int] = []
        for req, si, m in wave:
            ri = req.resume
            if ri is not None:
                first = ri.last_token
                slot = _Slot(req.request_id, ri.remaining,
                             t_first=ri.t_first, prompt=ri.orig.prompt,
                             req=ri.orig)
            else:
                slot = _Slot(req.request_id, req.max_new_tokens - 1,
                             t_first=t_tok, prompt=req.prompt, req=req)
            self._slots[si] = slot
            self._active[si] = True
            self._positions[si] = req.prompt.size
            self._temps[si] = req.sampling.temperature
            self._topk[si] = req.sampling.top_k
            self._topp[si] = req.sampling.top_p
            if ri is not None:
                self._tokens[si] = first
                self._rlog.event(req.uid, "resumed", engine=self._eid,
                                 mode="recompute", slot=int(si))
                self._f_resumed.labels(engine=self._eid,
                                       mode="recompute").inc()
                self._tracer.instant("serving.resumed",
                                     rid=req.request_id,
                                     mode="recompute", slot=int(si))
                continue
            first = self._sim_token(slot, si)
            self._tokens[si] = first
            self._results[req.request_id].append(first)
            self._m_tokens.inc()
            self._m_ttft.observe((t_tok - req.t_submit) * 1e3)
            if self._perf is not None:
                self._perf.on_ttft((t_tok - req.t_submit) * 1e3)
            self._rlog.event(req.uid, "first_token", engine=self._eid,
                             ttft_ms=(t_tok - req.t_submit) * 1e3)
            reason = self._finish_reason(first, slot, si)
            if reason is not None:
                finished.append(req.request_id)
                self._retire(slot, si, reason, t_tok)
        return finished

    # -- host-tier hooks (byte accounting only) ----------------------------

    def _host_swap_out(self, pairs):
        tier = self.kv.host_tier
        for bid, hid in pairs:
            tier.put(hid, None)          # the payload is virtual
            self._tick_swap_bytes += self._sim_block_nbytes
            self._m_swap_out_bytes.inc(self._sim_block_nbytes)

    def _host_swap_in(self, pairs):
        for hid, bid in pairs:
            self._tick_swap_bytes += self._sim_block_nbytes
            self._m_swap_in_bytes.inc(self._sim_block_nbytes)

    # -- device-only surfaces ----------------------------------------------

    def lint_step(self):
        """No jitted program, nothing to lint."""
        return []

    def kernel_preflight(self):
        raise NotImplementedError(
            "SimEngine has no device programs to preflight")


class FleetSim:
    """N SimEngine replicas behind the real ReplicaRouter (same
    ``submit``/``step``/``drain``/``result`` surface, so
    ``loadgen.replay`` drives it unchanged).  Per-replica simulated
    clocks advance independently — replicas tick in lockstep but a
    loaded replica's tick costs more — and the fleet's simulated wall
    is the slowest replica's clock."""

    def __init__(self, num_replicas: int = 16,
                 spec: Optional[SimSpec] = None, *,
                 policy: Optional[str] = None, seed: int = 0,
                 **engine_kwargs: Any):
        self.spec = spec or SimSpec.default()
        self.engines = [SimEngine(self.spec, seed=seed + i,
                                  **engine_kwargs)
                        for i in range(int(num_replicas))]
        self.router = ReplicaRouter(engines=self.engines, policy=policy)

    # the router surface loadgen.replay expects
    def submit(self, *a: Any, **kw: Any) -> int:
        return self.router.submit(*a, **kw)

    def step(self) -> List[int]:
        return self.router.step()

    def drain(self):
        return self.router.drain()

    def result(self, rid: int) -> List[int]:
        return self.router.result(rid)

    @property
    def pending_held(self) -> int:
        return self.router.pending_held

    @property
    def sim_wall_s(self) -> float:
        """Fleet simulated wall: the slowest replica's clock."""
        return max(e.sim_time_s for e in self.engines)

    def worker_clocks(self) -> Dict[str, float]:
        """Per-replica simulated clocks in ms, keyed ``replica<i>`` —
        the fleet-sim analogue of the multihost plane's stitched
        per-worker clocks.  No wire time is modelled, so these ARE the
        exact offsets a plane-side estimator would recover (BASELINE.md
        "Fleet observability conventions")."""
        return {f"replica{i}": round(e.sim_time_s * 1e3, 6)
                for i, e in enumerate(self.engines)}

    def slo_by_worker(self, slo: Dict[str, Any]) -> Dict[str, Any]:
        """A replay report's ``by_worker`` SLO attribution re-keyed
        from per-process ``engine:<id>`` onto run-stable ``replica<i>``
        names — the same federated attribution the multihost plane
        reports keyed by worker name, proving the one slo_report code
        path serves both clock domains."""
        eid_to_replica = {f"engine:{e._eid}": f"replica{i}"
                          for i, e in enumerate(self.engines)}
        byw = slo.get("by_worker") or {}
        return {eid_to_replica.get(k, k): v
                for k, v in sorted(byw.items())}

    def report(self) -> Dict[str, Any]:
        return {
            "spec": dataclasses.asdict(self.spec),
            "replicas": len(self.engines),
            "sim_wall_s": round(self.sim_wall_s, 6),
            "per_replica": [
                {"ticks": e._ticks,
                 "sim_time_s": round(e.sim_time_s, 6),
                 "requests_finished": int(e._m_finished.value()),
                 "tokens_generated": int(e._m_tokens.value())}
                for e in self.engines],
            "router": self.router.metrics()["aggregate"]["control_plane"],
        }


def fleet_signature(fleet: FleetSim,
                    replay_report: Dict[str, Any]) -> str:
    """sha256 over the deterministic state of one fleet replay: the
    structural request timeline, every replica's scheduler counters +
    simulated clock + preemption log + perf signature, and the sampled
    outputs.  Engine/router ids and host wall-clock fields are
    excluded, so two identical-seed runs in one process (fresh engines,
    new ids) must produce byte-identical signatures."""
    body = {
        "timeline": replay_report["signature"],
        "outputs": [o if o is None else list(map(int, o))
                    for o in replay_report["outputs"]],
        "per_replica": [
            {"ticks": e._ticks,
             "clock_ms": round(e.sim_time_s * 1e3, 6),
             "preempt": e.preempt_signature(),
             "perf": (_cm.perf_signature(e._perf.report())
                      if e._perf is not None else None)}
            for e in fleet.engines],
        "decisions": fleet.router.metrics()["aggregate"]["control_plane"][
            "decisions"],
    }
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


def fleet_load_spec(requests: int, *, seed_gap: float = 0.13,
                    replicas: int = 16,
                    num_slots: int = 8) -> _loadgen.LoadSpec:
    """The heavy-tail scale scenario: Zipf prompt/output lengths (many
    short requests, a long tail out to 8x the median), Poisson arrivals
    tuned just under the fleet's token service rate so queues stay
    loaded but bounded, and a Zipf tenant mix sharing prompt prefixes
    (the prefix trie sees realistic hit rates at scale)."""
    # service ~= replicas*num_slots tokens per fleet tick; the mean
    # Zipf output is ~14 tokens, so gap = 0.13 ticks lands near 85%
    # decode utilization before prefill waves claim their ticks
    gap = seed_gap * (16 * 8) / max(1, replicas * num_slots)
    return _loadgen.LoadSpec(
        n_requests=int(requests), vocab=256,
        arrival="poisson", mean_gap=gap,
        prompt_dist="zipf", prompt_buckets=(8, 16, 32, 64, 224),
        prompt_zipf_a=1.1, prompt_max=224,
        output_dist="zipf", output_buckets=(4, 8, 16, 32, 64),
        output_zipf_a=1.1, output_max=64,
        tenants=8, tenant_zipf_a=1.2, shared_prefix_len=8)


def run_fleet(*, requests: int = 100_000, replicas: int = 16,
              num_slots: int = 8, max_length: int = 512,
              admission: str = "predictive", policy: str = "least_loaded",
              preempt: str = "off", host_blocks: int = 0,
              seed: int = 0, spec: Optional[SimSpec] = None,
              profile: str = "v5e",
              max_ticks: Optional[int] = None) -> Dict[str, Any]:
    """One deterministic fleet replay of the heavy-tail scenario.
    Returns the loadgen replay report plus the fleet report and the
    run's :func:`fleet_signature`.  Flags are scoped to the run and
    restored on exit."""
    saved = {k: _flags.flag(k) for k in
             ("serving_admission", "perf_model", "request_log_max_requests",
              "serving_chunked_prefill", "serving_spec_decode")}
    # keep the scale run's memory bounded: the rolling request-log
    # window covers the trace tail, plenty for the structural signature
    _flags.set_flags({
        "serving_admission": admission,
        "perf_model": "on",
        "serving_chunked_prefill": False,
        "serving_spec_decode": False,
        "request_log_max_requests": min(8192, max(4096, requests // 8))})
    tracer = _obs.get_tracer()
    saved_trace = tracer.enabled
    # span tracing at 100k-request scale is pure host overhead (the
    # run's artifact is the fleet signature, not a trace); the request
    # log keeps its structural timeline either way
    tracer.enabled = False
    try:
        fleet = FleetSim(replicas, spec, policy=policy, seed=seed,
                         num_slots=num_slots, max_length=max_length,
                         preempt=preempt, host_blocks=host_blocks,
                         profile=_cm.PROFILES[profile])
        load = _loadgen.generate_load(
            fleet_load_spec(requests, replicas=replicas,
                            num_slots=num_slots), seed=seed)
        t0 = time.perf_counter()
        rep = _loadgen.replay(fleet, load, max_ticks=max_ticks)
        wall = time.perf_counter() - t0
        out = {
            "requests": requests,
            "replicas": replicas,
            "admission": admission,
            "ticks": rep["ticks"],
            "generated_tokens": rep["generated_tokens"],
            "rejected": rep["rejected"],
            "host_wall_s": round(wall, 3),
            "sim_wall_s": round(fleet.sim_wall_s, 3),
            "sim_tok_per_s": round(
                rep["generated_tokens"] / max(fleet.sim_wall_s, 1e-9), 3),
            "goodput": rep["slo"].get("goodput"),
            # federated attribution under simulated clocks (ISSUE 19):
            # the same slo_report by_worker join the multihost plane
            # uses, re-keyed onto run-stable replica names
            "slo_by_worker": fleet.slo_by_worker(rep["slo"]),
            "worker_clocks_ms": fleet.worker_clocks(),
            "fleet": fleet.report(),
            "signature": fleet_signature(fleet, rep),
        }
        return out
    finally:
        tracer.enabled = saved_trace
        _flags.set_flags(saved)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="device-free serving fleet simulator (cost-model "
                    "clock; see module docstring)")
    p.add_argument("--requests", type=int, default=100_000)
    p.add_argument("--replicas", type=int, default=16)
    p.add_argument("--num-slots", type=int, default=8)
    p.add_argument("--max-length", type=int, default=512)
    p.add_argument("--admission", default="predictive",
                   choices=("queue_depth", "predictive"))
    p.add_argument("--policy", default="least_loaded",
                   choices=("prefix", "least_loaded", "round_robin"))
    p.add_argument("--preempt", default="off",
                   choices=("off", "swap", "recompute"))
    p.add_argument("--host-blocks", type=int, default=0)
    p.add_argument("--profile", default="v5e",
                   choices=sorted(_cm.PROFILES),
                   help="roofline profile the simulated replicas run "
                        "on (the sim clock's time domain)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--runs", type=int, default=2,
                   help="replays to run; >1 gates byte-stable "
                        "signatures across runs")
    args = p.parse_args(argv)
    sigs: List[str] = []
    for run in range(max(1, args.runs)):
        rep = run_fleet(requests=args.requests, replicas=args.replicas,
                        num_slots=args.num_slots,
                        max_length=args.max_length,
                        admission=args.admission, policy=args.policy,
                        preempt=args.preempt, profile=args.profile,
                        host_blocks=args.host_blocks, seed=args.seed)
        sigs.append(rep["signature"])
        slim = {k: v for k, v in rep.items() if k != "fleet"}
        print(json.dumps({"run": run, **slim}, indent=2, default=str))
    if len(set(sigs)) != 1:
        print("FLEET SIM NON-DETERMINISTIC: signatures differ across "
              "identical-seed runs")
        return 1
    print(f"signature stable across {len(sigs)} run(s): {sigs[0][:16]}…")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
