"""Paged KV cache — block allocator + prefix cache for the serving engine.

The vLLM PagedAttention memory model, TPU-shaped: instead of one
contiguous ``(max_length, Hkv, D)`` cache row per slot (capacity paid at
worst-case length, identical system prompts stored once per request), the
device cache is ONE pooled array ``(L, 2, num_blocks, block_len, Hkv, D)``
of fixed-size KV blocks, and each slot owns a *block table* — the ordered
list of physical block ids that back its logical token positions.  Cache
cost becomes ``live tokens + shared prefixes`` instead of
``num_slots × max_length``.

Division of labour:

  * **this module is pure host-side bookkeeping** — a free-list allocator,
    per-slot block chains, refcounts, a prefix trie, an eviction LRU, and
    the numpy block-table rows the engine uploads each tick.  Nothing here
    touches the device; the pool array itself is created by
    :func:`init_paged_kv_cache` and carried through the engine's jitted
    step exactly like the contiguous cache (the block table rides along as
    a tiny traced ``(num_slots, max_blocks)`` int32 input, so allocation
    changes never retrace);
  * the device-side dereference lives in the attention paths: the Pallas
    flash-decode kernel takes the table as a second scalar-prefetch
    operand and its KV-chunk index maps look physical blocks up *before*
    each grid step (ops/pallas/decode_attention.py), and the XLA math path
    gathers ``pool[block_table]`` into the contiguous layout
    (ops/attention.py).  Writes are batched scatters to
    ``(physical_block, offset)`` pairs (models/llama.py ``decode``).

Conventions the device side relies on:

  * **block 0 is the null block** — never allocated to a request.  Block
    tables are zero-filled beyond a slot's allocated chain, so every table
    entry is always a valid physical index: reads of the dead tail land in
    scratch (and are masked by position anyway), and writes from prompt
    padding are steered to the null block instead of needing a dropped
    scatter.  Its contents are junk by design;
  * a slot's table covers positions ``[0, len(chain) · block_len)``; the
    engine guarantees the block holding position ``pos + s - 1`` is
    allocated before any step that reads or writes it (``ensure_capacity``
    runs on the host before dispatch);
  * full *prompt* blocks are immutable once written (generation appends at
    positions ≥ prompt length, which live in later blocks) — that is what
    makes them safely shareable and trie-cacheable without copies.

Prefix cache: full prompt blocks are registered in a chain-keyed trie
(``(parent_block_id, block tokens) -> block_id``, the vLLM hash-chain
scheme with exact keys instead of hashes).  A later request whose prompt
starts with the same token blocks *adopts* the existing chain — refcount
bump, zero recompute, zero new HBM — and its prefill runs only the
suffix.  Matching is capped at ``(plen - 1) // block_len`` blocks so at
least one real token always remains to produce the first logits.  Retired
chains whose blocks are trie-registered are kept (refcount 0) on an LRU
list and revived on later hits; allocation under pressure evicts the LRU
head, cascading the trie unregistration through its descendants so a
reused block id can never satisfy a stale lookup.

Copy-on-write: ``ensure_writable`` is the guard a writer calls before
mutating a block mid-chain — if the block is shared (refcount > 1) it is
swapped for a fresh private copy and the (src, dst) pair is returned so
the caller can issue the device copy.  In the current engine flow full
blocks are immutable and tail blocks are private, so this never fires;
it is the hook forking features (beam/speculative decode, n>1 sampling)
build on, and it is unit-tested at this layer.

Rollback: ``truncate_to`` is the inverse of ``ensure_capacity`` — the
speculative-decode engine writes a draft window ahead of the committed
position and, when verification rejects a suffix, rolls the chain back so
blocks that only held rejected tokens return to the pool (reservation
re-credited, shared blocks deref'd not freed, and every trie registration
at or past the cut cascade-invalidated so a stale block can never serve a
prefix hit afterwards).

Admission is reservation-based so mid-flight allocation cannot fail: a
request is admitted only if ``free + evictable - already-reserved`` covers
every block it could ever need (prompt + max_new_tokens, minus the shared
prefix); the reservation is consumed block-by-block as the sequence
deepens and released with the slot.  There is no fragmentation (any free
block serves any slot), so the check is exact.

Tiering (ISSUE 16): ``host_blocks > 0`` arms a second, host-RAM tier — a
:class:`HostTier` pool of pinned host buffers the same block geometry as
the device pool.  Two flows feed it, both pure host-side bookkeeping plus
one device copy the engine performs through the ``on_swap_out`` /
``on_swap_in`` hooks (exactly the ``on_demote`` pattern the mixed-mode
int8 demotion already uses):

  * **demote-on-evict**: when pool pressure would DROP the LRU head's
    content, the block instead demotes HBM→host — its payload moves to a
    host buffer and its full TOKEN PATH (the tuple of per-block token
    tuples from the prompt root) keys a host-side trie.  A later
    admission whose prompt walk runs off the end of the device trie
    continues into the host trie and PROMOTES each hit: a fresh device
    block is allocated from the request's reservation, the payload is
    copied back, and the block re-registers in the device trie — so the
    prefix cache's effective capacity is host-RAM-sized, not HBM-sized.
    Token paths key the host trie (not parent block ids) because the
    physical parent id dies at demotion; a path is in AT MOST ONE tier
    at a time, and unreachable host entries (an ancestor dropped from
    both tries) are cascade-freed exactly like the device trie's;
  * **swap-out** (preemption): :meth:`swap_out` tears down a victim
    slot's allocation — private blocks (refcount 1) move payload+dtype
    to PINNED host buffers recorded in a resume record, shared blocks
    keep this slot's reference so the chain survives other owners'
    releases — and :meth:`resume_swapped` rebuilds the chain later.
    Record entries are keyed by host id, never by token path: a swapped
    chain can NEVER serve a prefix hit until promoted back.  Pinned
    buffers are not evictable; demoted trie entries are (LRU), so swap
    capacity always wins over cached-prefix capacity.
"""

from __future__ import annotations

import itertools
from collections import OrderedDict, deque
from collections.abc import Mapping
from typing import Deque, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import observability as _obs

__all__ = ["BlockManager", "HostTier", "NULL_BLOCK", "init_paged_kv_cache"]

NULL_BLOCK = 0          # physical block 0: pad/dummy scratch, never allocated
_ROOT = -1              # trie parent id of a prompt's first block

# pool instances share the default registry; the ``pool`` label keeps
# their series independent
_POOL_IDS = itertools.count()


class _StatsView(Mapping):
    """The historical ``BlockManager.stats`` dict, now a live read-through
    over the shared metrics registry — same keys, same int values, so
    ``m.stats["evictions"]`` keeps working while the counters flow into
    ``observability.snapshot()`` / Prometheus exposition like everything
    else."""

    _KEYS = ("prefix_lookups", "prefix_hit_blocks", "prefix_hit_tokens",
             "evictions", "cow_copies", "peak_blocks_in_use",
             "quantized_blocks", "host_demotions", "host_promotions",
             "swapped_out_blocks", "swapped_in_blocks",
             "exported_blocks", "imported_blocks")

    def __init__(self, mgr: "BlockManager"):
        self._mgr = mgr

    def __getitem__(self, key: str) -> int:
        if key == "peak_blocks_in_use":
            return self._mgr._peak
        if key == "quantized_blocks":
            return self._mgr.quantized_blocks()
        return int(self._mgr._counters[key].value())

    def __iter__(self):
        return iter(self._KEYS)

    def __len__(self):
        return len(self._KEYS)

    def __repr__(self):
        return repr(dict(self))


def init_paged_kv_cache(config, num_blocks: int, block_len: int, dtype=None,
                        quantized: bool = False):
    """Pooled paged cache: (L, 2, num_blocks, block_len, kv_heads, head_dim)
    — the contiguous cache's (B, max_len) plane re-cut into fixed blocks.

    ``quantized``: the int8 pool — a two-leaf pytree
    ``{"kv": int8 (L, 2, nb, bl, Hkv, D), "scale": f32 (L, 2, nb, Hkv)}``
    where ``scale[l, kv, b, h]`` is physical block ``b``'s
    per-kv-head symmetric dequant factor (absmax/127, running-max across
    scatter-time writes).  Zero scale == empty block (dequantizes to 0).
    The pytree threads through the engine's jitted step exactly like the
    plain array (same argnum, donated wholesale).
    """
    import jax.numpy as jnp

    if quantized:
        return {
            "kv": jnp.zeros((config.num_hidden_layers, 2, num_blocks,
                             block_len, config.num_key_value_heads,
                             config.head_dim), jnp.int8),
            "scale": jnp.zeros((config.num_hidden_layers, 2, num_blocks,
                                config.num_key_value_heads), jnp.float32),
        }
    dt = dtype if dtype is not None else config.dtype
    return jnp.zeros((config.num_hidden_layers, 2, num_blocks, block_len,
                      config.num_key_value_heads, config.head_dim), dt)


class _SlotAlloc:
    __slots__ = ("chain", "reserved_left")

    def __init__(self, chain: List[int], reserved_left: int):
        self.chain = chain
        self.reserved_left = reserved_left


# a block's full token path from the prompt root: one tuple of tokens
# per block, root first — the tier-stable identity of its contents
_Path = Tuple[Tuple[int, ...], ...]


class HostTier:
    """Pinned host-RAM block pool — the HBM pool's second tier.

    Capacity is counted in blocks of the SAME geometry as the device
    pool; each live host id owns one block-shaped payload (a host numpy
    pytree the engine reads off / writes back to the device through the
    manager's ``on_swap_out`` / ``on_swap_in`` hooks).  The tier itself
    is a dumb id allocator + payload store: WHICH ids are evictable
    (demoted prefix-trie blocks) versus pinned (preemption swap records)
    is the :class:`BlockManager`'s call — it only ever reclaims trie
    ids, so this class never evicts on its own and ``alloc()`` on a full
    tier is a caller bug."""

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ids = itertools.count()
        self._live: Set[int] = set()
        self._payload: Dict[int, object] = {}

    @property
    def used(self) -> int:
        return len(self._live)

    def free_slots(self) -> int:
        return self.capacity - len(self._live)

    def alloc(self) -> int:
        if len(self._live) >= self.capacity:
            raise RuntimeError(
                "host tier full (BlockManager must make room before "
                "allocating)")
        hid = next(self._ids)
        self._live.add(hid)
        return hid

    def put(self, hid: int, payload) -> None:
        if hid not in self._live:
            raise KeyError(f"host id {hid} is not allocated")
        self._payload[hid] = payload

    def get(self, hid: int):
        return self._payload[hid]

    def free(self, hid: int) -> None:
        self._live.remove(hid)
        self._payload.pop(hid, None)


class BlockManager:
    """Host-side allocator for a pool of ``num_blocks`` KV blocks of
    ``block_len`` tokens (block 0 reserved as the null block).

    ``stats`` counters: ``prefix_lookups`` (admissions that consulted the
    trie), ``prefix_hit_blocks`` / ``prefix_hit_tokens`` (blocks/tokens
    adopted instead of recomputed), ``evictions`` (cached blocks reclaimed
    under pressure), ``cow_copies`` (ensure_writable copies), and
    ``peak_blocks_in_use`` (high-water mark of referenced blocks).
    """

    def __init__(self, num_blocks: int, block_len: int,
                 prefix_cache: bool = True, kv_dtype: str = "bf16",
                 host_blocks: int = 0):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the null block), "
                f"got {num_blocks}")
        if block_len < 1:
            raise ValueError(f"block_len must be >= 1, got {block_len}")
        if kv_dtype not in ("bf16", "int8", "mixed"):
            raise ValueError(
                f"kv_dtype must be bf16|int8|mixed, got {kv_dtype!r}")
        self.num_blocks = int(num_blocks)
        self.block_len = int(block_len)
        self.prefix_cache = bool(prefix_cache)
        self.kv_dtype = kv_dtype
        # per-block element dtype: 0 = the pool's native (bf16) dtype,
        # 1 = int8.  A pure-int8 pool is born all-1; ``mixed`` blocks are
        # born hot (0) and demote to 1 when they register as cold full
        # prefix blocks (``on_demote`` fires so the engine can rewrite
        # the device block); a freed block resets to the pool default.
        self._default_dtype = 1 if kv_dtype == "int8" else 0
        self._dtype = np.full(num_blocks, self._default_dtype, np.int8)
        # engine hook: called with the list of newly demoted physical
        # block ids (mixed mode only) so the device-side block rewrite —
        # a host-triggered quantize→dequantize pass — happens exactly
        # once per demotion, COW/refcount-safe because registration only
        # covers immutable full prompt blocks
        self.on_demote = None
        # host-tier hooks, same pattern as on_demote: the engine copies
        # device block contents off to / back from host payloads.  Fired
        # with [(device_bid, host_id)] pairs (swap-out / demote) or
        # [(host_id, device_bid)] pairs (swap-in / promote), always
        # BEFORE the device block id can be handed to a new owner, so
        # the copy is ordered against any later dispatch by host program
        # order.
        self.on_swap_out = None
        self.on_swap_in = None
        self._host: Optional[HostTier] = (
            HostTier(host_blocks) if host_blocks > 0 else None)
        # host-side trie: full token path -> (host id, element dtype).
        # OrderedDict insertion order IS the host LRU (oldest demotion
        # evicted first when swap records need the room).
        self._host_trie: "OrderedDict[_Path, Tuple[int, str]]" = (
            OrderedDict())
        # device block id -> its full token path while trie-registered
        # (what survives demotion as the host-trie key)
        self._block_path: Dict[int, _Path] = {}
        # bytes per block, per element dtype — set by the engine (the
        # manager has no model dims); feeds kv_cache.bytes_by_dtype
        self._block_nbytes: Dict[str, int] = {}
        self._free: Deque[int] = deque(range(1, num_blocks))
        # blocks newly appended to a chain since the last drain — an
        # int8 engine zeroes their device scale rows before dispatch
        # (a reused block's stale scale would otherwise inflate the
        # running-max quantization scale for its new tenant).  COW
        # destinations are excluded: the device copy carries the source
        # block's live scale with it.
        self._fresh: Set[int] = set()
        self._ref = np.zeros(num_blocks, np.int64)
        self._reserved = 0                       # admitted-but-unallocated
        self._slots: Dict[int, _SlotAlloc] = {}
        # chain-keyed trie: (parent block id, this block's tokens) -> id
        self._trie: Dict[Tuple[int, Tuple[int, ...]], int] = {}
        self._block_key: Dict[int, Tuple[int, Tuple[int, ...]]] = {}
        self._children: Dict[int, Set[int]] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # ref==0 cached
        # telemetry: counters + pool gauges in the shared registry
        # (labelled pool=<id>); ``stats`` stays the public readout as a
        # live Mapping view over them
        reg = _obs.default_registry()
        self._pid = str(next(_POOL_IDS))
        lbl = {"pool": self._pid}
        self._counters = {
            "prefix_lookups": reg.counter(
                "kv_cache.prefix_lookups",
                "admissions that consulted the prefix trie").labels(**lbl),
            "prefix_hit_blocks": reg.counter(
                "kv_cache.prefix_hit_blocks",
                "blocks adopted from the prefix cache instead of "
                "recomputed").labels(**lbl),
            "prefix_hit_tokens": reg.counter(
                "kv_cache.prefix_hit_tokens",
                "tokens adopted from the prefix cache").labels(**lbl),
            "evictions": reg.counter(
                "kv_cache.evictions",
                "cached blocks reclaimed under pool pressure").labels(
                    **lbl),
            "cow_copies": reg.counter(
                "kv_cache.cow_copies",
                "ensure_writable copy-on-write copies").labels(**lbl),
            "host_demotions": reg.counter(
                "kv_cache.host_demotions",
                "cold prefix blocks demoted HBM -> host instead of "
                "dropped under pool pressure").labels(**lbl),
            "host_promotions": reg.counter(
                "kv_cache.host_promotions",
                "host-tier prefix blocks promoted back to HBM on an "
                "admission hit").labels(**lbl),
            "swapped_out_blocks": reg.counter(
                "kv_cache.swapped_out_blocks",
                "private blocks moved to pinned host buffers by "
                "preemption swap-out").labels(**lbl),
            "swapped_in_blocks": reg.counter(
                "kv_cache.swapped_in_blocks",
                "pinned host blocks restored to HBM by preemption "
                "resume").labels(**lbl),
            "exported_blocks": reg.counter(
                "kv_cache.exported_blocks",
                "blocks serialized out of this pool for cross-worker "
                "migration (export_blocks)").labels(**lbl),
            "imported_blocks": reg.counter(
                "kv_cache.imported_blocks",
                "blocks materialized into this pool from a migration "
                "record (import_blocks)").labels(**lbl),
        }
        self._peak = 0
        self._g_peak = reg.gauge(
            "kv_cache.peak_blocks_in_use",
            "high-water mark of referenced blocks").labels(**lbl)
        self._g_in_use = reg.gauge(
            "kv_cache.blocks_in_use",
            "blocks referenced by at least one live chain").labels(**lbl)
        self._g_occ = reg.gauge(
            "kv_cache.pool_occupancy",
            "blocks_in_use / usable_blocks").labels(**lbl)
        self._g_free = reg.gauge(
            "kv_cache.free_blocks", "free-list length").labels(**lbl)
        self._g_cached = reg.gauge(
            "kv_cache.cached_blocks",
            "retired prefix blocks parked for future hits "
            "(evictable)").labels(**lbl)
        self._g_quant = reg.gauge(
            "kv_cache.quantized_blocks",
            "live (referenced or LRU-cached) blocks holding int8 "
            "content").labels(**lbl)
        self._g_host_used = reg.gauge(
            "kv_cache.host_blocks_used",
            "host-tier blocks live (demoted trie blocks + pinned swap "
            "records)").labels(**lbl)
        self._g_host_trie = reg.gauge(
            "kv_cache.host_trie_blocks",
            "host-tier blocks holding demoted (promotable, evictable) "
            "prefix-trie content").labels(**lbl)
        self._f_bytes = reg.gauge(
            "kv_cache.bytes_by_dtype",
            "live pool bytes per element dtype (payload + scale share; "
            "set once the engine provides per-block byte costs)")
        self._g_bytes = {
            "bf16": self._f_bytes.labels(dtype="bf16", **lbl),
            "int8": self._f_bytes.labels(dtype="int8", **lbl)}
        self._stats_view = _StatsView(self)
        self._refresh_gauges()

    @property
    def stats(self) -> Mapping:
        """Counter readout (``prefix_lookups``/``prefix_hit_blocks``/
        ``prefix_hit_tokens``/``evictions``/``cow_copies``/
        ``peak_blocks_in_use``) — a live view over the registry series."""
        return self._stats_view

    # -- accounting --------------------------------------------------------

    @property
    def usable_blocks(self) -> int:
        """Pool capacity a request can ever draw on (excludes the null
        block; includes blocks currently parked on the eviction LRU)."""
        return self.num_blocks - 1

    def blocks_in_use(self) -> int:
        """Blocks referenced by at least one live chain."""
        return int((self._ref > 0).sum())

    def cached_blocks(self) -> int:
        """Retired prefix blocks kept for future hits (evictable)."""
        return len(self._lru)

    def free_blocks(self) -> int:
        return len(self._free)

    def block_dtype(self, bid: int) -> str:
        """Element dtype of physical block ``bid``'s contents."""
        return "int8" if self._dtype[bid] else "bf16"

    def quantized_blocks(self) -> int:
        """Live (referenced or LRU-cached) blocks holding int8 content."""
        live = self._live_mask()
        return int((live & (self._dtype == 1)).sum())

    def set_block_nbytes(self, by_dtype: Dict[str, int]):
        """Engine-supplied per-block byte costs (payload + scale share)
        keyed by element dtype — arms the ``kv_cache.bytes_by_dtype``
        gauges (the manager itself has no model dimensions)."""
        self._block_nbytes = {k: int(v) for k, v in by_dtype.items()}
        self._refresh_gauges()

    def _live_mask(self) -> np.ndarray:
        live = self._ref > 0
        if self._lru:
            live[list(self._lru)] = True
        return live

    def blocks_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        """Worst-case blocks a request needs over its whole lifetime
        (positions 0 .. prompt_len + max_new_tokens - 1)."""
        return -(-(prompt_len + max_new_tokens) // self.block_len)

    def _available(self) -> int:
        return len(self._free) + len(self._lru) - self._reserved

    # -- admission ---------------------------------------------------------

    def admit(self, slot: int, prompt: Sequence[int], prompt_len: int,
              max_new_tokens: int, chunked: bool = False) -> Optional[int]:
        """Admit a request into ``slot``: match the prompt against the
        prefix trie, reserve every block the request could need, allocate
        the blocks covering positions ``[0, prompt_len]`` now, and
        register the prompt's full blocks for future sharing.

        Returns the number of prefix TOKENS adopted from the cache (the
        prefill may skip recomputing them), or ``None`` when the pool
        cannot cover the request yet (caller keeps it queued).  The match
        is capped at ``(prompt_len - 1) // block_len`` blocks so at least
        one token remains to produce the first sampled logits.

        ``chunked``: the chunked-prefill admission contract — the prompt
        will be written chunk by chunk over several ticks, so (a) no
        blocks beyond the adopted prefix are allocated now (the engine
        grows the chain per chunk via :meth:`ensure_capacity` — the
        reservation still covers the worst case, so growth cannot fail)
        and (b) the prompt is NOT registered in the trie yet: a block
        must never satisfy a prefix lookup before its contents are
        written (wave admission writes in the same scheduler call, so it
        registers immediately; chunked callers register incrementally
        via :meth:`register_prompt_upto` as chunks land on the device).
        """
        if slot in self._slots:
            raise ValueError(f"slot {slot} already has an allocation")
        bl = self.block_len
        prompt = [int(t) for t in prompt[:prompt_len]]
        matched: List[int] = []
        path: _Path = ()
        promo: List[Tuple[_Path, Tuple[int, ...], Tuple[int, str]]] = []
        if self.prefix_cache:
            self._counters["prefix_lookups"].inc()
            parent = _ROOT
            cap = (prompt_len - 1) // bl
            for b in range(cap):
                toks = tuple(prompt[b * bl:(b + 1) * bl])
                bid = self._trie.get((parent, toks))
                if bid is None:
                    break
                path = path + (toks,)
                matched.append(bid)
                parent = bid
            # the walk continues into the HOST tier: demoted blocks whose
            # full token path extends the device match are promotion
            # candidates (allocated below, from this request's own
            # reservation — they count as unmatched for admission math)
            if self._host is not None:
                for b in range(len(matched), cap):
                    toks = tuple(prompt[b * bl:(b + 1) * bl])
                    p = path + (toks,)
                    ent = self._host_trie.get(p)
                    if ent is None:
                        break
                    promo.append((p, toks, ent))
                    path = p
        m = len(matched)
        total = self.blocks_needed(prompt_len, max_new_tokens)
        need = total - m
        # a revived LRU block stops being evictable, so count the match
        # against availability too
        revive = sum(1 for bid in matched if self._ref[bid] == 0)
        if self._available() - revive < need:
            return None
        for bid in matched:                      # adopt the shared chain
            if self._ref[bid] == 0:
                self._lru.pop(bid, None)
            self._ref[bid] += 1
        st = _SlotAlloc(list(matched), need)
        self._slots[slot] = st
        self._reserved += need
        if promo:
            # promote host hits: fresh device blocks (reservation-funded,
            # so allocation cannot fail), payload copied back by the
            # engine's on_swap_in, re-registered in the device trie under
            # their original keys.  NOT _fresh: swap-in restores content
            # AND scale — the int8 engine's fresh-scale zeroing would
            # wipe the restored quantization scale.
            #
            # Claim the host entries FIRST: _append_block below may have
            # to evict (_evict_one), whose demotion path calls
            # _host_make_room / _host_drop_cascade — either could evict a
            # still-listed promo entry, freeing the very payload we are
            # about to copy back (and the later trie delete would then
            # KeyError).  Popped entries keep their host ids allocated,
            # so they are invisible to host eviction but their payloads
            # stay live until on_swap_in has read them.
            for p, _, _ in promo:
                del self._host_trie[p]
            pairs: List[Tuple[int, int]] = []
            parent = matched[-1] if matched else _ROOT
            for p, toks, (hid, dt) in promo:
                bid = self._append_block(st)
                self._fresh.discard(bid)
                self._dtype[bid] = 1 if dt == "int8" else 0
                key = (parent, toks)
                self._trie[key] = bid
                self._block_key[bid] = key
                self._block_path[bid] = p
                if parent != _ROOT:
                    self._children.setdefault(parent, set()).add(bid)
                pairs.append((hid, bid))
                parent = bid
            if self.on_swap_in is not None:
                self.on_swap_in(list(pairs))
            for hid, _ in pairs:
                self._host.free(hid)
            self._counters["host_promotions"].inc(len(pairs))
        m_blocks = m + len(promo)
        if not chunked:
            # blocks covering positions [0, prompt_len]: the prefill
            # writes the suffix and the first decode step writes position
            # prompt_len
            for _ in range(prompt_len // bl + 1 - m_blocks):
                self._append_block(st)
            if self.prefix_cache:
                self._register_prompt(st.chain, prompt, prompt_len)
        self._counters["prefix_hit_blocks"].inc(m_blocks)
        self._counters["prefix_hit_tokens"].inc(m_blocks * bl)
        self._note_peak()
        return m_blocks * bl

    def prefix_probe(self, prompt: Sequence[int],
                     prompt_len: Optional[int] = None) -> int:
        """READ-ONLY longest trie match for ``prompt``, in tokens — the
        dp replica router's placement probe (serving/router.py): which
        replica holds the warm blocks for this prompt?  No refcount
        changes, no LRU touches, no counter increments — admission via
        :meth:`admit` remains the only trie consumer with side effects.
        Capped exactly like admission (at least one token must remain
        to produce the first logits), so the probe never promises more
        than admit() would adopt."""
        if not self.prefix_cache:
            return 0
        n = int(prompt_len if prompt_len is not None else len(prompt))
        bl = self.block_len
        toks = [int(t) for t in prompt[:n]]
        parent = _ROOT
        m = 0
        for b in range((n - 1) // bl):
            bid = self._trie.get((parent, tuple(toks[b * bl:(b + 1) * bl])))
            if bid is None:
                break
            m += 1
            parent = bid
        return m * bl

    def register_prompt_upto(self, slot: int, prompt: Sequence[int],
                             upto: int):
        """Chunked-prefill trie registration: insert the prompt's full
        blocks whose every token is among the first ``upto`` WRITTEN
        tokens.  Idempotent — the engine calls it after each chunk's
        device step is dispatched (program order sequences any adopter's
        reads after the writes), so prefix hits become available chunk by
        chunk instead of all-or-nothing at retirement."""
        if not self.prefix_cache:
            return
        st = self._slots[slot]
        self._register_prompt(st.chain,
                              [int(t) for t in prompt[:upto]], int(upto))

    def _register_prompt(self, chain: List[int], prompt: List[int],
                         prompt_len: int):
        """Insert the prompt's FULL blocks into the trie.  Only blocks
        whose every position is a prompt token are registered — the block
        holding position ``prompt_len`` onward is still being written by
        decode and must stay private."""
        bl = self.block_len
        parent = _ROOT
        path: _Path = ()
        demoted: List[int] = []
        for b in range(prompt_len // bl):
            bid = chain[b]
            toks = tuple(prompt[b * bl:(b + 1) * bl])
            key = (parent, toks)
            path = path + (toks,)
            if key not in self._trie and bid not in self._block_key:
                self._trie[key] = bid
                self._block_key[bid] = key
                self._block_path[bid] = path
                if parent != _ROOT:
                    self._children.setdefault(parent, set()).add(bid)
                # one-tier rule: this path now has freshly written HBM
                # content, so a host-demoted copy of the same path is
                # redundant — drop it (content-identical by definition:
                # the path IS the content identity)
                if self._host is not None:
                    ent = self._host_trie.pop(path, None)
                    if ent is not None:
                        self._host.free(ent[0])
                # mixed pool: a block registering as a shareable FULL
                # prefix block is cold by definition (immutable from
                # here on) — demote it to int8 now; the engine's
                # on_demote device rewrite is refcount-safe because no
                # writer ever touches a registered full block again
                # (forks go through ensure_writable first)
                if self.kv_dtype == "mixed" and not self._dtype[bid]:
                    self._dtype[bid] = 1
                    demoted.append(bid)
            parent = self._trie.get(key, bid)
        if demoted:
            if self.on_demote is not None:
                self.on_demote(list(demoted))
            self._refresh_gauges()

    # -- growth / writes ---------------------------------------------------

    def _pop_block(self) -> int:
        if self._free:
            return self._free.popleft()
        return self._evict_one()

    def _append_block(self, st: _SlotAlloc) -> int:
        if st.reserved_left <= 0:
            raise RuntimeError(
                "block allocation beyond the slot's admission reservation "
                "(engine bug: reservation must cover prompt + max_new)")
        bid = self._pop_block()
        self._ref[bid] = 1
        self._fresh.add(bid)
        st.chain.append(bid)
        st.reserved_left -= 1
        self._reserved -= 1
        return bid

    def drain_fresh(self) -> List[int]:
        """Physical ids of blocks newly appended to chains since the last
        call (cleared on read).  The int8 engine zeroes these blocks'
        device scale rows before the next step dispatch — see
        ``_fresh``'s init comment for why reuse makes that necessary."""
        out = sorted(self._fresh)
        self._fresh.clear()
        return out

    def ensure_capacity(self, slot: int, pos: int) -> bool:
        """Grow ``slot``'s chain until it covers position ``pos``.
        Returns True when blocks were appended (table row changed)."""
        st = self._slots[slot]
        grew = False
        while len(st.chain) * self.block_len <= pos:
            self._append_block(st)
            grew = True
        if grew:
            self._note_peak()
        return grew

    def ensure_writable(self, slot: int,
                        logical_block: int) -> Optional[Tuple[int, int]]:
        """Copy-on-write guard: make ``slot``'s ``logical_block`` private.
        Returns ``(src, dst)`` physical ids when a copy is needed (caller
        must copy the device block src -> dst), else None.  The fresh
        block comes from the free/evictable pool — COW is not covered by
        the admission reservation (it cannot occur in the append-only
        engine flow; forking callers must size the pool for it)."""
        st = self._slots[slot]
        src = st.chain[logical_block]
        if self._ref[src] <= 1:
            return None
        dst = self._pop_block()
        self._ref[src] -= 1
        self._ref[dst] = 1
        st.chain[logical_block] = dst
        self._counters["cow_copies"].inc()
        self._note_peak()
        return src, dst

    # -- retirement / eviction --------------------------------------------

    def release(self, slot: int):
        """Retire a slot: drop its references and its unused reservation.
        Trie-registered blocks that reach refcount 0 are parked on the
        eviction LRU (future prefix hits revive them for free); anonymous
        blocks return to the free list."""
        st = self._slots.pop(slot)
        self._reserved -= st.reserved_left
        for bid in st.chain:
            self._ref[bid] -= 1
            if self._ref[bid] == 0:
                if bid in self._block_key:
                    # LRU-parked: the content (and its dtype) persists
                    # for future prefix hits
                    self._lru[bid] = None
                    self._lru.move_to_end(bid)
                else:
                    self._free.append(bid)
                    self._dtype[bid] = self._default_dtype
        self._refresh_gauges()

    # -- preemption / host tier --------------------------------------------

    @property
    def host_tier(self) -> Optional[HostTier]:
        """The host-RAM tier (None when ``host_blocks == 0``) — the
        engine reads/writes payloads through it from the swap hooks."""
        return self._host

    def host_blocks_used(self) -> int:
        return self._host.used if self._host is not None else 0

    def host_trie_blocks(self) -> int:
        """Host-tier blocks holding demoted (promotable) trie content;
        the rest of ``host_blocks_used`` is pinned swap records."""
        return len(self._host_trie)

    def host_cache_bytes(self) -> int:
        """Host-RAM entitlement of the tier: capacity x full-precision
        block bytes (payloads are per-entry dtype, so this is the
        worst case).  Deliberately NOT part of ``cache_hbm_bytes`` or
        the mesh pre-flight HBM-liveness cross-check — the tier lives
        in pinned host memory, never on device."""
        if self._host is None or not self._block_nbytes:
            return 0
        return self._host.capacity * self._block_nbytes.get("bf16", 0)

    def private_swap_blocks(self, slot: int) -> int:
        """How many of ``slot``'s blocks a swap-out would have to move
        to the host tier (refcount-1 blocks; shared blocks stay put)."""
        st = self._slots[slot]
        return sum(1 for bid in st.chain if self._ref[bid] == 1)

    def host_can_accept(self, n: int) -> bool:
        """Could the host tier take ``n`` more pinned blocks right now,
        evicting demoted trie entries if it must?  (Pinned swap records
        are never evicted for other swap records.)"""
        if self._host is None:
            return False
        return self._host.free_slots() + len(self._host_trie) >= n

    def _host_make_room(self, n: int) -> bool:
        """Ensure ``n`` free host slots by evicting the oldest demoted
        trie entries (never pinned swap records).  False when the tier
        cannot cover ``n`` — nothing is evicted needlessly first."""
        if self._host is None:
            return False
        if self._host.free_slots() + len(self._host_trie) < n:
            return False
        while self._host.free_slots() < n:
            p, (hid, _) = self._host_trie.popitem(last=False)
            self._host.free(hid)
            self._host_drop_cascade(p)
        return True

    def _host_drop_cascade(self, path: _Path):
        """Free host-trie entries STRICTLY below ``path`` — with their
        ancestor gone from both tiers the admission walk can never
        reach them, and unreachable entries would leak host capacity."""
        if self._host is None or not self._host_trie:
            return
        k = len(path)
        for p in [p for p in self._host_trie
                  if len(p) > k and p[:k] == path]:
            hid, _ = self._host_trie.pop(p)
            self._host.free(hid)

    def swap_out(self, slot: int) -> Optional[Dict[str, object]]:
        """Preempt ``slot``: tear down its allocation, moving every
        PRIVATE block (refcount 1) to a pinned host buffer and keeping
        this slot's reference on every SHARED block so the chain
        survives other owners' releases.  Returns the resume record for
        :meth:`resume_swapped` — ``entries`` is the chain in order, each
        entry ``("hbm", bid)`` (reference kept) or ``("host", hid,
        dtype)`` (payload pinned on host) — or ``None`` when the host
        tier cannot take the private blocks even after evicting every
        demoted trie entry (caller falls back to recompute or skips the
        victim).  Record entries are never trie keys: a swapped chain
        cannot serve a prefix hit until it is resumed."""
        st = self._slots[slot]
        n_priv = sum(1 for bid in st.chain if self._ref[bid] == 1)
        if not self._host_make_room(n_priv):
            return None
        st = self._slots.pop(slot)
        reserved_left = st.reserved_left
        self._reserved -= reserved_left
        entries: List[Tuple] = []
        pairs: List[Tuple[int, int]] = []
        for bid in st.chain:
            if self._ref[bid] > 1:
                entries.append(("hbm", int(bid)))
                continue
            if bid in self._block_key:
                # the physical id is about to be freed — its trie entry
                # (and descendants') would dangle
                self._unregister_cascade(bid)
            hid = self._host.alloc()
            entries.append(("host", hid, self.block_dtype(bid)))
            pairs.append((int(bid), hid))
            self._ref[bid] = 0
            self._free.append(bid)
            self._dtype[bid] = self._default_dtype
        if pairs:
            if self.on_swap_out is not None:
                self.on_swap_out(list(pairs))
            self._counters["swapped_out_blocks"].inc(len(pairs))
        self._fresh.difference_update(b for b, _ in pairs)
        self._refresh_gauges()
        return {"entries": entries, "reserved_left": int(reserved_left)}

    def resume_swapped(self, slot: int, record: Dict[str, object]
                       ) -> Optional[int]:
        """Rebuild a swapped-out chain into (free) ``slot``: allocate a
        fresh device block per ``host`` entry (payload copied back via
        ``on_swap_in``, host buffer freed), re-adopt each ``hbm`` entry
        (its reference was never dropped), and re-arm the remaining
        reservation.  Returns the chain length, or ``None`` when the
        pool cannot cover the host blocks + reservation yet (caller
        keeps the record and retries later)."""
        if slot in self._slots:
            raise ValueError(f"slot {slot} already has an allocation")
        entries = record["entries"]
        reserved = int(record["reserved_left"])
        n_host = sum(1 for e in entries if e[0] == "host")
        if self._available() < n_host + reserved:
            return None
        chain: List[int] = []
        pairs: List[Tuple[int, int]] = []
        for e in entries:
            if e[0] == "hbm":
                chain.append(int(e[1]))
                continue
            _, hid, dt = e
            bid = self._pop_block()
            self._ref[bid] = 1
            self._dtype[bid] = 1 if dt == "int8" else 0
            chain.append(bid)
            pairs.append((hid, int(bid)))
        self._slots[slot] = _SlotAlloc(chain, reserved)
        self._reserved += reserved
        if pairs:
            if self.on_swap_in is not None:
                self.on_swap_in(list(pairs))
            for hid, _ in pairs:
                self._host.free(hid)
            self._counters["swapped_in_blocks"].inc(len(pairs))
        self._note_peak()
        return len(chain)

    def drop_swap_record(self, record: Dict[str, object]):
        """Cancel a swapped-out request: release the record's pinned
        host buffers and drop the references it kept on shared blocks
        (parking registered ones on the LRU exactly like a release)."""
        for e in record["entries"]:
            if e[0] == "hbm":
                bid = int(e[1])
                self._ref[bid] -= 1
                if self._ref[bid] == 0:
                    if bid in self._block_key:
                        self._lru[bid] = None
                        self._lru.move_to_end(bid)
                    else:
                        self._free.append(bid)
                        self._dtype[bid] = self._default_dtype
            else:
                self._host.free(e[1])
        self._refresh_gauges()

    # -- cross-pool migration (ISSUE 18) -----------------------------------

    def export_blocks(self, slot: int, read_payload) -> Dict[str, object]:
        """Serialize ``slot``'s chain for migration into ANOTHER pool:
        one entry per block, in chain order, carrying the block's element
        dtype tag and the payload ``read_payload(bid)`` returns (a host
        pytree — the engine reads the device block including its scale
        row, so quantized blocks survive the trip bit-for-bit).

        By-value and read-only: shared (refcount > 1) blocks are copied
        like private ones — the importing pool is a different manager,
        so exporting never touches refcounts, the trie, or the LRU here.
        The source chain stays fully live until the caller releases it.
        """
        st = self._slots[slot]
        entries: List[Dict[str, object]] = [
            {"dtype": self.block_dtype(bid), "payload": read_payload(
                int(bid))} for bid in st.chain]
        self._counters["exported_blocks"].inc(len(entries))
        return {"entries": entries,
                "reserved_left": int(st.reserved_left),
                "block_len": int(self.block_len)}

    def import_blocks(self, slot: int, record: Dict[str, object],
                      write_payload) -> Optional[int]:
        """Materialise an exported chain into (free) ``slot`` of THIS
        pool: allocate one device block per entry, restore its dtype tag,
        and hand the payload to ``write_payload(bid, payload)``; the
        remaining admission reservation is re-armed so the imported
        request can keep decoding to its original budget.  Returns the
        chain length, or ``None`` when the pool cannot cover the blocks
        plus the reservation right now (existing reservations are
        respected — migration never strands an admitted local request).
        Imported blocks are NOT marked fresh: their scale rows arrive in
        the payload and must not be zeroed before the next dispatch."""
        if slot in self._slots:
            raise ValueError(f"slot {slot} already has an allocation")
        if int(record.get("block_len", self.block_len)) != self.block_len:
            raise ValueError(
                f"block_len mismatch: record has "
                f"{record.get('block_len')}, pool has {self.block_len}")
        entries = record["entries"]
        reserved = int(record["reserved_left"])
        if self._available() < len(entries) + reserved:
            return None
        chain: List[int] = []
        for e in entries:
            bid = self._pop_block()
            self._ref[bid] = 1
            self._dtype[bid] = 1 if e["dtype"] == "int8" else 0
            write_payload(int(bid), e["payload"])
            chain.append(bid)
        self._slots[slot] = _SlotAlloc(chain, reserved)
        self._reserved += reserved
        self._counters["imported_blocks"].inc(len(chain))
        self._note_peak()
        self._refresh_gauges()
        return len(chain)

    def preempt_free(self, slot: int):
        """Recompute-mode preemption: pool mechanics identical to
        :meth:`release` — registered prompt blocks park on the LRU, so
        the victim's resume re-prefill adopts whatever survives the
        pressure through the ordinary prefix-trie path (possibly via
        the host tier if it demotes in between)."""
        self.release(slot)

    def _evict_one(self) -> int:
        """Reclaim the LRU cached block.  Unregistering cascades through
        the block's trie descendants (their chain keys dangle once the
        parent id is reused): cached descendants move to the free list,
        live ones just lose their trie entry."""
        if not self._lru:
            raise RuntimeError(
                "KV block pool exhausted: no free or evictable blocks "
                "(reservation accounting should have prevented this)")
        bid, _ = self._lru.popitem(last=False)
        self._counters["evictions"].inc()
        # tiering: instead of dropping the content, demote it HBM ->
        # host (payload copied off by the engine BEFORE the id can be
        # handed to a new owner; the full token path keys the host trie
        # so a later admission can promote it back).  Skipped when the
        # host tier is absent or full of pinned swap records.
        bpath = self._block_path.get(bid)
        if (self._host is not None and bpath is not None
                and self._host_make_room(1)):
            hid = self._host.alloc()
            if self.on_swap_out is not None:
                self.on_swap_out([(int(bid), hid)])
            self._host_trie[bpath] = (hid, self.block_dtype(bid))
            self._counters["host_demotions"].inc()
        self._unregister_cascade(bid)
        self._dtype[bid] = self._default_dtype  # new owner rewrites it
        return bid

    def _unregister_cascade(self, bid: int):
        """Drop ``bid``'s trie registration and every descendant's —
        their chain keys dangle the moment the parent link goes, so a
        partial invalidation would leave unreachable-but-stale entries.
        Cached (refcount-0, LRU-parked) descendants move to the free
        list; live ones just lose their trie entry."""
        stack = [bid]
        while stack:
            b = stack.pop()
            key = self._block_key.pop(b, None)
            if key is not None:
                self._trie.pop(key, None)
            bpath = self._block_path.pop(b, None)
            if bpath is not None and self._host is not None:
                # host entries STRICTLY below this path lose their last
                # ancestor link — the admission walk can never reach
                # them again, so they are dropped like device-trie
                # descendants (the demoted copy AT b's own path, if the
                # eviction above just created it, survives: strict
                # descendants only)
                self._host_drop_cascade(bpath)
            stack.extend(self._children.pop(b, ()))
            if b != bid and b in self._lru:
                del self._lru[b]
                self._free.append(b)
                self._dtype[b] = self._default_dtype

    def truncate_to(self, slot: int, pos: int):
        """Roll ``slot``'s chain back to cover exactly positions
        ``[0, pos)`` — the speculative-decode ROLLBACK hook: after the
        verify step rejects a draft suffix, the blocks that existed only
        to hold rejected tokens go back to the pool and the admission
        reservation is re-credited, so the slot can grow over the same
        positions again as real decoding proceeds (growth stays
        infallible).  A no-op when the chain is already within ``pos``.

        Safety invariants, in the order they matter:

          * **trie**: every registered block at chain index >=
            ``pos // block_len`` is cascade-unregistered BEFORE anything
            is freed.  The partial block at the cut stays in the chain
            but will be rewritten in place at positions >= ``pos``, and
            removed blocks return to the free list for arbitrary reuse —
            either way, a later prefix lookup must never be served by
            them (the stale-hit hazard :meth:`_evict_one` also guards).
            In the engine flow only *generated* positions are ever
            rolled back, so registered PROMPT blocks sit strictly below
            the cut and keep serving hits;
          * **refcounts / COW**: removed blocks are deref'd, not freed
            outright — a block shared with another slot's chain (COW
            sharing, adopted prefixes) survives untouched for its other
            owners and only leaves this chain's table;
          * **reservation**: each block this slot actually releases is
            re-credited to its ``reserved_left``, keeping
            ``blocks_needed``-based admission exact.
        """
        st = self._slots[slot]
        if pos < 0:
            raise ValueError(f"pos must be >= 0, got {pos}")
        keep = -(-pos // self.block_len)         # blocks covering [0, pos)
        cut = pos // self.block_len              # first rewritable block
        for bid in st.chain[cut:]:
            if bid in self._block_key:
                self._unregister_cascade(bid)
        removed = st.chain[keep:]
        if not removed:
            self._refresh_gauges()
            return
        del st.chain[keep:]
        for bid in removed:
            self._ref[bid] -= 1
            if self._ref[bid] == 0:
                # unregistered above, so never LRU-parked: straight back
                # to the free list
                self._free.append(bid)
                self._dtype[bid] = self._default_dtype
        st.reserved_left += len(removed)
        self._reserved += len(removed)
        self._refresh_gauges()

    # -- table export ------------------------------------------------------

    def table_row(self, slot: int, max_blocks: int) -> np.ndarray:
        """(max_blocks,) int32 physical ids, null-block-filled past the
        allocated chain (every entry is a valid pool index).

        Null-block aliasing rule (ISSUE 14; the kernel pre-flight's
        ClampCheck proves the other half): PAD columns past the chain
        may map to ``NULL_BLOCK`` — the decode kernel's dead-tail clamp
        guarantees they are never dereferenced — but a LIVE chain entry
        mapping to block 0 would alias the null block's pad data into
        the row's attention window, silently corrupting the output.
        The allocator can never produce one (block 0 is excluded from
        the free list at construction), so this is asserted, not
        handled."""
        st = self._slots[slot]
        if len(st.chain) > max_blocks:
            raise ValueError(
                f"slot {slot} chain ({len(st.chain)} blocks) exceeds "
                f"max_blocks ({max_blocks})")
        assert NULL_BLOCK not in st.chain, (
            f"slot {slot} chain references the null block: live rows "
            f"must never map to block 0 (pad aliasing)")
        row = np.full((max_blocks,), NULL_BLOCK, np.int32)
        row[:len(st.chain)] = st.chain
        return row

    def chain(self, slot: int) -> List[int]:
        return list(self._slots[slot].chain)

    def _note_peak(self):
        used = self._refresh_gauges()
        if used > self._peak:
            self._peak = used
            self._g_peak.set(used)

    def _refresh_gauges(self) -> int:
        """Push the pool-occupancy gauges; returns blocks_in_use."""
        used = self.blocks_in_use()
        self._g_in_use.set(used)
        self._g_occ.set(used / self.usable_blocks)
        self._g_free.set(len(self._free))
        self._g_cached.set(len(self._lru))
        live = self._live_mask()
        n_int8 = int((live & (self._dtype == 1)).sum())
        self._g_quant.set(n_int8)
        if self._host is not None:
            self._g_host_used.set(self._host.used)
            self._g_host_trie.set(len(self._host_trie))
        if self._block_nbytes:
            self._g_bytes["int8"].set(
                n_int8 * self._block_nbytes.get("int8", 0))
            self._g_bytes["bf16"].set(
                (int(live.sum()) - n_int8)
                * self._block_nbytes.get("bf16", 0))
        return used
