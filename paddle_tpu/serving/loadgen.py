"""Trace-driven load harness: seeded arrival processes + length mixes
replayed deterministically against a ServingEngine or ReplicaRouter.

Raw tok/s on a drain()-until-empty batch says nothing about production
serving, which is judged on goodput under SLO — requests finishing
within TTFT/TPOT deadlines under realistic traffic.  This module
supplies the traffic half of that judgment:

  * **arrival processes** — seeded Poisson (exponential inter-arrival
    gaps) and bursty on/off (Markov-modulated: dense arrivals inside
    ``burst_on``-tick windows separated by silent ``burst_off`` gaps),
    both in scheduler-tick time so replays are device-speed-independent;
  * **length mixes** — heavy-tail prompt/output lengths, either
    lognormal (median × e^{σZ}, clamped) or Zipf-bucketed (a fixed
    bucket ladder with rank-``a`` power-law mass — the multi-workload
    mixture shape real traces show);
  * **tenant populations** — Zipf-popular tenants, each with a shared
    prompt prefix (its "system prompt"), so prefix caching and
    prefix-affinity routing see the traffic they were built for.

``generate_load(spec, seed)`` is a pure function of its arguments —
the SAME (spec, seed) yields the SAME trace, byte for byte —  and
``replay`` drives the trace through ``submit()``/``step()`` ticks,
segmenting the process-wide RequestLog with ``mark()`` and returning
outputs, the goodput report, and the run's structural
``timeline_signature``.  Two identical-seed replays against
identically-configured engines must produce identical signatures AND
identical sampled outputs (BASELINE.md "SLO accounting conventions");
``python -m paddle_tpu.serving.loadgen --smoke`` enforces exactly that,
plus the step retrace budget, against both engine modes on CPU — the
CI hook.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import observability as _obs

__all__ = ["LoadRequest", "LoadSpec", "generate_load", "replay"]


@dataclasses.dataclass
class LoadRequest:
    """One request of a generated trace."""

    index: int                  # position in the trace (stable id)
    arrival: float              # arrival time, in scheduler ticks
    tenant: int                 # which shared-prefix population
    prompt: np.ndarray          # (plen,) int32, tenant prefix included
    max_new_tokens: int


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """Everything that shapes a trace; with the seed, it IS the trace."""

    n_requests: int = 16
    vocab: int = 256

    # arrival process (tick time)
    arrival: str = "poisson"            # "poisson" | "bursty"
    mean_gap: float = 1.0               # poisson: mean inter-arrival gap
    burst_on: float = 4.0               # bursty: window of dense arrivals
    burst_off: float = 16.0             # bursty: silent gap between windows
    burst_gap: float = 0.25             # bursty: mean gap inside a window

    # prompt length mix
    prompt_dist: str = "lognormal"      # "lognormal" | "zipf"
    prompt_median: float = 32.0         # lognormal median
    prompt_sigma: float = 0.6           # lognormal log-space sigma
    prompt_buckets: Tuple[int, ...] = (8, 16, 32, 64, 128)
    prompt_zipf_a: float = 1.2          # bucket rank exponent
    prompt_min: int = 2
    prompt_max: int = 128

    # output length mix (same knobs, own values)
    output_dist: str = "lognormal"
    output_median: float = 16.0
    output_sigma: float = 0.6
    output_buckets: Tuple[int, ...] = (4, 8, 16, 32, 64)
    output_zipf_a: float = 1.2
    output_min: int = 2
    output_max: int = 64

    # tenant population: Zipf-popular tenants sharing a prompt prefix
    tenants: int = 1
    tenant_zipf_a: float = 1.2
    shared_prefix_len: int = 0


def _lengths(rng: np.random.RandomState, n: int, dist: str,
             median: float, sigma: float, buckets: Sequence[int],
             zipf_a: float, lo: int, hi: int) -> np.ndarray:
    if dist == "lognormal":
        vals = np.exp(rng.normal(np.log(median), sigma, n))
    elif dist == "zipf":
        ranks = np.arange(1, len(buckets) + 1, dtype=np.float64)
        p = ranks ** -zipf_a
        p /= p.sum()
        vals = np.asarray(buckets)[rng.choice(len(buckets), n, p=p)]
    else:
        raise ValueError(f"unknown length distribution {dist!r}")
    return np.clip(np.round(vals).astype(np.int64), lo, hi)


def _arrivals(rng: np.random.RandomState, spec: LoadSpec) -> np.ndarray:
    n = spec.n_requests
    if spec.arrival == "poisson":
        return np.cumsum(rng.exponential(spec.mean_gap, n))
    if spec.arrival != "bursty":
        raise ValueError(f"unknown arrival process {spec.arrival!r}")
    # on/off: walk burst windows, filling each with exponential gaps
    # until its ``burst_on`` budget is spent, then jump ``burst_off``
    out = np.empty((n,))
    t = window_start = 0.0
    for i in range(n):
        t += float(rng.exponential(spec.burst_gap))
        if t - window_start > spec.burst_on:
            window_start = window_start + spec.burst_on + spec.burst_off
            t = window_start + float(rng.exponential(spec.burst_gap))
        out[i] = t
    return out


def generate_load(spec: LoadSpec, seed: int = 0) -> List[LoadRequest]:
    """Materialise a trace: deterministic in (spec, seed), independent
    of any engine or device state."""
    rng = np.random.RandomState(seed)
    arrivals = _arrivals(rng, spec)
    plens = _lengths(rng, spec.n_requests, spec.prompt_dist,
                     spec.prompt_median, spec.prompt_sigma,
                     spec.prompt_buckets, spec.prompt_zipf_a,
                     spec.prompt_min, spec.prompt_max)
    olens = _lengths(rng, spec.n_requests, spec.output_dist,
                     spec.output_median, spec.output_sigma,
                     spec.output_buckets, spec.output_zipf_a,
                     spec.output_min, spec.output_max)
    ranks = np.arange(1, max(1, spec.tenants) + 1, dtype=np.float64)
    tp = ranks ** -spec.tenant_zipf_a
    tp /= tp.sum()
    tenants = rng.choice(len(ranks), spec.n_requests, p=tp)
    prefixes = rng.randint(0, spec.vocab,
                           (max(1, spec.tenants),
                            max(0, spec.shared_prefix_len))
                           ).astype(np.int32)
    load: List[LoadRequest] = []
    for i in range(spec.n_requests):
        body = rng.randint(0, spec.vocab, int(plens[i])).astype(np.int32)
        prompt = np.concatenate([prefixes[int(tenants[i])], body])
        load.append(LoadRequest(index=i, arrival=float(arrivals[i]),
                                tenant=int(tenants[i]), prompt=prompt,
                                max_new_tokens=int(olens[i])))
    return load


def replay(target, load: Sequence[LoadRequest],
           max_ticks: Optional[int] = None) -> Dict[str, Any]:
    """Drive a trace through ``target`` (ServingEngine or
    ReplicaRouter): each loop iteration submits every request whose
    arrival tick has come, then runs one ``step()``, until the trace is
    exhausted and the target is idle.  Arrival time is tick time — the
    replay schedule is identical however fast the device steps, which
    is what makes two identical-seed runs comparable event-for-event.

    Returns outputs (trace order; None = rejected), the segment's
    goodput report against the deadlines recorded at submit, the
    structural timeline signature, and per-engine step retrace counts.
    """
    log = _obs.get_request_log()
    mark = log.mark()
    engines = list(getattr(target, "engines", [target]))

    def busy() -> bool:
        # pending_held: requests parked in a router's predictive hold
        # queue (ISSUE 17) — invisible to every engine, so the replay
        # must poll the target itself or it would stop with work parked
        return bool(getattr(target, "pending_held", 0)) or any(
            e.queue_depth or e.num_active or e.num_pending
            or getattr(e, "num_preempted", 0) for e in engines)

    order = sorted(range(len(load)),
                   key=lambda i: (load[i].arrival, load[i].index))
    rids: Dict[int, int] = {}           # trace index -> target rid
    rejected = 0
    tick = 0
    nxt = 0
    t0 = time.perf_counter()
    while nxt < len(order) or busy():
        while nxt < len(order) and load[order[nxt]].arrival <= tick:
            r = load[order[nxt]]
            try:
                rids[r.index] = target.submit(
                    r.prompt, max_new_tokens=r.max_new_tokens)
            except ValueError:
                rejected += 1
            nxt += 1
        target.step()
        tick += 1
        if max_ticks is not None and tick >= max_ticks:
            break
    wall = time.perf_counter() - t0
    end_mark = log.mark()
    outputs = [target.result(rids[r.index]) if r.index in rids else None
               for r in load]
    generated = sum(len(o) for o in outputs if o)
    return {
        "requests": len(load),
        "rejected": rejected,
        "ticks": tick,
        "wall_s": wall,
        "outputs": outputs,
        "generated_tokens": generated,
        "step_traces": [int(getattr(e, "step_traces", 0))
                        for e in engines],
        "slo": log.slo_report(since_uid=mark, until_uid=end_mark,
                              wall_s=wall),
        "signature": log.timeline_signature(since_uid=mark,
                                            until_uid=end_mark),
        # predicted-vs-measured attribution per engine (ISSUE 15); the
        # predicted side is schedule-deterministic — _smoke gates its
        # perf_signature byte-stable across the A/B replays
        "perf": [e.perf_report() for e in engines
                 if hasattr(e, "perf_report")],
        # the (mark, end_mark] bracket scopes any post-hoc RequestLog
        # readout — slo_report with explicit targets, Perfetto export —
        # to exactly this run
        "mark": mark,
        "end_mark": end_mark,
    }


# -- CI smoke ----------------------------------------------------------------

def _smoke() -> int:
    """Tiny seeded load against the engine modes CI guards (wave,
    chunked, paged int8-KV, preempt-saturated), each replayed twice on
    fresh engines: non-zero exit on a step retrace past budget 1, any
    determinism drift (signature, sampled outputs, or — saturated —
    the preemption-decision signature) between the identical-seed
    runs, or any graph/kernel-lint finding."""
    import json

    import jax
    # the env var alone is not enough where a sitecustomize pins
    # jax_platforms; the config API wins
    jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaForCausalLM, tiny_llama_config
    from .engine import ServingEngine

    pt.seed(7)
    model = LlamaForCausalLM(tiny_llama_config())
    model.eval()
    spec = LoadSpec(n_requests=8, arrival="poisson", mean_gap=1.5,
                    prompt_dist="zipf", prompt_buckets=(8, 16, 32, 48),
                    prompt_zipf_a=1.1, prompt_max=48,
                    output_dist="lognormal", output_median=6.0,
                    output_sigma=0.4, output_min=3, output_max=10,
                    tenants=2, shared_prefix_len=4)
    load = generate_load(spec, seed=11)

    modes = {"wave": {}, "chunked": {"chunked": True, "prefill_chunk": 8},
             # quantized-cache drift canary (ISSUE 13): one paged int8-KV
             # replay so a regression in the quantize-at-scatter /
             # dequant-in-kernel path fails CI, not just the bench
             "int8_paged": {"paged": True, "block_len": 16,
                            "kv_cache_dtype": "int8"},
             # preemption canary (ISSUE 16): a pool too tight for the
             # trace to fit resident, so the preemptive scheduler must
             # evict mid-decode and swap back in via the host tier —
             # gated below on preemptions actually firing and on the
             # victim-decision signature replaying byte-stable
             "saturated": {"paged": True, "block_len": 8,
                           "num_blocks": 12, "preempt": "swap",
                           "host_blocks": 32}}
    failures: List[str] = []
    summary: Dict[str, Any] = {"requests": spec.n_requests}
    for mode, kw in modes.items():
        runs = []
        kernel_findings = -1
        preempt_sigs: List[str] = []
        preemptions: List[int] = []
        for _ in range(2):
            eng = ServingEngine(model, num_slots=4, max_length=128,
                                prefill_batch=2, **kw)
            if kernel_findings < 0:
                # ISSUE 14 CI gate: the kernels this mode's dispatch
                # would select must pre-flight clean (static — no
                # compile), so a kernel-lint regression fails the smoke.
                # The saturated mode runs the FULL merged lint
                # (graph rules + kernel pre-flight) — the ISSUE 16
                # contract is zero findings of either kind
                kf = (eng.lint_step() if mode == "saturated"
                      else eng.kernel_preflight()["findings"])
                kernel_findings = len(kf)
                if kf:
                    failures.append(
                        f"{mode}: pre-flight findings: "
                        + "; ".join(str(f) for f in kf))
            runs.append(replay(eng, load))
            if mode == "saturated":
                preempt_sigs.append(eng.preempt_signature())
                preemptions.append(sum(
                    eng.metrics()["preempt"]["preemptions"].values()))
        a, b = runs
        traces = max(max(r["step_traces"]) for r in runs)
        if traces > 1:
            failures.append(f"{mode}: step retraced (traces={traces})")
        if a["signature"] != b["signature"]:
            failures.append(f"{mode}: timeline signature drift between "
                            f"identical-seed runs")
        if a["outputs"] != b["outputs"]:
            failures.append(f"{mode}: sampled-output drift between "
                            f"identical-seed runs")
        # ISSUE 15 gates: the cost-model report must be clean (no drift
        # findings, no perf anomalies) on the deterministic CPU traces,
        # and its predicted side byte-stable across the A/B replays
        perf_sigs = []
        drift_findings = 0
        anomalies = 0
        for r in (a, b):
            for rep in r.get("perf", []):
                if not rep.get("enabled", False):
                    continue
                perf_sigs.append(_obs.perf_signature(rep))
                drift_findings += len(rep.get("drift", []))
                anomalies += sum(rep.get("anomalies", {}).values())
        if drift_findings:
            failures.append(f"{mode}: {drift_findings} cost-model drift "
                            f"finding(s) on a deterministic CPU trace")
        if anomalies:
            failures.append(f"{mode}: {anomalies} serving.perf_anomalies "
                            f"detection(s) on a deterministic CPU trace")
        if len(set(perf_sigs)) > 1:
            failures.append(f"{mode}: perf_report predicted-side drift "
                            f"between identical-seed runs")
        if mode == "saturated":
            # the mode only tests anything if the pool actually forced
            # eviction, and the victim decisions must replay byte-stable
            if not all(preemptions):
                failures.append(
                    "saturated: tight pool produced no preemption — "
                    "the mode is not exercising the scheduler")
            if len(set(preempt_sigs)) > 1:
                failures.append(
                    "saturated: preemption-decision signature drift "
                    "between identical-seed runs")
        summary[mode] = {
            "ticks": a["ticks"],
            "generated_tokens": a["generated_tokens"],
            "step_traces": traces,
            "goodput": a["slo"]["goodput"],
            "kernel_findings": kernel_findings,
            "perf_drift_findings": drift_findings,
            "perf_anomalies": anomalies,
            "perf_deterministic": len(set(perf_sigs)) <= 1,
            "deterministic": (a["signature"] == b["signature"]
                              and a["outputs"] == b["outputs"])}
        if mode == "saturated":
            summary[mode]["preemptions"] = preemptions
            summary[mode]["preempt_signature_stable"] = (
                len(set(preempt_sigs)) <= 1)
    summary["fleet_sim"] = _smoke_fleet_sim(model, load, failures)
    summary["multihost"] = _smoke_multihost(model, load, failures)
    summary["federated"] = _smoke_federated(model, load, failures)
    summary["spec_model"] = _smoke_spec_model(model, load, failures)
    summary["failures"] = failures
    print(json.dumps(summary, indent=2))
    return 1 if failures else 0


def _smoke_fleet_sim(model, load: Sequence[LoadRequest],
                     failures: List[str]) -> Dict[str, Any]:
    """ISSUE 17 CI gates for the device-free fleet simulator
    (serving/fleet_sim.py), two halves:

    * sim-vs-engine agreement — the SAME small trace through a real
      paged CPU engine and a SimEngine cloned from its cost model must
      produce the IDENTICAL structural schedule: equal tick counts,
      equal per-request token counts, byte-equal timeline signatures
      and equal goodput (scheduling decisions are shared code and a
      pure function of scheduler state, so the tolerance is exact;
      only the clock domains differ — BASELINE.md "Simulated-clock
      accounting conventions");

    * fleet determinism — a small multi-replica heavy-tail scenario
      replayed twice must produce byte-identical fleet signatures."""
    from . import fleet_sim as _fs
    from .engine import ServingEngine

    kw = dict(num_slots=4, max_length=128, prefill_batch=2,
              block_len=16)
    eng = ServingEngine(model, paged=True, **kw)
    spec = _fs.SimSpec.from_engine(eng)
    er = replay(eng, load)
    sr = replay(_fs.SimEngine(spec, **kw), load)
    agree = {
        "ticks": (er["ticks"], sr["ticks"]),
        "token_counts_equal": (
            [len(o) if o else 0 for o in er["outputs"]]
            == [len(o) if o else 0 for o in sr["outputs"]]),
        "signature_equal": er["signature"] == sr["signature"],
        "goodput": (er["slo"]["goodput"], sr["slo"]["goodput"]),
    }
    if er["ticks"] != sr["ticks"]:
        failures.append(
            f"fleet_sim: tick-count disagreement with the real engine "
            f"({er['ticks']} vs {sr['ticks']})")
    if not agree["token_counts_equal"]:
        failures.append(
            "fleet_sim: per-request token counts disagree with the "
            "real engine on the shared trace")
    if not agree["signature_equal"]:
        failures.append(
            "fleet_sim: structural timeline disagrees with the real "
            "engine on the shared trace")
    if er["slo"]["goodput"] != sr["slo"]["goodput"]:
        failures.append(
            f"fleet_sim: goodput disagreement with the real engine "
            f"({er['slo']['goodput']} vs {sr['slo']['goodput']})")
    sigs = [
        _fs.run_fleet(requests=300, replicas=4, num_slots=4,
                      admission="predictive", seed=5)["signature"]
        for _ in range(2)]
    if len(set(sigs)) != 1:
        failures.append("fleet_sim: fleet signature drift between "
                        "identical-seed replays")
    return dict(agree, fleet_signature_stable=len(set(sigs)) == 1)


def _smoke_multihost(model, load: Sequence[LoadRequest],
                     failures: List[str]) -> Dict[str, Any]:
    """ISSUE 18 CI gates for the multi-host plane, run entirely over
    LoopbackTransport (full RPC serialization, zero processes):

    * the trace replayed twice through frontend-grade plumbing
      (plane -> 2 engine workers) must keep the once-jitted budget
      (step_traces <= 1), lint clean, and replay byte-stable
      (timeline signature AND sampled outputs);

    * a worker killed mid-trace must NOT hang or drop work: every
      request still finishes, token-identical to the no-kill replay,
      each under its ONE original lifecycle uid."""
    from collections import OrderedDict

    from .engine import ServingEngine
    from .multihost import EngineWorker, LoopbackTransport, MultiHostRouter

    # the modes above created ~a dozen engines; their per-engine counter
    # children sit near the metrics_max_children cap, and a collapsed
    # {overflow} child would MERGE step-trace counts across engines and
    # fail the budget gate spuriously.  This leg builds everything
    # fresh, so start it on a clean registry (replay brackets the
    # request log with mark(), nothing above reads the registry later).
    _obs.reset()

    def mk_plane():
        workers = OrderedDict()
        engines = []
        for i in range(2):
            eng = ServingEngine(model, num_slots=4, max_length=128,
                                prefill_batch=2, paged=True, block_len=8)
            engines.append(eng)
            w = EngineWorker(eng, name=f"w{i}")
            workers[f"w{i}"] = LoopbackTransport(w.handle, name=f"w{i}")
        return MultiHostRouter(workers, policy="prefix"), engines

    runs = []
    lint_findings = -1
    for _ in range(2):
        plane, engines = mk_plane()
        if lint_findings < 0:
            kf = [f for e in engines for f in e.lint_step()]
            lint_findings = len(kf)
            if kf:
                failures.append("multihost: lint findings: "
                                + "; ".join(str(f) for f in kf))
        runs.append(replay(plane, load))
    a, b = runs
    traces = max(max(r["step_traces"]) for r in runs)
    if traces > 1:
        failures.append(f"multihost: step retraced (traces={traces})")
    if a["signature"] != b["signature"]:
        failures.append("multihost: timeline signature drift between "
                        "identical-seed runs")
    if a["outputs"] != b["outputs"]:
        failures.append("multihost: sampled-output drift between "
                        "identical-seed runs")

    # -- worker-kill leg: same trace, one transport killed mid-flight
    plane, _ = mk_plane()
    order = sorted(range(len(load)),
                   key=lambda i: (load[i].arrival, load[i].index))
    rids: Dict[int, int] = {}
    tick = 0
    nxt = 0
    killed = False
    while nxt < len(order) or any(not r.done
                                  for r in plane._reqs.values()):
        while nxt < len(order) and load[order[nxt]].arrival <= tick:
            r = load[order[nxt]]
            rids[r.index] = plane.submit(
                r.prompt, max_new_tokens=r.max_new_tokens)
            nxt += 1
        plane.step()
        tick += 1
        if not killed and tick >= 3:
            victim = next((plane.worker_of(rid) for rid in rids.values()
                           if plane.worker_of(rid) is not None), None)
            if victim is not None:
                plane._workers[victim].kill()
                killed = True
    if not killed:
        failures.append("multihost: kill leg never found a placed "
                        "request to orphan")
    kill_outputs = [plane.result(rids[r.index])
                    if r.index in rids else None for r in load]
    finished_all = all(o is not None and len(o) > 0 for o in kill_outputs)
    if not finished_all:
        failures.append("multihost: killed worker left unfinished "
                        "requests (failover hang)")
    if kill_outputs != a["outputs"]:
        failures.append("multihost: post-kill outputs drifted from the "
                        "no-kill replay (recompute-from-prefix broke "
                        "token identity)")
    one_timeline = all(
        _obs.get_request_log().event_names(
            plane.request_uid(rid)).count("submitted") == 1
        for rid in rids.values())
    if not one_timeline:
        failures.append("multihost: a failed-over request forked its "
                        "lifecycle timeline (uid not threaded)")
    return {
        "ticks": a["ticks"],
        "generated_tokens": a["generated_tokens"],
        "step_traces": traces,
        "lint_findings": lint_findings,
        "deterministic": (a["signature"] == b["signature"]
                          and a["outputs"] == b["outputs"]),
        "kill": {"fired": killed,
                 "lost_workers": len(plane.lost_workers),
                 "failovers": int(
                     plane.metrics()["aggregate"]["failovers"]),
                 "finished_all": finished_all,
                 "outputs_match_no_kill": kill_outputs == a["outputs"],
                 "one_timeline_per_uid": one_timeline},
    }


def _smoke_federated(model, load: Sequence[LoadRequest],
                     failures: List[str]) -> Dict[str, Any]:
    """ISSUE 19 CI gates for the federated observability layer, run
    over a 2-worker loopback plane under INJECTED deterministic clocks
    (every time source — the request log, the engines, the transports'
    server clocks — reads one virtual counter, with a fixed per-worker
    skew on the server side so the NTP-style estimator has real work):

    * federated ``/metrics`` counter totals must EXACTLY equal the sum
      of the per-worker (engine-scoped) registry series;
    * each transport's recovered clock offset must sit within the
      min-RTT error bound of its injected skew;
    * the merged timeline must be valid Perfetto JSON carrying the
      plane track, BOTH worker process tracks, rpc.call slices split
      into wire/in_worker, and per-request hop tracks;
    * the fleet-obs signature must replay byte-stable across two
      identical-seed runs;
    * one real HTTP GET each of /metrics and /fleet must serve the
      federated exposition and a healthy roster with tick-accurate
      heartbeat ages."""
    import urllib.request
    from collections import OrderedDict

    from ..observability.http_exposition import ExpositionServer
    from .engine import ServingEngine
    from .multihost import EngineWorker, LoopbackTransport, MultiHostRouter

    # same reasoning as the multihost leg: fresh engines near the
    # cardinality cap would coalesce, and a coalesced registry breaks
    # the exact federated-total equality this leg gates
    _obs.reset()
    log = _obs.get_request_log()
    skews = {"w0": 37.0, "w1": -53.0}       # ms the worker clock leads
    out: Dict[str, Any] = {"skews_ms": dict(skews)}

    def run_once(http_leg: bool) -> Dict[str, Any]:
        saved_clock, saved_t0 = log._clock, log._t0
        cell = {"t": 0.0}

        def vclock() -> float:              # virtual seconds; each read
            cell["t"] += 1e-4               # advances 0.1 ms
            return cell["t"]

        log._clock, log._t0 = vclock, 0.0
        try:
            workers = OrderedDict()
            engines = []
            for i in range(2):
                n = f"w{i}"
                eng = ServingEngine(model, num_slots=4, max_length=128,
                                    prefill_batch=2, paged=True,
                                    block_len=8)
                eng._clock = vclock         # SLO stamps off the wall too
                engines.append(eng)
                w = EngineWorker(eng, name=n)
                workers[n] = LoopbackTransport(
                    w.handle, name=n,
                    server_clock=(lambda s=skews[n]: log.now_ms() + s))
            plane = MultiHostRouter(workers, policy="prefix")
            rep = replay(plane, load)
            r: Dict[str, Any] = {"ticks": rep["ticks"]}

            # federated totals == sum of the per-worker registry series
            fed = plane.federation()
            merged = fed.merged()
            eids = {str(e._eid) for e in engines}
            proc = _obs.snapshot()
            bad = []
            n_counters = 0
            for name, fam in merged.items():
                if name in ("schema_version", "workers") \
                        or fam["type"] != "counter":
                    continue
                n_counters += 1
                want = sum(float(row["value"])
                           for row in proc[name]["series"]
                           if str(row["labels"].get("engine", ""))
                           in eids)
                got = float(fam["pooled"]["value"])
                if got != want:
                    bad.append(f"{name}: federated {got} != sum of "
                               f"worker registries {want}")
            if not n_counters:
                bad.append("no counter families federated at all")
            if bad:
                failures.append("federated: " + "; ".join(bad))
            r["counter_families"] = n_counters
            r["counter_totals_equal"] = not bad

            # recovered offsets within the min-RTT bound of the skew
            offs = {}
            for n, t in plane._workers.items():
                est = t.stitch.estimator
                err = abs(est.offset_ms - skews[n])
                offs[n] = {"offset_ms": round(est.offset_ms, 6),
                           "error_ms": round(err, 6),
                           "bound_ms": round(est.error_bound_ms, 6)}
                if not est.ready or err > est.error_bound_ms + 1e-9:
                    failures.append(
                        f"federated: {n} recovered offset "
                        f"{est.offset_ms} is outside the min-RTT bound "
                        f"of the injected skew {skews[n]}")
            r["offsets"] = offs

            # one merged, valid Perfetto timeline with every track kind
            trace = plane.export_merged_perfetto(
                since_uid=rep["mark"], until_uid=rep["end_mark"])
            import json as _json
            _json.dumps(trace)              # valid Perfetto JSON
            evs = trace["traceEvents"]
            procs = {e["args"]["name"] for e in evs
                     if e.get("name") == "process_name"}
            structure = {
                "worker_tracks": {"paddle_tpu worker w0",
                                  "paddle_tpu worker w1"} <= procs,
                "plane_track": "paddle_tpu plane" in procs,
                "rpc_split": (
                    any(str(e.get("name", "")).startswith("rpc.call:")
                        for e in evs)
                    and any(e.get("name") == "wire" for e in evs)
                    and any(e.get("name") == "in_worker" for e in evs)),
                "request_tracks": any(
                    str(e.get("name", "")).startswith("on w")
                    for e in evs)}
            if not all(structure.values()):
                failures.append(
                    f"federated: merged timeline is missing tracks: "
                    f"{[k for k, v in structure.items() if not v]}")
            r["merged_timeline"] = structure

            # tick-accurate heartbeat ages + live roster
            fleet = plane.fleet_report()
            hb = plane._hb_every
            exp_age = plane._ticks - hb * ((plane._ticks - 1) // hb)
            ages = {n: w["heartbeat_age_ticks"]
                    for n, w in fleet["workers"].items()}
            if not all(w["alive"] for w in fleet["workers"].values()):
                failures.append("federated: a loopback worker reported "
                                "dead on a clean run")
            if any(a != exp_age for a in ages.values()):
                failures.append(
                    f"federated: heartbeat ages {ages} are not tick-"
                    f"accurate (expected {exp_age} after "
                    f"{plane._ticks} ticks, heartbeat_every={hb})")
            r["heartbeat_age_ticks"] = ages

            r["signature"] = plane.fleet_obs_signature(
                since_uid=rep["mark"], until_uid=rep["end_mark"])

            if http_leg:
                with ExpositionServer(port=-1, engines=[plane]) as srv:
                    base = f"http://127.0.0.1:{srv.port}"
                    text = urllib.request.urlopen(
                        base + "/metrics", timeout=10).read().decode()
                    fl = _json.loads(urllib.request.urlopen(
                        base + "/fleet", timeout=10).read().decode())
                http_ok = {
                    "metrics_has_fleet_prefix":
                        "paddle_tpu_fleet_" in text,
                    "metrics_has_worker_labels":
                        'worker="w0"' in text and 'worker="w1"' in text,
                    "fleet_reports_both_workers": all(
                        fl["workers"].get(n, {}).get("alive")
                        for n in ("w0", "w1"))}
                if not all(http_ok.values()):
                    failures.append(
                        f"federated: HTTP exposition gaps: "
                        f"{[k for k, v in http_ok.items() if not v]}")
                r["http"] = http_ok
            return r
        finally:
            log._clock, log._t0 = saved_clock, saved_t0

    a = run_once(http_leg=True)
    b = run_once(http_leg=False)
    if a["signature"] != b["signature"]:
        failures.append("federated: fleet-obs signature drift between "
                        "identical-seed replays")
    out.update(a)
    out["signature_stable"] = a["signature"] == b["signature"]
    return out


def _smoke_spec_model(model, load: Sequence[LoadRequest],
                      failures: List[str]) -> Dict[str, Any]:
    """ISSUE 20 CI gates for draft-model speculation: the trace replayed
    twice through a 2-replica loopback plane running MIXED drafters
    (replica w0 a truncated draft model, w1 the n-gram drafter) must
    keep BOTH once-jitted budgets (verify step and draft step, 1 trace
    each), replay byte-stable (timeline signature and sampled outputs),
    lint clean, and the per-shard kernel geometry a model-parallel
    engine would pre-flight (heads/mp, the ``mpN-shard`` variant) must
    be finding-free — all device-free except the tiny CPU replay."""
    from collections import OrderedDict

    from .. import static_analysis as _sa
    from ..models.llama import draft_model_from
    from .engine import ServingEngine
    from .multihost import EngineWorker, LoopbackTransport, MultiHostRouter

    # fresh registry: this leg builds its own engines and reads their
    # trace budgets; collapsed {overflow} children from the modes above
    # would merge counters across engines (same reasoning as multihost)
    _obs.reset()
    dm, dparams = draft_model_from(model, num_layers=1)

    def mk_plane():
        workers = OrderedDict()
        engines = []
        for name, kw in (("w0", {"drafter": "model",
                                 "draft_model": (dm, dparams)}),
                         ("w1", {"drafter": "ngram"})):
            eng = ServingEngine(model, num_slots=4, max_length=128,
                                prefill_batch=2, spec_decode=True,
                                spec_k=3, **kw)
            engines.append(eng)
            w = EngineWorker(eng, name=name)
            workers[name] = LoopbackTransport(w.handle, name=name)
        return MultiHostRouter(workers, policy="prefix"), engines

    runs = []
    lint_findings = -1
    draft_traces = 0
    drafted: Dict[str, int] = {}
    for _ in range(2):
        plane, engines = mk_plane()
        if lint_findings < 0:
            kf = [f for e in engines for f in e.lint_step()]
            lint_findings = len(kf)
            if kf:
                failures.append("spec_model: lint findings: "
                                + "; ".join(str(f) for f in kf))
        runs.append(replay(plane, load))
        for e in engines:
            by = e.metrics().get("spec", {}).get("by_drafter", {})
            for kind, m in by.items():
                drafted[kind] = (drafted.get(kind, 0)
                                 + m["drafted_tokens"])
            d = e._drafter
            if getattr(d, "uses_device", False):
                draft_traces = max(draft_traces, d.draft_traces)
    a, b = runs
    traces = max(max(r["step_traces"]) for r in runs)
    if traces > 1:
        failures.append(f"spec_model: verify step retraced "
                        f"(traces={traces})")
    if draft_traces > 1:
        failures.append(f"spec_model: draft step retraced "
                        f"(traces={draft_traces})")
    if a["signature"] != b["signature"]:
        failures.append("spec_model: timeline signature drift between "
                        "identical-seed runs")
    if a["outputs"] != b["outputs"]:
        failures.append("spec_model: sampled-output drift between "
                        "identical-seed runs")
    if drafted.get("model", 0) <= 0:
        failures.append("spec_model: the draft-model replica proposed "
                        "nothing — the mode is not exercising the "
                        "drafter")
    # per-shard pre-flight: the exact geometry a mesh (mp=2) engine's
    # _kernel_specs projects — heads/mp, head_dim and cache length
    # rounded to kernel tiles — must lint clean (static, no devices)
    c = model.config
    mp = 2
    hq = max(int(c.num_attention_heads) // mp, 1)
    hkv = max(int(c.num_key_value_heads) // mp, 1)
    shard_spec = _sa.decode_attention_spec(
        4, 4, hq, hkv, 128, kv_len=4096,
        variant=f"contiguous,spec_verify,s=4,mp{mp}-shard")
    shard_findings = _sa.analyze_kernels([shard_spec])
    if shard_findings:
        failures.append("spec_model: per-shard kernel pre-flight "
                        "findings: "
                        + "; ".join(str(f) for f in shard_findings))
    return {
        "ticks": a["ticks"],
        "generated_tokens": a["generated_tokens"],
        "step_traces": traces,
        "draft_step_traces": draft_traces,
        "lint_findings": lint_findings,
        "kernel_findings": len(shard_findings),
        "drafted_tokens_by_kind": dict(sorted(drafted.items())),
        "deterministic": (a["signature"] == b["signature"]
                          and a["outputs"] == b["outputs"]),
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.loadgen",
        description="trace-driven serving load harness")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny seeded load against both engine modes on "
                         "CPU; exits non-zero on retrace-budget or "
                         "determinism drift (the CI hook)")
    args = ap.parse_args(argv)
    if args.smoke:
        return _smoke()
    ap.print_help()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
