"""Multi-host serving plane (ISSUE 18): process-separated router,
engine workers behind an RPC surface, KV migration, and a streaming
HTTP front end.

Layering::

    frontend.ServingFrontend        streaming /v1/generate, driver thread
        plane.MultiHostRouter       placement / failover / disagg policy
            transport.Transport     Loopback (in-process) or Socket (TCP)
                worker.EngineWorker RPC verbs over ONE ServingEngine

The SAME protocol runs in-process over :class:`LoopbackTransport`
(every tier-1 test, the loadgen smoke, the fleet sim) and over real
sockets between OS processes (``python -m paddle_tpu.serving.multihost
--worker`` / ``--selfcheck``) — CI exercises the full wire path without
ever spawning a process.
"""

from .transport import (IDEMPOTENT_METHODS, LoopbackTransport, RpcError,
                        RpcServer, SocketTransport, StoreClient,
                        StoreServer, Transport, TransportError,
                        decode_message, encode_message, rendezvous)
from .worker import EngineWorker
from .plane import MultiHostRouter
from .frontend import ServingFrontend

__all__ = [
    "IDEMPOTENT_METHODS", "LoopbackTransport", "RpcError", "RpcServer",
    "SocketTransport", "StoreClient", "StoreServer", "Transport",
    "TransportError", "decode_message", "encode_message", "rendezvous",
    "EngineWorker", "MultiHostRouter", "ServingFrontend",
]
