"""CLI for the multi-host serving plane (ISSUE 18).

Two modes:

``--worker --name w0 --store-host H --store-port P [--seed 7]``
    Run ONE engine worker in THIS process: build the deterministic tiny
    model (same seed => same weights in every process), serve the
    EngineWorker RPC surface on an ephemeral localhost port, publish
    the address under ``worker/<name>`` in the rendezvous store, and
    spin until the plane sends ``shutdown``.

``--selfcheck``
    The end-to-end gate: spawn TWO real worker processes on localhost,
    rendezvous through a TCP store, run a short deterministic trace
    through the socket plane — killing one worker process mid-trace —
    and verify (a) every request still finishes, (b) outputs are
    token-identical to a single in-process reference engine, (c) every
    request has ONE lifecycle timeline (one ``submitted``, a
    ``retired``, and ``worker_lost -> failover -> placed`` in order on
    the victims), and (d) the merged fleet Perfetto timeline (ISSUE 19)
    contains both worker process tracks plus at least one stitched
    cross-process request track.  Exits non-zero on any parity or
    timeline drift — the verify-skill hook for the real-process path.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from collections import OrderedDict
from typing import List

_TRACE_SEED = 11
_MODEL_SEED = 7
_ENGINE_KW = dict(num_slots=4, max_length=128, prefill_batch=2,
                  paged=True, block_len=8)


def _build_engine():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaForCausalLM, tiny_llama_config
    from paddle_tpu.serving.engine import ServingEngine
    pt.seed(_MODEL_SEED)
    model = LlamaForCausalLM(tiny_llama_config())
    return ServingEngine(model, **_ENGINE_KW)


def _trace(n: int = 4):
    import numpy as np
    rng = np.random.default_rng(_TRACE_SEED)
    return [rng.integers(3, 90, size=int(ln)).tolist()
            for ln in rng.integers(5, 17, size=n)]


def _run_worker(args: argparse.Namespace) -> int:
    from .transport import RpcServer, StoreClient
    from .worker import EngineWorker
    worker = EngineWorker(_build_engine(), name=args.name)
    # the RPC server stamps t1/t2 with the worker's request-log clock,
    # so the plane's offset estimate maps shipped events and handler
    # slices onto the plane clock in one go (ISSUE 19)
    rpc = RpcServer(worker.handle, host="127.0.0.1", port=0,
                    clock=worker.clock_ms)
    store = StoreClient(args.store_host, args.store_port)
    store.set(f"worker/{args.name}",
              {"host": rpc.host, "port": rpc.port})
    print(f"[worker {args.name}] serving on {rpc.host}:{rpc.port}",
          flush=True)
    try:
        while not worker.stop_requested:
            time.sleep(0.05)
    finally:
        rpc.stop()
        store.close()
    return 0


def _spawn_worker(name: str, store_host: str, store_port: int
                  ) -> "subprocess.Popen[bytes]":
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.serving.multihost", "--worker",
         "--name", name, "--store-host", store_host,
         "--store-port", str(store_port)],
        env=env)


def _selfcheck(args: argparse.Namespace) -> int:
    from paddle_tpu import observability as obs
    from .plane import MultiHostRouter
    from .transport import (SocketTransport, StoreClient, StoreServer,
                            rendezvous)

    failures: List[str] = []

    def check(ok: bool, what: str) -> None:
        print(f"[selfcheck] {'ok  ' if ok else 'FAIL'} {what}", flush=True)
        if not ok:
            failures.append(what)

    prompts = _trace()
    store = StoreServer(host="127.0.0.1", port=0)
    names = ["w0", "w1"]
    print(f"[selfcheck] store on {store.host}:{store.port}; "
          f"spawning workers {names}", flush=True)
    procs = [_spawn_worker(n, store.host, store.port) for n in names]
    try:
        # workers warm up (jax import + jit) while the reference builds
        print("[selfcheck] building in-process reference engine",
              flush=True)
        ref = _build_engine()
        import numpy as np
        rref = [ref.submit(np.asarray(p, np.int32), max_new_tokens=8)
                for p in prompts]
        ref_out = dict(ref.drain())
        expected = [ref_out[r] for r in rref]
        client = StoreClient(store.host, store.port)
        addrs = rendezvous(client, names, timeout=args.timeout)
        print(f"[selfcheck] rendezvous complete: {addrs}", flush=True)
        transports = OrderedDict(
            (n, SocketTransport(addrs[n][0], addrs[n][1], name=n,
                                timeout=10.0, retries=1, backoff=0.05))
            for n in names)
        plane = MultiHostRouter(transports, policy="prefix")
        rids = [plane.submit(p, max_new_tokens=8) for p in prompts]
        for _ in range(3):
            plane.step()
        victim = None
        for rid in rids:
            w = plane.worker_of(rid)
            if w is not None:
                victim = w
                break
        check(victim is not None, "some request is placed before the kill")
        if victim is not None:
            k = names.index(victim)
            print(f"[selfcheck] killing worker process {victim} "
                  f"(pid {procs[k].pid}) mid-trace", flush=True)
            procs[k].kill()
            procs[k].wait(timeout=30)
        out = dict(plane.drain())
        check(all(out[rids[i]] == list(expected[i])
                  for i in range(len(prompts))),
              "outputs token-identical to the in-process reference")
        check(len(plane.lost_workers) == 1, "exactly one worker lost")
        check(plane.step_traces <= 1, "surviving engine once-jitted")
        rlog = obs.get_request_log()
        saw_failover = False
        for rid in rids:
            uid = plane.request_uid(rid)
            evs = [ev["name"] for ev in rlog.timeline(uid)]
            check(evs.count("submitted") == 1,
                  f"uid {uid}: one submitted event")
            check("retired" in evs, f"uid {uid}: retired")
            if "failover" in evs:
                saw_failover = True
                order = [evs.index("worker_lost"), evs.index("failover"),
                         len(evs) - 1 - evs[::-1].index("placed")]
                check(order == sorted(order),
                      f"uid {uid}: worker_lost -> failover -> placed order")
        check(saw_failover, "at least one request failed over")
        # ISSUE 19: the merged fleet timeline over REAL processes must
        # stitch both workers' clock domains onto the plane clock
        trace = plane.export_merged_perfetto()
        tracks = {e["args"]["name"] for e in trace["traceEvents"]
                  if e.get("name") == "process_name"}
        check({"paddle_tpu worker w0",
               "paddle_tpu worker w1"} <= tracks,
              "merged timeline carries both worker process tracks")
        stitched = any(
            str(e.get("name", "")).startswith("on w")
            and e.get("ph") == "X"
            for e in trace["traceEvents"])
        check(stitched, "merged timeline has >= 1 stitched "
                        "cross-process request track")
        check(any(str(e.get("name", "")).startswith("rpc.call:")
                  for e in trace["traceEvents"]),
              "merged timeline splits rpc.call slices")
        plane.shutdown()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        store.stop()
    if failures:
        print(f"[selfcheck] FAILED: {failures}", flush=True)
        return 1
    print("[selfcheck] PASS", flush=True)
    return 0


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(prog="paddle_tpu.serving.multihost")
    ap.add_argument("--worker", action="store_true",
                    help="run one engine worker process")
    ap.add_argument("--selfcheck", action="store_true",
                    help="spawn 2 worker processes, run the kill-"
                         "failover trace, exit non-zero on drift")
    ap.add_argument("--name", default="w0")
    ap.add_argument("--store-host", default="127.0.0.1")
    ap.add_argument("--store-port", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=240.0,
                    help="rendezvous timeout (workers must import jax "
                         "and jit the tiny model first)")
    args = ap.parse_args(argv)
    if args.worker:
        return _run_worker(args)
    if args.selfcheck:
        return _selfcheck(args)
    ap.error("pick a mode: --worker or --selfcheck")
    return 2


if __name__ == "__main__":
    sys.exit(main())
