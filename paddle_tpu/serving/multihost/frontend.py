"""Streaming HTTP front end of the multi-host plane (ISSUE 18).

:class:`ServingFrontend` owns a :class:`~.plane.MultiHostRouter`,
drives it from a single background step thread, and exposes a
``stream(payload) -> Iterator[dict]`` generator that the extended
PR-15 :class:`~paddle_tpu.observability.http_exposition.
ExpositionServer` plugs straight into ``POST /v1/generate``.

The streaming contract ("tokens surface per tick, not at retirement"):
the first yielded line carries the request's lifecycle ``uid``; every
subsequent line carries the tokens that surfaced that plane tick; the
final line carries ``done`` plus totals.  TTFT under streaming is
first-chunk-on-wire (BASELINE.md "Multi-host accounting conventions"),
which is why the driver thread flushes deltas into per-request queues
the moment ``plane.step()`` returns rather than waiting for drain.

The plane itself is single-threaded by design (deterministic ticks);
the front end serializes HTTP-handler submits against the driver's
steps with one lock, so concurrency lives at the edges and the tick
order — which the timeline signature hashes — stays deterministic.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Iterator, Optional

from ... import flags as _flags
from ...observability.http_exposition import ExpositionServer
from ..engine import SamplingParams
from .plane import MultiHostRouter

__all__ = ["ServingFrontend"]


class ServingFrontend:
    """Background-driven plane + the ``stream`` generator surface."""

    def __init__(self, plane: MultiHostRouter,
                 poll_s: Optional[float] = None):
        self.plane = plane
        self._poll_s = float(
            poll_s if poll_s is not None
            else _flags.flag("multihost_stream_poll_s"))
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "ServingFrontend":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._drive, name="multihost-frontend-driver",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "ServingFrontend":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    def serve(self, port: int = -1) -> ExpositionServer:
        """An ExpositionServer wired to this front end: /metrics (the
        process exposition plus the plane's federated per-worker
        series), /fleet (live per-worker health), /healthz, /requests
        (uid lookup included) and the streaming POST /v1/generate, all
        on one port."""
        self.start()
        return ExpositionServer(port=port, engines=[self.plane],
                                generator=self).start()

    # -- the driver ----------------------------------------------------

    def _drive(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                busy = any(not r.done
                           for r in self.plane._reqs.values())
                if busy:
                    self.plane.step()
            if not busy:
                self._stop.wait(self._poll_s)

    # -- the generator the HTTP layer consumes -------------------------

    def stream(self, payload: Dict[str, Any]) -> Iterator[Dict[str, Any]]:
        """Yield JSON-able chunks for one generate call.  Lines:
        ``{"uid", "rid"}`` (accepted), then ``{"tokens": [...]}`` per
        tick that surfaced tokens, then ``{"done": true, "uid",
        "tokens_total"}``.  A rejection yields one ``{"error": ...}``
        line instead (the uid's timeline holds the rejection trail)."""
        prompt = [int(t) for t in payload.get("prompt", [])]
        sp = payload.get("sampling") or {}
        sampling = None
        if sp:
            sampling = SamplingParams(
                temperature=float(sp.get("temperature", 0.0)),
                top_k=int(sp.get("top_k", 0)),
                top_p=float(sp.get("top_p", 1.0)))
        q: "queue.Queue[Dict[str, Any]]" = queue.Queue()
        with self._lock:
            try:
                rid = self.plane.submit(
                    prompt,
                    max_new_tokens=int(payload.get("max_new_tokens", 32)),
                    sampling=sampling,
                    session=payload.get("session"),
                    priority=int(payload.get("priority", 0)),
                    ttft_slo_ms=payload.get("ttft_slo_ms"),
                    tpot_slo_ms=payload.get("tpot_slo_ms"))
            except ValueError as e:
                yield {"error": str(e)}
                return
            uid = self.plane.request_uid(rid)
            self.plane.attach_stream(rid, q.put)
        yield {"uid": int(uid), "rid": int(rid)}
        while True:
            item = q.get()
            if item["tokens"]:
                yield {"tokens": item["tokens"]}
            if item["done"]:
                with self._lock:
                    total = len(self.plane.result(rid))
                yield {"done": True, "uid": int(uid),
                       "tokens_total": int(total)}
                return
