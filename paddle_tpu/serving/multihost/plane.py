"""The process-separated serving plane (ISSUE 18): a router that talks
to :class:`~.worker.EngineWorker`\\ s over :class:`~.transport.Transport`
handles instead of holding engines in-process.

What carries over from the PR-9 in-process router, verbatim in spirit:

  * ONE lifecycle uid per request, minted plane-side at submit and
    threaded through every worker via ``request_uid`` — placement,
    admission failover, migration, and worker-loss failover all append
    to the SAME timeline;
  * prefix-affinity placement via the read-only ``prefix_probe`` RPC,
    session affinity (sessions never migrate while their worker lives),
    and admission failover: a worker whose engine rejects (the RPC
    surfaces the engine's ValueError as ``RpcError(kind='ValueError')``)
    just moves placement to the next candidate.

What is new:

  * **worker loss is survivable** — a heartbeat ping every
    ``FLAGS_multihost_heartbeat_every`` plane ticks (tick-counted, so
    loopback replays stay byte-deterministic) plus transport errors on
    any call mark a worker lost; its in-flight requests are re-admitted
    on the survivors by resubmitting ``prompt + generated`` with the
    remaining token budget — the PR-16 recompute-from-prefix idea at
    plane scope.  Greedy decode conditioned on the committed tokens
    continues the sequence identically, so failover is invisible in the
    output stream;
  * **disaggregated prefill/decode** (``policy='disagg'``) — new
    requests land on the prefill pool; the moment a request's first
    token surfaces, its KV chain migrates by value (export_request /
    import_request over the transport) to the least-loaded decode
    worker, which finishes the request without ever running a prefill.
    Migration bytes are accounted (``multihost.migration_bytes``) and
    are NOT streamed-KV bytes — BASELINE.md "Multi-host accounting
    conventions";
  * **per-tick token streaming** — ``step`` responses carry token
    deltas; ``attach_stream(rid, put)`` forwards each delta (and the
    final done marker) to the front end the tick it surfaces.
"""

from __future__ import annotations

import itertools
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ... import flags as _flags
from ... import observability as _obs
from ...observability import federation as _fed
from ..engine import SamplingParams
from .transport import RpcError, Transport, TransportError

__all__ = ["MultiHostRouter"]

_PLANE_IDS = itertools.count()


class _Req:
    __slots__ = ("rid", "uid", "prompt", "max_new", "sampling", "priority",
                 "ttft_slo_ms", "tpot_slo_ms", "session", "worker", "wrid",
                 "generated", "done", "phase", "stream")

    def __init__(self, rid: int, uid: int, prompt: List[int], max_new: int,
                 sampling: Optional[SamplingParams], priority: int,
                 ttft_slo_ms: Optional[float], tpot_slo_ms: Optional[float],
                 session: Any):
        self.rid = rid
        self.uid = uid
        self.prompt = prompt
        self.max_new = max_new
        self.sampling = sampling
        self.priority = priority
        self.ttft_slo_ms = ttft_slo_ms
        self.tpot_slo_ms = tpot_slo_ms
        self.session = session
        self.worker: Optional[str] = None
        self.wrid: Optional[int] = None
        self.generated: List[int] = []
        self.done = False
        self.phase = "prefill"            # disagg: prefill -> decode
        self.stream: Optional[Callable[[Dict[str, Any]], None]] = None


class MultiHostRouter:
    """Router over named worker transports.

    ``policy``: ``'prefix'`` (warm-token affinity, then least loaded,
    then name order), ``'round_robin'``, or ``'disagg'`` (``prefill``
    names the prefill pool; every other worker is a decode worker).
    The surface matches what ``loadgen.replay`` drives: submit / step /
    result / cancel / drain plus the busy properties."""

    def __init__(self, transports: "OrderedDict[str, Transport]",
                 policy: str = "prefix",
                 prefill: Optional[Sequence[str]] = None,
                 heartbeat_every: Optional[int] = None):
        if policy not in ("prefix", "round_robin", "disagg"):
            raise ValueError(
                f"policy must be prefix|round_robin|disagg, got {policy!r}")
        self._workers: "OrderedDict[str, Transport]" = OrderedDict(
            transports)
        if not self._workers:
            raise ValueError("need at least one worker transport")
        self.policy = policy
        self._prefill_pool = list(prefill or [])
        if policy == "disagg":
            missing = [n for n in self._prefill_pool
                       if n not in self._workers]
            if missing or not self._prefill_pool:
                raise ValueError(
                    f"disagg policy needs a prefill pool drawn from the "
                    f"workers (missing: {missing})")
            if not [n for n in self._workers
                    if n not in self._prefill_pool]:
                raise ValueError("disagg policy needs >= 1 decode worker")
        self._hb_every = int(
            heartbeat_every if heartbeat_every is not None
            else _flags.flag("multihost_heartbeat_every"))
        self._dead: Dict[str, str] = {}         # name -> loss reason
        self._reqs: Dict[int, _Req] = {}
        self._by_worker: Dict[Tuple[str, int], int] = {}
        self._affinity: Dict[Any, str] = {}     # session -> worker name
        self._pending_imports: List[Tuple[int, Dict[str, Any]]] = []
        self._status: Dict[str, Dict[str, int]] = {}
        self._next_rid = 0
        self._rr = 0
        self._ticks = 0
        self._rlog = _obs.get_request_log()
        self._tracer = _obs.get_tracer()
        self._pid = str(next(_PLANE_IDS))
        reg = _obs.default_registry()
        lbl = {"plane": self._pid}
        self._m_migrations = reg.counter(
            "multihost.migrations",
            "requests migrated prefill -> decode worker").labels(**lbl)
        self._m_mig_bytes = reg.counter(
            "multihost.migration_bytes",
            "KV payload bytes moved across workers by migration "
            "(transport traffic, never streamed-KV bytes)").labels(**lbl)
        self._m_failovers = reg.counter(
            "multihost.failovers",
            "in-flight requests re-admitted after worker loss").labels(
                **lbl)
        self._m_lost = reg.counter(
            "multihost.workers_lost",
            "workers marked lost (heartbeat or call failure)").labels(
                **lbl)
        self._m_heartbeats = reg.counter(
            "multihost.heartbeats", "heartbeat pings issued").labels(**lbl)
        # fleet-health observability (ISSUE 19): per-worker heartbeat
        # age in plane ticks and loss classification by reason
        self._f_hb_age = reg.gauge(
            "plane.heartbeat_age_ticks",
            "plane ticks since the worker's last successful heartbeat")
        self._f_worker_lost = reg.counter(
            "plane.worker_lost",
            "workers marked lost, by reason "
            "(missed_heartbeat|transport_error)")
        self._last_hb_tick: Dict[str, int] = {n: 0 for n in self._workers}

    # -- roster --------------------------------------------------------

    @property
    def live_workers(self) -> List[str]:
        return [n for n in self._workers if n not in self._dead]

    @property
    def lost_workers(self) -> Dict[str, str]:
        return dict(self._dead)

    def _decode_pool(self) -> List[str]:
        return [n for n in self.live_workers
                if n not in self._prefill_pool]

    def _mark_lost(self, name: str, reason: str) -> None:
        if name in self._dead:
            return
        self._dead[name] = reason
        self._m_lost.inc()
        # one reason label per loss class: a missed heartbeat is the
        # silent kind, every other loss surfaced as a TransportError
        self._f_worker_lost.labels(
            plane=self._pid, worker=name,
            reason=("missed_heartbeat" if reason == "heartbeat_failed"
                    else "transport_error")).inc()
        self._status.pop(name, None)
        self._tracer.instant("multihost.worker_lost", worker=name,
                             reason=reason)
        for s in [s for s, w in self._affinity.items() if w == name]:
            del self._affinity[s]          # sessions re-pin cold
        self._failover_worker(name, reason)

    # -- placement -----------------------------------------------------

    def _load(self, name: str) -> int:
        st = self._status.get(name, {})
        return (int(st.get("queue_depth", 0)) + int(st.get("num_active", 0))
                + int(st.get("num_pending", 0))
                + int(st.get("num_preempted", 0)))

    def _candidates(self, prompt: List[int], session: Any) -> List[str]:
        if self.policy == "disagg":
            pool = [n for n in self._prefill_pool if n not in self._dead]
            # degrade gracefully: with the whole prefill pool gone the
            # decode workers take whole requests (colocated fallback)
            pool = pool or self.live_workers
        else:
            pool = self.live_workers
        if session is not None and session in self._affinity:
            pin = self._affinity[session]
            if pin in pool:
                return [pin] + [n for n in pool if n != pin]
            del self._affinity[session]
        if self.policy == "round_robin":
            k = self._rr % max(1, len(pool))
            self._rr += 1
            return pool[k:] + pool[:k]
        if self.policy == "prefix":
            warm: Dict[str, int] = {}
            for n in pool:
                try:
                    warm[n] = int(self._workers[n].call(
                        "prefix_probe",
                        {"prompt": prompt})["warm_tokens"])
                except (TransportError, RpcError):
                    warm[n] = -1
            return sorted(pool, key=lambda n: (-warm[n], self._load(n), n))
        return sorted(pool, key=lambda n: (self._load(n), n))

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               sampling: Optional[SamplingParams] = None,
               session: Any = None, priority: int = 0,
               ttft_slo_ms: Optional[float] = None,
               tpot_slo_ms: Optional[float] = None) -> int:
        """Mint ONE lifecycle uid, then walk the placement order with
        admission failover: a rejecting worker logs ``rejected`` under
        the same uid and the walk moves on; only when EVERY candidate
        rejects does the ValueError reach the caller (the last
        rejection's message, PR-9 contract)."""
        prompt_l = [int(t) for t in np.asarray(prompt).reshape(-1)]
        uid = self._rlog.new_uid()
        self._rlog.event(uid, "submitted", router=self._pid,
                         prompt_len=len(prompt_l),
                         max_new_tokens=int(max_new_tokens))
        rid = self._next_rid
        self._next_rid += 1
        req = _Req(rid, uid, prompt_l, int(max_new_tokens), sampling,
                   int(priority), ttft_slo_ms, tpot_slo_ms, session)
        self._reqs[rid] = req
        err: Optional[str] = None
        for name in self._candidates(prompt_l, session):
            got = self._place(req, name)
            if got is True:
                if session is not None:
                    self._affinity.setdefault(session, name)
                return rid
            if got is not False:
                err = got                   # rejection message, walk on
        del self._reqs[rid]
        raise ValueError(err or "no live workers available")

    def _place(self, req: _Req, name: str):
        """True = placed, False = transport loss, str = rejected."""
        t = self._workers[name]
        payload = {"prompt": req.prompt, "max_new_tokens": req.max_new,
                   "request_uid": req.uid, "priority": req.priority,
                   "ttft_slo_ms": req.ttft_slo_ms,
                   "tpot_slo_ms": req.tpot_slo_ms}
        if req.sampling is not None:
            payload["sampling"] = {
                "temperature": req.sampling.temperature,
                "top_k": req.sampling.top_k,
                "top_p": req.sampling.top_p}
        try:
            wrid = int(t.call("submit", payload)["rid"])
        except RpcError as e:
            if e.kind == "ValueError":
                return e.message            # engine rejection: walk on
            raise
        except TransportError:
            self._mark_lost(name, "submit_failed")
            return False
        req.worker, req.wrid = name, wrid
        self._by_worker[(name, wrid)] = req.rid
        self._rlog.event(req.uid, "placed", router=self._pid, worker=name,
                         route=self.policy)
        return True

    # -- the tick ------------------------------------------------------

    def step(self) -> List[int]:
        """One plane tick: heartbeat the roster, retry parked imports,
        step every live worker (collect deltas / finishes), then run
        the disagg migrations that became ready.  Returns plane rids
        finished this tick."""
        if self._hb_every > 0 and self._ticks % self._hb_every == 0:
            for name in list(self.live_workers):
                self._m_heartbeats.inc()
                try:
                    self._workers[name].call("ping", {})
                    self._last_hb_tick[name] = self._ticks
                except (TransportError, RpcError):
                    self._mark_lost(name, "heartbeat_failed")
        self._retry_pending_imports()
        finished: List[int] = []
        for name in list(self.live_workers):
            try:
                out = self._workers[name].call("step", {})
            except TransportError:
                self._mark_lost(name, "step_failed")
                continue
            except RpcError:
                continue
            self._status[name] = dict(out.get("status", {}))
            if not self._workers[name].shares_process:
                # process-separated worker: merge its shipped request-
                # log events so each uid keeps ONE lifecycle timeline
                # in THIS process (loopback shares the log already).
                # Worker timestamps map onto the plane clock through
                # the transport's stitched offset estimate; without an
                # estimate yet they fall back to the arrival stamp.
                st = getattr(self._workers[name], "stitch", None)
                for ev in out.get("events", []):
                    t_ms = ev.get("t_ms")
                    if t_ms is not None and st is not None and st.ready:
                        t_ms = st.to_plane_ms(float(t_ms))
                    else:
                        t_ms = None
                    self._rlog.event(int(ev["uid"]), str(ev["name"]),
                                     t_ms=t_ms,
                                     **dict(ev.get("attrs") or {}))
            for wr, toks in out.get("deltas", {}).items():
                rid = self._by_worker.get((name, int(wr)))
                if rid is None:
                    continue
                req = self._reqs[rid]
                req.generated.extend(int(t) for t in toks)
                if req.stream is not None:
                    req.stream({"tokens": [int(t) for t in toks],
                                "done": False})
            for wr in out.get("finished", []):
                rid = self._by_worker.get((name, int(wr)))
                if rid is None:
                    continue
                req = self._reqs[rid]
                req.done = True
                finished.append(rid)
                if req.stream is not None:
                    req.stream({"tokens": [], "done": True})
        if self.policy == "disagg":
            self._run_migrations()
        self._ticks += 1
        # gauge AFTER the tick count advances: the exported age matches
        # what fleet_report computes between ticks, so a scrape and the
        # /fleet endpoint never disagree by the in-tick off-by-one
        for name in self.live_workers:
            self._f_hb_age.labels(plane=self._pid, worker=name).set(
                self._ticks - self._last_hb_tick.get(name, 0))
        return finished

    def _run_migrations(self) -> None:
        """Move every prefill-phase request whose first token has
        surfaced to a decode worker.  Export releases the source slot;
        if the destination cannot take the record right now it parks
        plane-side and retries next tick — nothing is lost either way."""
        decode = self._decode_pool()
        if not decode:
            return                          # degrade: finish colocated
        for req in list(self._reqs.values()):
            if (req.done or req.phase != "prefill" or not req.generated
                    or req.worker not in self._prefill_pool
                    or req.worker in self._dead):
                continue
            src = self._workers[req.worker]
            try:
                record = src.call("export_request",
                                  {"rid": req.wrid})["record"]
            except TransportError:
                self._mark_lost(req.worker, "export_failed")
                continue
            except RpcError:
                continue
            if record is None:
                continue                    # not in a decode slot yet
            self._by_worker.pop((req.worker, req.wrid), None)
            req.worker, req.wrid = None, None
            req.phase = "migrating"
            if not self._import_record(req, record):
                self._pending_imports.append((req.rid, record))

    def _import_record(self, req: _Req, record: Dict[str, Any]) -> bool:
        nbytes = int(record.get("payload_bytes", 0))
        for name in sorted(self._decode_pool(),
                           key=lambda n: (self._load(n), n)):
            try:
                wrid = self._workers[name].call(
                    "import_request", {"record": record})["rid"]
            except TransportError:
                self._mark_lost(name, "import_failed")
                continue
            except RpcError:
                continue
            if wrid is None:
                continue                    # that pool is full; next
            req.worker, req.wrid = name, int(wrid)
            req.phase = "decode"
            self._by_worker[(name, int(wrid))] = req.rid
            self._m_migrations.inc()
            self._m_mig_bytes.inc(nbytes)
            self._rlog.event(req.uid, "migrated", router=self._pid,
                             worker=name,
                             blocks=len(record["blocks"]["entries"]),
                             bytes=nbytes)
            return True
        return False

    def _retry_pending_imports(self) -> None:
        still: List[Tuple[int, Dict[str, Any]]] = []
        for rid, record in self._pending_imports:
            req = self._reqs[rid]
            if req.done or not self._import_record(req, record):
                if not req.done:
                    still.append((rid, record))
        self._pending_imports = still

    # -- worker-loss failover ------------------------------------------

    def _failover_worker(self, name: str, reason: str) -> None:
        """Re-admit every in-flight request of a lost worker on the
        survivors: resubmit ``prompt + generated`` with the REMAINING
        budget under the SAME uid — the recompute-from-prefix path at
        plane scope.  Greedy decode continues the stream identically;
        the one timeline records loss, failover, and the new placement
        in order."""
        victims = [r for r in self._reqs.values()
                   if r.worker == name and not r.done]
        for req in victims:
            self._by_worker.pop((name, req.wrid), None)
            req.worker, req.wrid = None, None
            self._rlog.event(req.uid, "worker_lost", router=self._pid,
                             worker=name, reason=reason,
                             tokens_committed=len(req.generated))
            left = req.max_new - len(req.generated)
            if left <= 0:
                # everything it owed was already streamed: finish it
                req.done = True
                if req.stream is not None:
                    req.stream({"tokens": [], "done": True})
                continue
            self._m_failovers.inc()
            self._rlog.event(req.uid, "failover", router=self._pid,
                             tokens_committed=len(req.generated))
            carry = req.prompt + req.generated
            placed = False
            if self.policy == "disagg":
                pool = [n for n in self._prefill_pool
                        if n not in self._dead] or self.live_workers
            else:
                pool = self.live_workers
            req2 = _Req(req.rid, req.uid, carry, left, req.sampling,
                        req.priority, req.ttft_slo_ms, req.tpot_slo_ms,
                        req.session)
            for cand in sorted(pool, key=lambda n: (self._load(n), n)):
                got = self._place(req2, cand)
                if got is True:
                    req.worker, req.wrid = req2.worker, req2.wrid
                    req.phase = "prefill"
                    placed = True
                    break
            if not placed:
                req.done = True
                self._rlog.event(req.uid, "retired", router=self._pid,
                                 reason="failover_exhausted",
                                 violation="failover_exhausted")
                if req.stream is not None:
                    req.stream({"tokens": [], "done": True})

    # -- results / readout ---------------------------------------------

    def result(self, rid: int) -> List[int]:
        return list(self._reqs[rid].generated)

    def request_uid(self, rid: int) -> int:
        return self._reqs[rid].uid

    def worker_of(self, rid: int) -> Optional[str]:
        return self._reqs[rid].worker

    def attach_stream(self, rid: int,
                      put: Callable[[Dict[str, Any]], None]) -> None:
        """Register a per-tick token sink for ``rid`` (the streaming
        front end): called with ``{"tokens": [...], "done": bool}``
        every tick that surfaces tokens, then once with ``done=True``.
        Tokens already committed are replayed into the sink first, so
        attaching after submit never loses the head of the stream."""
        req = self._reqs[rid]
        if req.generated:
            put({"tokens": list(req.generated), "done": False})
        if req.done:
            put({"tokens": [], "done": True})
            return
        req.stream = put

    def cancel(self, rid: int) -> bool:
        req = self._reqs.get(rid)
        if req is None or req.done:
            return False
        req.done = True
        if req.worker is not None and req.worker not in self._dead:
            try:
                self._workers[req.worker].call("cancel",
                                               {"rid": req.wrid})
            except (TransportError, RpcError):
                pass
        self._pending_imports = [(r, rec) for r, rec in
                                 self._pending_imports if r != rid]
        if req.stream is not None:
            req.stream({"tokens": [], "done": True})
        return True

    def drain(self) -> List[Tuple[int, List[int]]]:
        """Step until every submitted request is done (worker loss
        included — failover keeps the plane making progress as long as
        one worker survives)."""
        while any(not r.done for r in self._reqs.values()):
            self.step()
        return [(r.rid, list(r.generated))
                for r in self._reqs.values()]

    def shutdown(self) -> None:
        for name in self.live_workers:
            try:
                self._workers[name].call("shutdown", {})
            except (TransportError, RpcError):
                pass
        for t in self._workers.values():
            t.close()

    # -- busy surface (loadgen.replay polls these) ---------------------

    @property
    def queue_depth(self) -> int:
        return (sum(int(s.get("queue_depth", 0))
                    for s in self._status.values())
                + len(self._pending_imports))

    @property
    def num_active(self) -> int:
        return sum(int(s.get("num_active", 0))
                   for s in self._status.values())

    @property
    def num_pending(self) -> int:
        n = sum(int(s.get("num_pending", 0))
                for s in self._status.values())
        # requests between workers (just failed over / migrating) are
        # invisible to every engine but still owed tokens
        n += sum(1 for r in self._reqs.values()
                 if not r.done and r.worker is None)
        return n

    @property
    def num_preempted(self) -> int:
        return sum(int(s.get("num_preempted", 0))
                   for s in self._status.values())

    @property
    def pending_chunks(self) -> int:
        return sum(int(s.get("pending_chunks", 0))
                   for s in self._status.values())

    @property
    def step_traces(self) -> int:
        return max([int(s.get("step_traces", 0))
                    for s in self._status.values()] or [0])

    # -- federated observability (ISSUE 19) ----------------------------

    def federation(self, full: bool = False) -> "_fed.FederatedRegistry":
        """Pull every live worker's ``metrics_snapshot`` into one
        :class:`FederatedRegistry` (engine-scoped snapshots, so the
        federated totals equal the per-worker sums even when loopback
        workers share a process registry)."""
        fed = _fed.FederatedRegistry()
        for name in self.live_workers:
            try:
                out = self._workers[name].call(
                    "metrics_snapshot", {"full": bool(full)})
            except TransportError:
                self._mark_lost(name, "metrics_snapshot_failed")
                continue
            except RpcError:
                continue
            fed.add_snapshot(name, out["snapshot"])
        return fed

    def federated_metrics_text(self) -> str:
        """The fleet half of the /metrics page: the merged worker
        registries under the ``paddle_tpu_fleet_`` prefix (the serving
        process's own ``paddle_tpu_`` exposition rides alongside)."""
        return self.federation().prometheus_text()

    def fleet_report(self) -> Dict[str, Any]:
        """The /fleet endpoint payload: per-worker health (heartbeat
        age in plane ticks, in-flight slots, utilization, last-step
        cost-model ratio, transport error count) plus pooled figures
        computed sum-over-sum (BASELINE hit-rate cross-check rule)."""
        workers: Dict[str, Any] = {}
        tot_active = tot_slots = 0
        for name in self._workers:
            st = self._status.get(name, {})
            alive = name not in self._dead
            slots = int(st.get("num_slots", 0) or 0)
            active = int(st.get("num_active", 0))
            if alive:
                tot_active += active
                tot_slots += slots
            workers[name] = {
                "alive": alive,
                "reason": self._dead.get(name),
                "heartbeat_age_ticks": (
                    self._ticks - self._last_hb_tick.get(name, 0)
                    if alive else None),
                "in_flight": active,
                "num_slots": slots,
                "utilization": (round(active / slots, 4)
                                if slots else 0.0),
                "last_step_ratio": st.get("last_step_ratio"),
                "queue_depth": int(st.get("queue_depth", 0)),
                "transport_errors": int(
                    getattr(self._workers[name], "errors", 0))}
        return {
            "plane": {"ticks": int(self._ticks), "policy": self.policy,
                      "workers_lost": len(self._dead),
                      "heartbeat_every": self._hb_every},
            "workers": workers,
            "pooled": {"in_flight": tot_active, "num_slots": tot_slots,
                       "utilization": (round(tot_active / tot_slots, 4)
                                       if tot_slots else 0.0)}}

    def slo_report(self, since_uid: int = 0,
                   until_uid: Optional[int] = None,
                   **kw: Any) -> Dict[str, Any]:
        """Federated SLO report: all workers' timelines are already
        joined in the plane log on the plane clock (loopback shares it;
        socket events arrive clock-stitched), so this is the request
        log's report — including ``by_worker`` violation attribution —
        scoped to the plane's requests."""
        return self._rlog.slo_report(since_uid, until_uid, **kw)

    def export_merged_perfetto(self, path: Optional[str] = None,
                               since_uid: int = 0,
                               until_uid: Optional[int] = None
                               ) -> Dict[str, Any]:
        """ONE merged Perfetto timeline for the fleet — see
        :func:`~paddle_tpu.observability.federation.merge_perfetto`."""
        stitches = OrderedDict(
            (n, t.stitch) for n, t in self._workers.items()
            if getattr(t, "stitch", None) is not None)
        return _fed.merge_perfetto(
            stitches, self._rlog.records(since_uid, until_uid),
            path=path)

    def fleet_obs_signature(self, since_uid: int = 0,
                            until_uid: Optional[int] = None) -> str:
        """Byte-stability probe over the fleet observability state
        (merged timeline + wall-free federated metrics + health) — see
        :func:`~paddle_tpu.observability.federation.
        fleet_obs_signature`."""
        return _fed.fleet_obs_signature(
            self.export_merged_perfetto(since_uid=since_uid,
                                        until_uid=until_uid),
            self.federation().merged(), self.fleet_report())

    def metrics(self) -> Dict[str, Any]:
        agg = {
            "workers": {n: dict(self._status.get(n, {}))
                        for n in self._workers},
            "lost_workers": dict(self._dead),
            "requests": len(self._reqs),
            "migrations": int(self._m_migrations.value()),
            "migration_bytes": int(self._m_mig_bytes.value()),
            "failovers": int(self._m_failovers.value()),
            "heartbeats": int(self._m_heartbeats.value()),
            "pending_imports": len(self._pending_imports),
            "policy": self.policy,
        }
        return {"aggregate": agg}
