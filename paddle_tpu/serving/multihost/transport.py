"""Wire layer of the multi-host serving plane (ISSUE 18).

One protocol, two carriers:

  * :class:`SocketTransport` / :class:`RpcServer` — length-prefixed JSON
    frames over TCP (4-byte big-endian length, then a UTF-8 JSON body;
    numpy arrays and raw bytes ride inline as tagged base64 objects, so
    paged KV-block payloads cross the wire without any non-stdlib
    dependency).  Calls carry per-call timeouts; connect failures retry
    with deterministic exponential backoff, and IDEMPOTENT methods
    (ping/status/result/...) additionally retry a broken call once the
    connection re-establishes.

  * :class:`LoopbackTransport` — the SAME interface in-process: every
    call still round-trips through ``encode_message``/``decode_message``
    (both directions), so CI, the fleet simulator, and tier-1 tests
    exercise the full serialization protocol without sockets or
    processes, deterministically.  ``kill()`` simulates worker loss —
    subsequent calls raise :class:`TransportError` exactly like a dead
    TCP peer.

Worker rendezvous is TCP-store style: :class:`StoreServer` is a tiny
key/value service (set / get / wait) served over the same RPC framing;
workers publish ``worker/<name> -> host:port`` and the plane's
:func:`rendezvous` blocks until all expected workers have registered.

Telemetry: every call increments ``rpc.calls`` / ``rpc.errors`` /
``rpc.retries`` and the byte counters, and opens an ``rpc.call``
Perfetto span — label cardinality is bounded by transport name, not
method.

Clock stitching (ISSUE 19): every frame carries timestamps on both
sides — the client stamps ``t0``/``t3`` (send/receive) on ITS clock
into the request's ``ts`` field, the server answers with ``t1``/``t2``
(receive/respond) on the WORKER clock — and each transport feeds the
four into a :class:`~paddle_tpu.observability.federation.
TransportStitch` (``transport.stitch``), whose min-RTT NTP-style
estimator recovers the worker clock's offset from the plane clock.
Clocks are pluggable (``clock=`` returns milliseconds; default is the
request log's relative clock) so loopback planes and simulated fleets
stitch deterministically.
"""

from __future__ import annotations

import base64
import json
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ... import flags as _flags
from ... import observability as _obs
from ...observability.federation import TransportStitch

__all__ = [
    "encode_message", "decode_message", "RpcError", "TransportError",
    "Transport", "LoopbackTransport", "SocketTransport", "RpcServer",
    "StoreServer", "StoreClient", "rendezvous",
]

# calls safe to replay blind after a reconnect (read-only or naturally
# idempotent); everything else fails fast to the caller's failover path
IDEMPOTENT_METHODS = frozenset({
    "ping", "status", "result", "request_uid", "metrics",
    "metrics_snapshot", "prefix_probe", "lint",
    "store.get", "store.set", "store.wait"})

_HDR = struct.Struct(">I")
_MAX_FRAME = 1 << 30


# -- message codec -----------------------------------------------------------

def _enc(obj: Any) -> Any:
    if isinstance(obj, np.ndarray):
        return {"__nd__": {"dtype": obj.dtype.name,
                           "shape": list(obj.shape),
                           "data": base64.b64encode(
                               np.ascontiguousarray(obj).tobytes()
                           ).decode("ascii")}}
    if isinstance(obj, (bytes, bytearray)):
        return {"__bytes__": base64.b64encode(bytes(obj)).decode("ascii")}
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, dict):
        return {str(k): _enc(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_enc(v) for v in obj]
    return obj


def _dec(obj: Any) -> Any:
    if isinstance(obj, dict):
        if "__nd__" in obj and len(obj) == 1:
            nd = obj["__nd__"]
            raw = base64.b64decode(nd["data"])
            return np.frombuffer(raw, dtype=np.dtype(nd["dtype"])).reshape(
                nd["shape"]).copy()
        if "__bytes__" in obj and len(obj) == 1:
            return base64.b64decode(obj["__bytes__"])
        return {k: _dec(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_dec(v) for v in obj]
    return obj


def encode_message(obj: Any) -> bytes:
    """Serialize a payload tree (JSON scalars, lists, str-keyed dicts,
    numpy arrays, bytes) into one wire frame body.  Dict keys are
    coerced to ``str`` — the protocol convention is string keys
    everywhere (worker responses key deltas by ``str(rid)``)."""
    return json.dumps(_enc(obj), separators=(",", ":"),
                      allow_nan=False).encode("utf-8")


def decode_message(body: bytes) -> Any:
    return _dec(json.loads(body.decode("utf-8")))


def write_frame(sock: socket.socket, body: bytes) -> None:
    sock.sendall(_HDR.pack(len(body)) + body)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def read_frame(sock: socket.socket) -> bytes:
    (n,) = _HDR.unpack(_read_exact(sock, _HDR.size))
    if n > _MAX_FRAME:
        raise ConnectionError(f"frame length {n} exceeds cap {_MAX_FRAME}")
    return _read_exact(sock, n)


# -- errors ------------------------------------------------------------------

class RpcError(Exception):
    """The remote handler raised: the call REACHED the worker and failed
    there (``kind`` is the remote exception type — the plane's admission
    failover keys on ``kind == 'ValueError'``, the engine's rejection
    contract).  The worker itself is alive."""

    def __init__(self, kind: str, message: str):
        super().__init__(f"{kind}: {message}")
        self.kind = kind
        self.message = message


class TransportError(Exception):
    """The call did NOT complete: connection refused/reset, timeout, or
    a killed loopback peer.  The caller must treat the worker as lost
    (heartbeat/failover territory) — whether the side effect happened is
    unknowable from here."""


# -- metrics -----------------------------------------------------------------

class _RpcMetrics:
    def __init__(self, name: str):
        reg = _obs.default_registry()
        lbl = {"transport": name}
        self.calls = reg.counter(
            "rpc.calls", "RPC calls issued").labels(**lbl)
        self.errors = reg.counter(
            "rpc.errors",
            "RPC calls that failed (remote fault or transport "
            "loss)").labels(**lbl)
        self.retries = reg.counter(
            "rpc.retries",
            "reconnect/backoff retries across all calls").labels(**lbl)
        self.bytes_sent = reg.counter(
            "rpc.bytes_sent", "request frame bytes").labels(**lbl)
        self.bytes_recv = reg.counter(
            "rpc.bytes_recv", "response frame bytes").labels(**lbl)
        self.call_ms = reg.histogram(
            "rpc.call_ms", "round-trip wall time per call").labels(**lbl)


# -- transports --------------------------------------------------------------

class Transport:
    """The one client surface both carriers implement."""

    name = "?"
    # True when client and worker share one process (and therefore one
    # RequestLog): the plane skips merging shipped worker events then,
    # since the worker already wrote them into the shared log
    shares_process = False
    # clock-stitching state; concrete carriers replace it per instance
    stitch: Optional[TransportStitch] = None

    def call(self, method: str, payload: Optional[Dict[str, Any]] = None,
             timeout: Optional[float] = None) -> Any:
        raise NotImplementedError

    def close(self) -> None:
        pass

    @property
    def alive(self) -> bool:
        return True

    @property
    def errors(self) -> int:
        """Failed calls so far (transport loss + remote faults) — the
        /fleet per-worker transport error count."""
        m = getattr(self, "_m", None)
        return int(m.errors.value()) if m is not None else 0


def _default_clock_ms() -> float:
    """The plane/worker default timestamp source for RPC stitching: the
    process request log's relative clock, so RPC timestamps, request
    events, and merged timelines share one base per process (and one
    seam — swapping ``RequestLog._clock`` re-clocks all three)."""
    return _obs.get_request_log().now_ms()


class LoopbackTransport(Transport):
    """In-process carrier: ``handler(method, payload) -> result`` with
    the full encode/decode round trip on BOTH legs, so whatever the
    socket path would serialize, this path serializes too.  Worker loss
    is scripted — ``kill()`` makes every later call raise
    :class:`TransportError`, which is exactly what a dead TCP peer looks
    like to the plane."""

    shares_process = True

    def __init__(self, handler: Callable[[str, Dict[str, Any]], Any],
                 name: str = "loopback",
                 clock: Optional[Callable[[], float]] = None,
                 server_clock: Optional[Callable[[], float]] = None):
        self._handler = handler
        self.name = name
        self._dead = False
        self._m = _RpcMetrics(name)
        self._tracer = _obs.get_tracer()
        # ``clock``/``server_clock`` return ms on the caller's / the
        # worker's clock; both default to the shared request-log clock
        # (one process, one clock -> offset ~ 0 by construction)
        self._clock = clock or _default_clock_ms
        self._server_clock = server_clock or self._clock
        self.stitch = TransportStitch(name)

    def kill(self) -> None:
        """Simulate worker loss from now on (deterministic)."""
        self._dead = True

    @property
    def alive(self) -> bool:
        return not self._dead

    def call(self, method: str, payload: Optional[Dict[str, Any]] = None,
             timeout: Optional[float] = None) -> Any:
        self._m.calls.inc()
        t_wall = time.perf_counter()
        with self._tracer.span("rpc.call", transport=self.name,
                               method=method):
            if self._dead:
                self._m.errors.inc()
                raise TransportError(f"{self.name}: worker is gone")
            t0 = float(self._clock())
            req = encode_message({"method": method,
                                  "payload": payload or {},
                                  "ts": {"t0": t0}})
            self._m.bytes_sent.inc(len(req))
            frame = decode_message(req)
            t1 = float(self._server_clock())
            try:
                result = self._handler(frame["method"], frame["payload"])
                t2 = float(self._server_clock())
                resp = encode_message({"ok": True, "result": result,
                                       "ts": {"t1": t1, "t2": t2}})
            except Exception as e:                      # noqa: BLE001
                t2 = float(self._server_clock())
                resp = encode_message({"ok": False,
                                       "error": {"kind": type(e).__name__,
                                                 "msg": str(e)},
                                       "ts": {"t1": t1, "t2": t2}})
            self._m.bytes_recv.inc(len(resp))
            out = decode_message(resp)
            t3 = float(self._clock())
            self.stitch.record(method, t0, t1, t2, t3)
        self._m.call_ms.observe((time.perf_counter() - t_wall) * 1e3)
        if not out["ok"]:
            self._m.errors.inc()
            raise RpcError(out["error"]["kind"], out["error"]["msg"])
        return out["result"]


class SocketTransport(Transport):
    """TCP carrier with per-call timeouts, deterministic exponential
    backoff on (re)connect, and blind retry only for IDEMPOTENT
    methods.  One in-flight call at a time per transport (the plane is
    a single-threaded scheduler; the frontend talks to the plane, not
    to workers)."""

    def __init__(self, host: str, port: int, name: Optional[str] = None,
                 timeout: Optional[float] = None,
                 retries: Optional[int] = None,
                 backoff: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.host = host
        self.port = int(port)
        self.name = name or f"{host}:{port}"
        self._clock = clock or _default_clock_ms
        self.stitch = TransportStitch(self.name)
        self._timeout = float(timeout if timeout is not None
                              else _flags.flag("multihost_call_timeout_s"))
        self._retries = int(retries if retries is not None
                            else _flags.flag("multihost_call_retries"))
        self._backoff = float(backoff if backoff is not None
                              else _flags.flag("multihost_retry_backoff_s"))
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._dead = False
        self._m = _RpcMetrics(self.name)
        self._tracer = _obs.get_tracer()

    @property
    def alive(self) -> bool:
        return not self._dead

    def _connect(self, timeout: float) -> socket.socket:
        last: Optional[Exception] = None
        for attempt in range(self._retries + 1):
            if attempt:
                self._m.retries.inc()
                time.sleep(self._backoff * (2 ** (attempt - 1)))
            try:
                s = socket.create_connection((self.host, self.port),
                                             timeout=timeout)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                return s
            except OSError as e:
                last = e
        raise TransportError(
            f"{self.name}: connect failed after "
            f"{self._retries + 1} attempts: {last}")

    def _roundtrip(self, req: bytes, timeout: float) -> bytes:
        if self._sock is None:
            self._sock = self._connect(timeout)
        self._sock.settimeout(timeout)
        write_frame(self._sock, req)
        return read_frame(self._sock)

    def call(self, method: str, payload: Optional[Dict[str, Any]] = None,
             timeout: Optional[float] = None) -> Any:
        if self._dead:
            self._m.errors.inc()
            raise TransportError(f"{self.name}: transport closed")
        tmo = float(timeout if timeout is not None else self._timeout)
        self._m.calls.inc()
        t_wall = time.perf_counter()
        with self._lock, self._tracer.span(
                "rpc.call", transport=self.name, method=method):
            attempts = (self._retries + 1
                        if method in IDEMPOTENT_METHODS else 1)
            last: Optional[Exception] = None
            resp = None
            t0 = t3 = 0.0
            for attempt in range(attempts):
                if attempt:
                    self._m.retries.inc()
                    time.sleep(self._backoff * (2 ** (attempt - 1)))
                try:
                    # t0 per attempt: the stitch sample must bracket the
                    # round trip that actually completed
                    t0 = float(self._clock())
                    req = encode_message({"method": method,
                                          "payload": payload or {},
                                          "ts": {"t0": t0}})
                    self._m.bytes_sent.inc(len(req))
                    resp = self._roundtrip(req, tmo)
                    t3 = float(self._clock())
                    break
                except (OSError, ConnectionError) as e:
                    last = e
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
            if resp is None:
                self._m.errors.inc()
                raise TransportError(f"{self.name}: {method} failed: {last}")
        self._m.bytes_recv.inc(len(resp))
        self._m.call_ms.observe((time.perf_counter() - t_wall) * 1e3)
        out = decode_message(resp)
        ts = out.get("ts") or {}
        if "t1" in ts and "t2" in ts:
            self.stitch.record(method, t0, float(ts["t1"]),
                               float(ts["t2"]), t3)
        if not out["ok"]:
            self._m.errors.inc()
            raise RpcError(out["error"]["kind"], out["error"]["msg"])
        return out["result"]

    def close(self) -> None:
        self._dead = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


# -- server ------------------------------------------------------------------

class RpcServer:
    """Threaded frame server: one accept loop, one thread per
    connection, ``handler(method, payload) -> result`` dispatched per
    frame.  Handler exceptions become structured error responses (the
    connection survives); transport-level breakage just drops that
    connection."""

    def __init__(self, handler: Callable[[str, Dict[str, Any]], Any],
                 host: str = "127.0.0.1", port: int = 0,
                 clock: Optional[Callable[[], float]] = None):
        self._handler = handler
        # server-side stitch clock (ms); workers pass their own clock so
        # t1/t2 share a base with the request-log events they ship
        self._clock = clock or _default_clock_ms
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(port)))
        self._lsock.listen(16)
        self.host, self.port = self._lsock.getsockname()[:2]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._accept = threading.Thread(target=self._accept_loop,
                                        name=f"rpc-accept:{self.port}",
                                        daemon=True)
        self._accept.start()

    def _accept_loop(self) -> None:
        self._lsock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    frame = decode_message(read_frame(conn))
                except (ConnectionError, OSError, ValueError):
                    return
                t1 = float(self._clock())
                try:
                    result = self._handler(frame["method"],
                                           frame.get("payload") or {})
                    resp = {"ok": True, "result": result}
                except Exception as e:                  # noqa: BLE001
                    resp = {"ok": False,
                            "error": {"kind": type(e).__name__,
                                      "msg": str(e)}}
                resp["ts"] = {"t1": t1, "t2": float(self._clock())}
                try:
                    write_frame(conn, encode_message(resp))
                except (ConnectionError, OSError):
                    return

    def stop(self) -> None:
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass

    def __enter__(self) -> "RpcServer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


# -- TCP-store rendezvous ----------------------------------------------------

class StoreServer:
    """TCP-store-style rendezvous: a key/value dict behind the RPC
    framing with a blocking ``wait`` — workers ``set`` their address
    under ``worker/<name>``, the plane ``wait``s for the full roster."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._kv: Dict[str, Any] = {}
        self._cond = threading.Condition()
        self._rpc = RpcServer(self._handle, host=host, port=port)
        self.host, self.port = self._rpc.host, self._rpc.port

    def _handle(self, method: str, payload: Dict[str, Any]) -> Any:
        if method == "store.set":
            with self._cond:
                self._kv[str(payload["key"])] = payload["value"]
                self._cond.notify_all()
            return {"ok": 1}
        if method == "store.get":
            with self._cond:
                return {"value": self._kv.get(str(payload["key"]))}
        if method == "store.wait":
            keys = [str(k) for k in payload["keys"]]
            deadline = time.monotonic() + float(payload.get("timeout", 30.0))
            with self._cond:
                while not all(k in self._kv for k in keys):
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._cond.wait(timeout=left):
                        missing = [k for k in keys if k not in self._kv]
                        raise TimeoutError(
                            f"rendezvous timed out waiting for {missing}")
                return {"values": {k: self._kv[k] for k in keys}}
        raise ValueError(f"unknown store method {method!r}")

    def stop(self) -> None:
        self._rpc.stop()

    def __enter__(self) -> "StoreServer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


class StoreClient:
    """Client half of the rendezvous store (workers + plane)."""

    def __init__(self, host: str, port: int,
                 timeout: Optional[float] = None):
        self._t = SocketTransport(host, port, name=f"store:{host}:{port}",
                                  timeout=timeout)

    def set(self, key: str, value: Any) -> None:
        self._t.call("store.set", {"key": key, "value": value})

    def get(self, key: str) -> Any:
        return self._t.call("store.get", {"key": key})["value"]

    def wait(self, keys: List[str], timeout: float = 30.0) -> Dict[str, Any]:
        return self._t.call("store.wait",
                            {"keys": list(keys), "timeout": timeout},
                            timeout=timeout + 5.0)["values"]

    def close(self) -> None:
        self._t.close()


def rendezvous(store: StoreClient, names: List[str],
               timeout: float = 30.0) -> Dict[str, Tuple[str, int]]:
    """Block until every worker in ``names`` has published its RPC
    address under ``worker/<name>``; returns name -> (host, port)."""
    vals = store.wait([f"worker/{n}" for n in names], timeout=timeout)
    return {n: (vals[f"worker/{n}"]["host"],
                int(vals[f"worker/{n}"]["port"])) for n in names}
