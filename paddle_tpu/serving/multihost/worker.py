"""Engine worker of the multi-host serving plane (ISSUE 18).

:class:`EngineWorker` wraps ONE :class:`~paddle_tpu.serving.engine.
ServingEngine` behind the RPC method table the plane speaks —
submit / step / result / cancel / status / metrics / drain plus the
migration verbs (export_request / import_request) and the placement
probe (prefix_probe).  The SAME handler serves both carriers: a
:class:`~.transport.LoopbackTransport` wraps it in-process, and
``python -m paddle_tpu.serving.multihost --worker ...`` serves it over
a real socket from its own OS process.

Streaming contract: ``step`` returns per-request TOKEN DELTAS — every
token sampled this tick, keyed by ``str(rid)`` — so the front end can
put tokens on the wire per tick instead of at retirement.  The worker
tracks a read cursor per rid; deltas are exactly-once per token.
"""

from __future__ import annotations

from typing import Any, Dict, List, Set

import numpy as np

from ... import observability as _obs
from ...observability.federation import scope_snapshot
from ...observability.metrics import SNAPSHOT_SCHEMA_VERSION
from ..engine import SamplingParams, ServingEngine

__all__ = ["EngineWorker"]


class EngineWorker:
    """The RPC surface over one engine.  Pure dispatcher: all
    scheduling policy lives plane-side, all engine mechanics engine-
    side; this class only translates wire payloads."""

    def __init__(self, engine: ServingEngine, name: str = "w0"):
        self.engine = engine
        self.name = name
        self._cursor: Dict[int, int] = {}       # rid -> tokens reported
        self._live: List[int] = []              # rids not yet finished
        self._rlog = _obs.get_request_log()
        self._shipped: Dict[int, int] = {}      # uid -> events shipped
        self._closed: Set[int] = set()          # uid left us (exported)
        self.stop_requested = False

    def clock_ms(self) -> float:
        """The worker clock the RPC server stamps t1/t2 with — the
        request log's relative clock, so shipped event timestamps and
        stitch samples share one base (the plane's offset estimate
        maps both onto the plane clock at once)."""
        return self._rlog.now_ms()

    # -- dispatch ------------------------------------------------------

    def handle(self, method: str, payload: Dict[str, Any]) -> Any:
        fn = getattr(self, "_rpc_" + method, None)
        if fn is None:
            raise ValueError(f"unknown worker method {method!r}")
        return fn(payload)

    # -- methods -------------------------------------------------------

    def _rpc_ping(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        return {"ok": 1, "name": self.name}

    def _rpc_submit(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        sp = payload.get("sampling") or {}
        sampling = SamplingParams(
            temperature=float(sp.get("temperature", 0.0)),
            top_k=int(sp.get("top_k", 0)),
            top_p=float(sp.get("top_p", 1.0)))
        uid = payload.get("request_uid")
        rid = self.engine.submit(
            np.asarray(payload["prompt"], np.int32),
            max_new_tokens=int(payload.get("max_new_tokens", 32)),
            sampling=sampling,
            request_uid=None if uid is None else int(uid),
            priority=int(payload.get("priority", 0)),
            ttft_slo_ms=payload.get("ttft_slo_ms"),
            tpot_slo_ms=payload.get("tpot_slo_ms"))
        self._cursor[rid] = 0
        self._live.append(rid)
        self._shipped.setdefault(int(self.engine.request_uid(rid)), 0)
        return {"rid": int(rid)}

    def _rpc_step(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        finished = self.engine.step()
        deltas: Dict[str, List[int]] = {}
        for rid in list(self._live):
            toks = self.engine.result(rid)
            cur = self._cursor.get(rid, 0)
            if len(toks) > cur:
                deltas[str(rid)] = [int(t) for t in toks[cur:]]
                self._cursor[rid] = len(toks)
        for rid in finished:
            if rid in self._live:
                self._live.remove(rid)
        return {"finished": [int(r) for r in finished],
                "deltas": deltas,
                "status": self._status(),
                "events": self._collect_events()}

    def _collect_events(self) -> List[Dict[str, Any]]:
        """New request-log events since the last ship, for every uid
        this worker has hosted.  A socket plane merges these into ITS
        log so the lifecycle timeline stays ONE record per uid even
        when the engine lives in another OS process; a loopback plane
        discards them (shared log, already written)."""
        out: List[Dict[str, Any]] = []
        for uid in list(self._shipped):
            tl = self._rlog.timeline(uid)
            cur = self._shipped[uid]
            for ev in tl[cur:]:
                out.append({"uid": int(uid), "name": ev["name"],
                            "t_ms": float(ev["t_ms"]),
                            "attrs": _jsonable(ev["attrs"])})
            self._shipped[uid] = len(tl)
            if uid in self._closed or any(
                    ev["name"] == "retired" for ev in tl):
                self._shipped.pop(uid, None)
                self._closed.discard(uid)
        return out

    def _rpc_result(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        return {"tokens": [int(t)
                           for t in self.engine.result(
                               int(payload["rid"]))]}

    def _rpc_cancel(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        rid = int(payload["rid"])
        ok = self.engine.cancel(rid)
        if rid in self._live:
            self._live.remove(rid)
        return {"ok": bool(ok)}

    def _rpc_request_uid(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        return {"uid": int(self.engine.request_uid(int(payload["rid"])))}

    def _status(self) -> Dict[str, Any]:
        e = self.engine
        perf = getattr(e, "_perf", None)
        ratio = getattr(perf, "last_ratio", None) if perf else None
        return {"queue_depth": int(e.queue_depth),
                "num_active": int(e.num_active),
                "num_pending": int(e.num_pending),
                "num_preempted": int(e.num_preempted),
                "pending_chunks": int(e.pending_chunks),
                "step_traces": int(e.step_traces),
                "num_slots": int(getattr(e, "num_slots", 0) or 0),
                "engine": str(getattr(e, "_eid", "")),
                "last_step_ratio": (None if ratio is None
                                    else round(float(ratio), 6))}

    def _rpc_status(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        return self._status()

    def _rpc_metrics(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        return _jsonable(self.engine.metrics())

    def _rpc_metrics_snapshot(self, payload: Dict[str, Any]
                              ) -> Dict[str, Any]:
        """The PR-4 registry snapshot, scoped to THIS worker's engine
        series by default (federation correctness: on a loopback plane
        every worker shares one process registry, so the unscoped
        snapshot would double-count; ``full=True`` returns it anyway
        for process-separated debugging)."""
        snap = _obs.default_registry().snapshot()
        eid = str(getattr(self.engine, "_eid", ""))
        if not payload.get("full"):
            snap = scope_snapshot(snap, eid)
        return {"schema_version": SNAPSHOT_SCHEMA_VERSION,
                "worker": self.name, "engine": eid,
                "clock_ms": float(self.clock_ms()),
                "snapshot": snap}

    def _rpc_prefix_probe(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        warm = 0
        if self.engine.paged:
            warm = int(self.engine.kv.prefix_probe(
                [int(t) for t in payload["prompt"]]))
        return {"warm_tokens": warm}

    def _rpc_lint(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        return {"findings": [str(f) for f in self.engine.lint_step()]}

    def _rpc_export_request(self, payload: Dict[str, Any]
                            ) -> Dict[str, Any]:
        rid = int(payload["rid"])
        record = self.engine.export_request(
            rid, release=bool(payload.get("release", True)))
        if record is not None:
            if rid in self._live:
                # the request now lives wherever the record lands;
                # tokens already reported stay reported (the record's
                # "generated" carries them for the importer's cursor)
                self._live.remove(rid)
            # ship the trailing "exported" event next step, then stop
            # tracking the uid — it retires on another worker
            self._closed.add(int(record["uid"]))
        return {"record": record}

    def _rpc_import_request(self, payload: Dict[str, Any]
                            ) -> Dict[str, Any]:
        record = payload["record"]
        uid = int(record["uid"])
        # events before this point belong to the exporter (or, on a
        # loopback plane, are already in the shared log): ship only
        # what the import itself logs onward
        base = len(self._rlog.timeline(uid))
        rid = self.engine.import_request(record)
        if rid is not None:
            # start the delta cursor past the tokens the EXPORTER
            # already surfaced — exactly-once across the migration
            self._cursor[rid] = len(record.get("generated", []))
            self._live.append(rid)
            self._shipped.setdefault(uid, base)
        return {"rid": None if rid is None else int(rid)}

    def _rpc_drain(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        done = self.engine.drain()
        for rid, _ in done:
            if rid in self._live:
                self._live.remove(rid)
        return {"finished": [[int(r), [int(t) for t in toks]]
                             for r, toks in done]}

    def _rpc_shutdown(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        self.stop_requested = True
        return {"ok": 1}


def _jsonable(obj: Any) -> Any:
    """Engine metrics carry numpy scalars and tuple keys; flatten to
    wire-safe JSON types (tuple keys -> '/'-joined strings)."""
    if isinstance(obj, dict):
        return {("/".join(str(p) for p in k)
                 if isinstance(k, tuple) else str(k)): _jsonable(v)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return obj
