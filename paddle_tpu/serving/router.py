"""Data-parallel replica router — N ServingEngines behind one submit().

The horizontal half of ROADMAP item 1's mesh-sharded serving: the
tensor-parallel engine step (``ServingEngine(mesh=...)``) makes ONE
model instance span chips; this router scales *throughput* by running N
independent engine replicas — each with its own KV cache / block pool /
scheduler, optionally each mesh-sharded — and placing requests across
them.  Aggregate tok/s is the sum of per-replica committed tokens
(BASELINE.md multi-replica accounting), and the placement policy is
what keeps that sum high:

  * **prefix-affinity** (default, FLAGS_serving_router_policy): paged
    replicas expose a READ-ONLY trie probe
    (:meth:`~paddle_tpu.serving.kv_cache.BlockManager.prefix_probe`);
    the router sends a prompt to the replica holding its longest
    already-cached full-block prefix — a shared system prompt is
    computed once on ONE replica and every later tenant request lands
    on the warm trie instead of recomputing it cold elsewhere.  With no
    full-block match anywhere (cold start, empty trie, contiguous
    engines) placement falls back to **least-loaded** — queue depth +
    pending prefill chunks (the BASELINE.md capacity signal) + busy
    slots;
  * **session affinity** overrides every policy: the first request of a
    ``session`` pins the session to its replica and every later request
    reuses it, so a conversation's decode (and its incremental prefix
    blocks) never migrates — even across chunked-prefill ticks while an
    earlier turn is still streaming in;
  * **failover**: ``submit()`` tries replicas in placement order — a
    replica whose admission rejects the request outright (pool too
    small for the worst case) is skipped and the next candidate takes
    it, counted in ``router.submit_failovers``.  Only when EVERY
    replica rejects does the error propagate.

Scheduling is a round-robin tick loop: ``step()`` ticks every replica
once (an idle replica's tick returns immediately without device work),
``drain()`` loops until all replicas are empty.  There are no router
threads — on TPU each replica's step is an async dispatch, so one host
thread keeps N devices busy; the loop form also keeps tests and traces
deterministic.

Telemetry rides the shared registry with per-replica labels
(``router.requests{replica=..., route=...}``); :meth:`metrics` returns
the per-replica engine snapshots plus the pooled aggregates (summed
tokens, pooled prefix hit rate) the bench rows commit.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import flags as _flags
from .. import observability as _obs
from .engine import SamplingParams, ServingEngine

__all__ = ["ReplicaRouter"]

_ROUTER_IDS = itertools.count()


class ReplicaRouter:
    """N data-parallel ServingEngine replicas behind one ``submit()``.

    ``ReplicaRouter(model, num_replicas=4)`` builds the replicas (the
    model's host-side params are shared; each replica owns its cache
    and scheduler; ``engine_kwargs`` — ``paged``, ``chunked``,
    ``mesh``, ... — are forwarded to every one).  Pass ``engines=[...]``
    instead to route over pre-built, possibly heterogeneous engines.
    """

    def __init__(self, model=None, num_replicas: Optional[int] = None,
                 *, engines: Optional[List[ServingEngine]] = None,
                 policy: Optional[str] = None, **engine_kwargs):
        self.policy = str(policy
                          or _flags.flag("serving_router_policy"))
        if self.policy not in ("prefix", "least_loaded", "round_robin"):
            raise ValueError(
                f"policy must be 'prefix', 'least_loaded' or "
                f"'round_robin', got {self.policy!r}")
        if engines is not None:
            if model is not None or engine_kwargs:
                raise ValueError(
                    "pass either engines=[...] or a model (+kwargs), "
                    "not both")
            self.engines = list(engines)
        else:
            if model is None:
                raise ValueError("a model (or engines=[...]) is required")
            n = int(num_replicas
                    or _flags.flag("serving_dp_replicas"))
            if n < 1:
                raise ValueError(f"num_replicas must be >= 1, got {n}")
            self.engines = [ServingEngine(model, **engine_kwargs)
                            for _ in range(n)]
        if not self.engines:
            raise ValueError("at least one replica is required")
        self._rid = itertools.count()
        # router rid -> (replica index, engine rid); insertion order IS
        # arrival order (drain() returns it)
        self._placed: Dict[int, Tuple[int, int]] = {}
        self._affinity: Dict[object, int] = {}      # session -> replica
        self._rr = 0                                # round-robin cursor
        reg = _obs.default_registry()
        self._router_id = str(next(_ROUTER_IDS))
        self._rlog = _obs.get_request_log()
        self._uids: Dict[int, int] = {}     # router rid -> lifecycle uid
        lbl = {"router": self._router_id}
        self._m_requests = reg.counter(
            "router.requests",
            "requests placed, by replica and route (prefix = warm-trie "
            "match, affinity = session pin, least_loaded / round_robin "
            "= the fallbacks)")
        self._m_failovers = reg.counter(
            "router.submit_failovers",
            "submissions retried on another replica after the chosen "
            "one rejected admission outright").labels(**lbl)
        self._m_prefix_tokens = reg.counter(
            "router.prefix_routed_tokens",
            "prompt tokens the placement probe found already cached on "
            "the chosen replica at submit time").labels(**lbl)

    # -- placement ---------------------------------------------------------

    @property
    def num_replicas(self) -> int:
        return len(self.engines)

    @staticmethod
    def _load(eng: ServingEngine) -> Tuple[int, int]:
        """Replica load for the least-loaded fallback: queued requests
        plus pending prefill chunks (the BASELINE.md capacity signal)
        first, busy slots as the tie-breaker."""
        return (eng.queue_depth + eng.num_pending + eng.pending_chunks,
                eng.num_active)

    def _probe(self, eng: ServingEngine, prompt: np.ndarray) -> int:
        """Cached prefix tokens ``eng`` already holds for ``prompt``
        (0 for contiguous / prefix-cache-off replicas)."""
        if not eng.paged:
            return 0
        return int(eng.kv.prefix_probe(prompt))

    def _placement_order(self, prompt: np.ndarray,
                         session) -> List[Tuple[int, str, int]]:
        """Candidate replicas, best first, as ``(index, route, warm)``
        triples.  Failover walks this list in order."""
        idx = list(range(len(self.engines)))
        if session is not None and session in self._affinity:
            # the session's replica first; the rest by load as failover
            pin = self._affinity[session]
            rest = sorted((i for i in idx if i != pin),
                          key=lambda i: self._load(self.engines[i]))
            return ([(pin, "affinity", self._probe(self.engines[pin],
                                                   prompt))]
                    + [(i, "least_loaded", 0) for i in rest])
        if self.policy == "round_robin":
            order = idx[self._rr:] + idx[:self._rr]
            self._rr = (self._rr + 1) % len(idx)
            return [(i, "round_robin", 0) for i in order]
        loads = {i: self._load(self.engines[i]) for i in idx}
        by_load = sorted(idx, key=lambda i: loads[i])
        if self.policy == "least_loaded":
            return [(i, "least_loaded", 0) for i in by_load]
        # prefix policy: longest warm trie match wins (load breaks
        # ties); replicas with no full-block match rank by load behind
        # every warm one — the empty-trie cold start degenerates to
        # pure least-loaded
        warm = {i: self._probe(self.engines[i], prompt) for i in idx}
        order = sorted(idx, key=lambda i: (-warm[i], loads[i]))
        return [(i, "prefix" if warm[i] else "least_loaded", warm[i])
                for i in order]

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               sampling: Optional[SamplingParams] = None,
               session=None, priority: int = 0) -> int:
        """Place and enqueue a request; returns the ROUTER request id.
        ``session`` (any hashable) pins this and every later request of
        the session to one replica — decode never migrates.
        ``priority`` rides through to the replica's preemptive scheduler
        (higher wins a victim slot under saturation)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        # the lifecycle uid is minted HERE, before placement, and the
        # same uid rides through every replica attempt — on failover the
        # rejecting replica's "rejected" and the accepting replica's
        # "admitted" land on one timeline
        uid = self._rlog.new_uid()
        self._rlog.event(
            uid, "submitted", router=self._router_id,
            prompt_len=int(prompt.size),
            max_new_tokens=int(max_new_tokens),
            ttft_slo_ms=float(_flags.flag("serving_slo_ttft_ms")),
            tpot_slo_ms=float(_flags.flag("serving_slo_tpot_ms")))
        last_err: Optional[Exception] = None
        for i, route, warm in self._placement_order(prompt, session):
            try:
                erid = self.engines[i].submit(
                    prompt, max_new_tokens=max_new_tokens,
                    sampling=sampling, request_uid=uid,
                    priority=priority)
            except ValueError as e:
                # admission rejected the request outright (e.g. the
                # replica's pool cannot cover its worst case) — the
                # failover clause: try the next candidate
                last_err = e
                self._m_failovers.inc()
                continue
            rid = next(self._rid)
            self._placed[rid] = (i, erid)
            self._uids[rid] = uid
            self._rlog.event(uid, "placed", router=self._router_id,
                             replica=str(i), route=route,
                             warm_tokens=int(warm))
            if session is not None:
                self._affinity.setdefault(session, i)
            self._m_requests.labels(router=self._router_id,
                                    replica=str(i), route=route).inc()
            if warm:
                self._m_prefix_tokens.inc(int(warm))
            return rid
        raise last_err if last_err is not None else RuntimeError(
            "no replica accepted the request")

    def request_uid(self, rid: int) -> int:
        """The lifecycle uid behind router request ``rid`` — one key
        into the request log across every replica the request touched."""
        return self._uids[rid]

    def cancel(self, rid: int) -> bool:
        """Cancel router request ``rid`` wherever its replica holds it
        (queued, mid-prefill, decoding, or awaiting resume after a
        preemption).  Delegates to the owning replica's
        :meth:`ServingEngine.cancel`; returns ``False`` once the
        request already finished (its tokens stay retrievable via
        :meth:`result`)."""
        if rid not in self._placed:
            raise KeyError(f"unknown router request id {rid}")
        i, erid = self._placed[rid]
        return self.engines[i].cancel(erid)

    # -- scheduling --------------------------------------------------------

    def step(self) -> List[int]:
        """One round-robin tick over every replica (idle replicas return
        immediately).  Returns router rids finished this tick."""
        finished: List[int] = []
        for i, eng in enumerate(self.engines):
            done = set(eng.step())
            if done:
                finished.extend(
                    rid for rid, (ri, erid) in self._placed.items()
                    if ri == i and erid in done)
        return finished

    def drain(self) -> List[Tuple[int, List[int]]]:
        """Tick until every replica is empty; returns
        ``[(router_rid, tokens)]`` in arrival order."""
        while any(eng.queue_depth or eng.num_active or eng.num_pending
                  or eng.num_preempted for eng in self.engines):
            self.step()
        return [(rid, self.result(rid)) for rid in self._placed]

    def result(self, rid: int) -> List[int]:
        i, erid = self._placed[rid]
        return self.engines[i].result(erid)

    def replica_of(self, rid: int) -> int:
        """Which replica serves router request ``rid`` (affinity probes
        in tests; a session's requests all map to one value)."""
        return self._placed[rid][0]

    # -- telemetry ---------------------------------------------------------

    def metrics(self) -> Dict[str, object]:
        """Per-replica engine snapshots plus the pooled aggregates
        (BASELINE.md multi-replica accounting): aggregate tok/s derives
        from ``tokens_generated`` summed over replicas; the pooled
        prefix hit rate re-divides summed hit tokens by summed admitted
        prompt tokens (NOT the mean of per-replica rates)."""
        per = [eng.metrics() for eng in self.engines]
        agg: Dict[str, object] = {
            "replicas": len(self.engines),
            "policy": self.policy,
            "tokens_generated": sum(m["tokens_generated"] for m in per),
            "requests_submitted": sum(m["requests_submitted"]
                                      for m in per),
            "requests_finished": sum(m["requests_finished"] for m in per),
            "submit_failovers": int(self._m_failovers.value()),
            "prefix_routed_tokens": int(self._m_prefix_tokens.value())}
        if all(eng.paged for eng in self.engines):
            hits = sum(eng.kv.stats["prefix_hit_tokens"]
                       for eng in self.engines)
            total = sum(eng.prefill_tokens_total for eng in self.engines)
            agg["prefix_hit_rate_pooled"] = (round(hits / total, 3)
                                             if total else 0.0)
            agg["prefix_hit_rate_per_replica"] = [
                m["kv_cache"]["prefix_hit_rate"] for m in per]
        return {"aggregate": agg, "per_replica": per}
