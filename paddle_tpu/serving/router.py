"""Data-parallel replica router — N ServingEngines behind one submit().

The horizontal half of ROADMAP item 1's mesh-sharded serving: the
tensor-parallel engine step (``ServingEngine(mesh=...)``) makes ONE
model instance span chips; this router scales *throughput* by running N
independent engine replicas — each with its own KV cache / block pool /
scheduler, optionally each mesh-sharded — and placing requests across
them.  Aggregate tok/s is the sum of per-replica committed tokens
(BASELINE.md multi-replica accounting), and the placement policy is
what keeps that sum high:

  * **prefix-affinity** (default, FLAGS_serving_router_policy): paged
    replicas expose a READ-ONLY trie probe
    (:meth:`~paddle_tpu.serving.kv_cache.BlockManager.prefix_probe`);
    the router sends a prompt to the replica holding its longest
    already-cached full-block prefix — a shared system prompt is
    computed once on ONE replica and every later tenant request lands
    on the warm trie instead of recomputing it cold elsewhere.  With no
    full-block match anywhere (cold start, empty trie, contiguous
    engines) placement falls back to **least-loaded** — queue depth +
    pending prefill chunks (the BASELINE.md capacity signal) + busy
    slots;
  * **session affinity** overrides every policy: the first request of a
    ``session`` pins the session to its replica and every later request
    reuses it, so a conversation's decode (and its incremental prefix
    blocks) never migrates — even across chunked-prefill ticks while an
    earlier turn is still streaming in;
  * **failover**: ``submit()`` tries replicas in placement order — a
    replica whose admission rejects the request outright (pool too
    small for the worst case) is skipped and the next candidate takes
    it, counted in ``router.submit_failovers``.  Only when EVERY
    replica rejects does the error propagate.

**Predictive admission** (control plane, FLAGS_serving_admission
``'predictive'``): before placing, each candidate is priced against its
cost model (:func:`~paddle_tpu.serving.admission.place_verdict` over
:meth:`~paddle_tpu.serving.engine.ServingEngine.admission_probe`) —
"would this placement blow the pooled TPOT/TTFT SLO?".  The first
candidate that fits takes the request; when NONE fits, the request is
parked in a priced :class:`~paddle_tpu.serving.admission.HoldQueue`
instead of being blindly rejected, and ``step()`` retries placement
each tick (priority classes outrank pricing; entries older than
FLAGS_serving_admission_max_defer_ticks are force-placed — the queue
never starves).  The gate degrades to today's reactive policy whenever
FLAGS_perf_model is off or any live replica's model carries a drift
finding.  Decisions land in ``router.admission_decision{verdict=
admit|defer|reject}`` counters and ``router.predicted_tpot_ms``
per-replica gauges on the shared /metrics registry.

**Elasticity** (the autoscaler's surface): :meth:`add_replica` grows
the fleet mid-flight, :meth:`drain_replica` excludes a replica from
new placements (pinned sessions keep landing — sessions never
migrate), and :meth:`retire_replica` removes an EMPTY drained replica
from the tick loop (its index stays allocated so router rids remain
stable; session pins to it are dropped and re-pin cold).

Scheduling is a round-robin tick loop: ``step()`` services the hold
queue, then ticks every live replica once (an idle replica's tick
returns immediately without device work), ``drain()`` loops until all
replicas are empty AND the hold queue is drained.  There are no router
threads — on TPU each replica's step is an async dispatch, so one host
thread keeps N devices busy; the loop form also keeps tests and traces
deterministic.

Telemetry rides the shared registry with per-replica labels
(``router.requests{replica=..., route=...}``); :meth:`metrics` returns
the per-replica engine snapshots plus the pooled aggregates (summed
tokens, pooled prefix hit rate) the bench rows commit.
"""

from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import flags as _flags
from .. import observability as _obs
from .admission import HoldQueue, place_verdict
from .engine import SamplingParams, ServingEngine

__all__ = ["ReplicaRouter"]

_ROUTER_IDS = itertools.count()


class ReplicaRouter:
    """N data-parallel ServingEngine replicas behind one ``submit()``.

    ``ReplicaRouter(model, num_replicas=4)`` builds the replicas (the
    model's host-side params are shared; each replica owns its cache
    and scheduler; ``engine_kwargs`` — ``paged``, ``chunked``,
    ``mesh``, ... — are forwarded to every one).  Pass ``engines=[...]``
    instead to route over pre-built, possibly heterogeneous engines.
    """

    def __init__(self, model=None, num_replicas: Optional[int] = None,
                 *, engines: Optional[List[ServingEngine]] = None,
                 policy: Optional[str] = None, **engine_kwargs):
        self.policy = str(policy
                          or _flags.flag("serving_router_policy"))
        if self.policy not in ("prefix", "least_loaded", "round_robin"):
            raise ValueError(
                f"policy must be 'prefix', 'least_loaded' or "
                f"'round_robin', got {self.policy!r}")
        self._factory: Optional[Callable[[], ServingEngine]] = None
        if engines is not None:
            if model is not None or engine_kwargs:
                raise ValueError(
                    "pass either engines=[...] or a model (+kwargs), "
                    "not both")
            self.engines = list(engines)
        else:
            if model is None:
                raise ValueError("a model (or engines=[...]) is required")
            n = int(num_replicas
                    or _flags.flag("serving_dp_replicas"))
            if n < 1:
                raise ValueError(f"num_replicas must be >= 1, got {n}")
            self._factory = lambda: ServingEngine(model, **engine_kwargs)
            self.engines = [self._factory() for _ in range(n)]
        if not self.engines:
            raise ValueError("at least one replica is required")
        self._rid = itertools.count()
        # router rid -> (replica index, engine rid); _order is arrival
        # order (drain() returns it — held requests keep their arrival
        # slot even though they enter _placed late)
        self._placed: Dict[int, Tuple[int, int]] = {}
        self._order: List[int] = []
        # replica index -> {engine rid -> router rid}: the O(1) reverse
        # map step() resolves finished ids through (the fleet simulator
        # replays 100k+ requests — a linear scan of _placed per tick
        # would be quadratic in trace length)
        self._by_engine: Dict[int, Dict[int, int]] = {
            i: {} for i in range(len(self.engines))}
        self._affinity: Dict[object, int] = {}      # session -> replica
        self._rr = 0                                # round-robin cursor
        # control plane: the priced deferral queue + elastic state
        self._hold = HoldQueue()
        self._draining: Set[int] = set()
        self._retired: Set[int] = set()
        reg = _obs.default_registry()
        self._router_id = str(next(_ROUTER_IDS))
        self._rlog = _obs.get_request_log()
        self._uids: Dict[int, int] = {}     # router rid -> lifecycle uid
        lbl = {"router": self._router_id}
        self._m_requests = reg.counter(
            "router.requests",
            "requests placed, by replica and route (prefix = warm-trie "
            "match, affinity = session pin, least_loaded / round_robin "
            "= the fallbacks)")
        self._m_failovers = reg.counter(
            "router.submit_failovers",
            "submissions retried on another replica after the chosen "
            "one rejected admission outright").labels(**lbl)
        self._m_prefix_tokens = reg.counter(
            "router.prefix_routed_tokens",
            "prompt tokens the placement probe found already cached on "
            "the chosen replica at submit time").labels(**lbl)
        self._f_admission = reg.counter(
            "router.admission_decision",
            "control-plane placement decisions by verdict: admit (a "
            "replica took the request), defer (every candidate priced "
            "over the SLO — parked in the hold queue), reject (a "
            "replica's admission refused outright)")
        self._f_pred_tpot = reg.gauge(
            "router.predicted_tpot_ms",
            "last cost-model predicted post-admission TPOT per replica "
            "(calibrated wall ms), refreshed at every predictive "
            "placement probe")
        self._g_held = reg.gauge(
            "router.held_requests",
            "requests currently parked in the predictive hold "
            "queue").labels(**lbl)

    # -- placement ---------------------------------------------------------

    @property
    def num_replicas(self) -> int:
        return len(self.engines)

    @property
    def live_replicas(self) -> List[int]:
        """Indices still in the tick loop (not retired)."""
        return [i for i in range(len(self.engines))
                if i not in self._retired]

    @property
    def pending_held(self) -> int:
        """Requests parked in the predictive hold queue — loadgen's
        ``busy()`` must count these or replay would stop early."""
        return len(self._hold)

    @staticmethod
    def _load(eng: ServingEngine) -> Tuple[int, int]:
        """Replica load for the least-loaded fallback: queued requests
        plus pending prefill chunks (the BASELINE.md capacity signal)
        first, busy slots as the tie-breaker."""
        return (eng.queue_depth + eng.num_pending + eng.pending_chunks,
                eng.num_active)

    def _probe(self, eng: ServingEngine, prompt: np.ndarray) -> int:
        """Cached prefix tokens ``eng`` already holds for ``prompt``
        (0 for contiguous / prefix-cache-off replicas)."""
        if not eng.paged:
            return 0
        return int(eng.kv.prefix_probe(prompt))

    def _placement_order(self, prompt: np.ndarray,
                         session) -> List[Tuple[int, str, int]]:
        """Candidate replicas, best first, as ``(index, route, warm)``
        triples.  Failover walks this list in order.  Retired replicas
        never appear; draining replicas only appear for their pinned
        sessions (sessions never migrate, but no NEW work lands)."""
        idx = [i for i in range(len(self.engines))
               if i not in self._retired and i not in self._draining]
        if session is not None and session in self._affinity:
            pin = self._affinity[session]
            if pin in self._retired:
                # the pinned replica is gone — drop the pin, the
                # session re-pins cold on whatever takes this request
                del self._affinity[session]
            else:
                # the session's replica first (draining or not); the
                # rest by load as failover
                rest = sorted((i for i in idx if i != pin),
                              key=lambda i: self._load(self.engines[i]))
                return ([(pin, "affinity",
                          self._probe(self.engines[pin], prompt))]
                        + [(i, "least_loaded", 0) for i in rest])
        if not idx:
            # every live replica is draining: placement must still make
            # progress (the autoscaler never drains the whole fleet,
            # but a user can) — fall back to the live set
            idx = self.live_replicas
        if self.policy == "round_robin":
            r = self._rr % len(idx)
            order = idx[r:] + idx[:r]
            self._rr = (self._rr + 1) % len(idx)
            return [(i, "round_robin", 0) for i in order]
        loads = {i: self._load(self.engines[i]) for i in idx}
        by_load = sorted(idx, key=lambda i: loads[i])
        if self.policy == "least_loaded":
            return [(i, "least_loaded", 0) for i in by_load]
        # prefix policy: longest warm trie match wins (load breaks
        # ties); replicas with no full-block match rank by load behind
        # every warm one — the empty-trie cold start degenerates to
        # pure least-loaded
        warm = {i: self._probe(self.engines[i], prompt) for i in idx}
        order = sorted(idx, key=lambda i: (-warm[i], loads[i]))
        return [(i, "prefix" if warm[i] else "least_loaded", warm[i])
                for i in order]

    def _predictive_armed(self) -> bool:
        """The control-plane gate arms only when EVERY live replica's
        model is trustworthy: one drifting replica means predictions
        can no longer rank candidates — fall back conservative."""
        if str(_flags.flag("serving_admission")) != "predictive":
            return False
        live = [self.engines[i] for i in self.live_replicas]
        return bool(live) and all(e.admission_armed() for e in live)

    def _register(self, i: int, route: str, warm: int, session,
                  uid: int, erid: int, rid: Optional[int] = None) -> int:
        """Book one successful placement (fresh or from the hold
        queue): rid maps, reverse map, lifecycle event, telemetry."""
        if rid is None:
            rid = next(self._rid)
            self._order.append(rid)
        self._placed[rid] = (i, erid)
        self._by_engine[i][erid] = rid
        self._uids[rid] = uid
        self._rlog.event(uid, "placed", router=self._router_id,
                         replica=str(i), route=route,
                         warm_tokens=int(warm))
        if session is not None:
            self._affinity.setdefault(session, i)
        self._m_requests.labels(router=self._router_id,
                                replica=str(i), route=route).inc()
        if warm:
            self._m_prefix_tokens.inc(int(warm))
        self._f_admission.labels(router=self._router_id,
                                 verdict="admit").inc()
        return rid

    def _try_place(self, prompt: np.ndarray, max_new_tokens: int,
                   sampling: Optional[SamplingParams], session,
                   priority: int, uid: int, *,
                   slo_ttft: float, slo_tpot: float,
                   rid: Optional[int] = None,
                   gate: bool = True) -> Tuple[Optional[int],
                                               Optional[Exception],
                                               float, int]:
        """One walk of the placement order.  With ``gate`` (and the
        control plane armed) each candidate is priced first and
        over-SLO candidates are skipped.  ``slo_ttft`` / ``slo_tpot``
        are the request's deadlines captured at ROUTER submit — they
        price the placement AND stamp the engine-side request, so a
        hold-queue retry ticks later still carries the class deadlines
        it arrived with.  Returns ``(rid, last_err, hold_price,
        deferrals)`` — rid None means nothing placed."""
        armed = gate and self._predictive_armed()
        last_err: Optional[Exception] = None
        price = 0.0
        deferrals = 0
        for i, route, warm in self._placement_order(prompt, session):
            if armed:
                v = place_verdict(self.engines[i], int(prompt.size),
                                  ttft_slo_ms=slo_ttft,
                                  tpot_slo_ms=slo_tpot)
                self._f_pred_tpot.labels(
                    router=self._router_id,
                    replica=str(i)).set(v.predicted_tpot_ms)
                if v.verdict != "admit":
                    deferrals += 1
                    price = min(price, v.price) if deferrals > 1 \
                        else v.price
                    continue
            try:
                erid = self.engines[i].submit(
                    prompt, max_new_tokens=max_new_tokens,
                    sampling=sampling, request_uid=uid,
                    priority=priority, ttft_slo_ms=slo_ttft,
                    tpot_slo_ms=slo_tpot)
            except ValueError as e:
                # admission rejected the request outright (e.g. the
                # replica's pool cannot cover its worst case) — the
                # failover clause: try the next candidate
                last_err = e
                self._m_failovers.inc()
                self._f_admission.labels(router=self._router_id,
                                         verdict="reject").inc()
                continue
            return (self._register(i, route, warm, session, uid, erid,
                                   rid=rid), None, 0.0, deferrals)
        return (None, last_err, price, deferrals)

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               sampling: Optional[SamplingParams] = None,
               session=None, priority: int = 0) -> int:
        """Place and enqueue a request; returns the ROUTER request id.
        ``session`` (any hashable) pins this and every later request of
        the session to one replica — decode never migrates.
        ``priority`` rides through to the replica's preemptive scheduler
        (higher wins a victim slot under saturation) AND through the
        predictive hold queue (priority classes outrank pricing).

        Under predictive admission a request every candidate prices
        over the SLO is PARKED, not rejected: the returned rid is
        valid immediately, placement happens on a later ``step()``."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        # the lifecycle uid is minted HERE, before placement, and the
        # same uid rides through every replica attempt — on failover the
        # rejecting replica's "rejected" and the accepting replica's
        # "admitted" land on one timeline
        uid = self._rlog.new_uid()
        slo_ttft = float(_flags.flag("serving_slo_ttft_ms"))
        slo_tpot = float(_flags.flag("serving_slo_tpot_ms"))
        self._rlog.event(
            uid, "submitted", router=self._router_id,
            prompt_len=int(prompt.size),
            max_new_tokens=int(max_new_tokens),
            ttft_slo_ms=slo_ttft, tpot_slo_ms=slo_tpot)
        rid, last_err, price, deferrals = self._try_place(
            prompt, max_new_tokens, sampling, session, priority, uid,
            slo_ttft=slo_ttft, slo_tpot=slo_tpot)
        if rid is not None:
            return rid
        if deferrals == 0:
            # every candidate REJECTED (infeasible everywhere) — the
            # legacy contract: propagate, nothing to hold
            raise last_err if last_err is not None else RuntimeError(
                "no replica accepted the request")
        # at least one candidate merely priced over the SLO: park it
        rid = next(self._rid)
        self._order.append(rid)
        self._uids[rid] = uid
        self._hold.push(
            {"rid": rid, "uid": uid, "prompt": prompt,
             "max_new_tokens": int(max_new_tokens), "sampling": sampling,
             "session": session, "priority": int(priority),
             "slo_ttft": slo_ttft, "slo_tpot": slo_tpot},
            priority=priority, price=price)
        self._g_held.set(len(self._hold))
        self._f_admission.labels(router=self._router_id,
                                 verdict="defer").inc()
        self._rlog.event(uid, "held", router=self._router_id,
                         price_ms=round(price, 6),
                         priority=int(priority))
        return rid

    def _service_hold(self) -> None:
        """Retry placement for every held request, best-first (aged →
        priority → price → arrival).  Aged entries bypass the gate —
        the starvation bound force-places at the legacy best candidate.
        Entries that still do not fit are re-priced in place."""
        if not len(self._hold):
            return
        for e in self._hold.ordered():
            p = e.payload
            rid, _, price, deferrals = self._try_place(
                p["prompt"], p["max_new_tokens"], p["sampling"],
                p["session"], p["priority"], p["uid"], rid=p["rid"],
                slo_ttft=p["slo_ttft"], slo_tpot=p["slo_tpot"],
                gate=not self._hold.aged(e))
            if rid is not None:
                self._hold.remove(e)
            elif deferrals:
                e.price = price
            else:
                # zero deferrals and nothing placed: every live replica
                # rejected outright.  Engine-side rejection is STATIC
                # infeasibility (prompt past max_length, pool too small
                # for the worst case) — retrying forever would wedge
                # drain().  Surface the same terminal verdict submit()
                # would have raised, as a lifecycle event
                self._hold.remove(e)
                self._order.remove(p["rid"])
                self._rlog.event(p["uid"], "rejected",
                                 router=self._router_id, stage="held")
                self._f_admission.labels(router=self._router_id,
                                         verdict="reject").inc()
        self._g_held.set(len(self._hold))

    def request_uid(self, rid: int) -> int:
        """The lifecycle uid behind router request ``rid`` — one key
        into the request log across every replica the request touched."""
        return self._uids[rid]

    def cancel(self, rid: int) -> bool:
        """Cancel router request ``rid`` wherever its replica holds it
        (held pre-placement, queued, mid-prefill, decoding, or awaiting
        resume after a preemption).  Delegates to the owning replica's
        :meth:`ServingEngine.cancel`; returns ``False`` once the
        request already finished (its tokens stay retrievable via
        :meth:`result`)."""
        if rid not in self._placed:
            for e in self._hold:
                if e.payload["rid"] == rid:
                    self._hold.remove(e)
                    self._order.remove(rid)
                    self._rlog.event(self._uids[rid], "cancelled",
                                     router=self._router_id,
                                     stage="held")
                    self._g_held.set(len(self._hold))
                    return True
            raise KeyError(f"unknown router request id {rid}")
        i, erid = self._placed[rid]
        return self.engines[i].cancel(erid)

    # -- elasticity (the autoscaler's surface) -----------------------------

    def add_replica(self,
                    engine: Optional[ServingEngine] = None) -> int:
        """Grow the fleet by one replica mid-flight; returns its index.
        Routers built from a model construct the engine themselves;
        routers built over pre-built engines must be handed one."""
        if engine is None:
            if self._factory is None:
                raise ValueError(
                    "router was built over pre-built engines — pass "
                    "engine= to add_replica")
            engine = self._factory()
        i = len(self.engines)
        self.engines.append(engine)
        self._by_engine[i] = {}
        self._rlog.event(self._rlog.new_uid(), "replica_added",
                         router=self._router_id, replica=str(i))
        return i

    def drain_replica(self, i: int) -> None:
        """Exclude replica ``i`` from NEW placements.  Its queue keeps
        draining and pinned sessions keep landing (sessions never
        migrate); once empty it can be retired."""
        if i in self._retired or not 0 <= i < len(self.engines):
            raise ValueError(f"replica {i} is not live")
        self._draining.add(i)

    def undrain_replica(self, i: int) -> None:
        """Return a draining (not yet retired) replica to service."""
        if i in self._retired:
            raise ValueError(f"replica {i} is already retired")
        self._draining.discard(i)

    def replica_empty(self, i: int) -> bool:
        eng = self.engines[i]
        return not (eng.queue_depth or eng.num_active or eng.num_pending
                    or eng.num_preempted)

    def retire_replica(self, i: int) -> None:
        """Remove an EMPTY replica from the tick loop.  Indices stay
        allocated (router rids remain stable); session pins to the
        retired replica are dropped and re-pin cold on their next
        request.  Raises if the replica still holds work — drain
        first, retire only when empty (sessions never migrate)."""
        if i in self._retired:
            return
        if not 0 <= i < len(self.engines):
            raise ValueError(f"replica {i} does not exist")
        if not self.replica_empty(i):
            raise RuntimeError(
                f"replica {i} still holds work — drain_replica() and "
                f"tick until empty before retiring")
        if len(self.live_replicas) <= 1:
            raise RuntimeError("cannot retire the last live replica")
        self._retired.add(i)
        self._draining.discard(i)
        for s in [s for s, ri in self._affinity.items() if ri == i]:
            del self._affinity[s]
        self._rlog.event(self._rlog.new_uid(), "replica_retired",
                         router=self._router_id, replica=str(i))

    # -- scheduling --------------------------------------------------------

    def step(self) -> List[int]:
        """One round-robin tick: service the hold queue, then tick
        every live replica (idle replicas return immediately).  Returns
        router rids finished this tick."""
        self._service_hold()
        finished: List[int] = []
        for i, eng in enumerate(self.engines):
            if i in self._retired:
                continue
            done = eng.step()
            if done:
                emap = self._by_engine[i]
                finished.extend(sorted(
                    emap.pop(erid) for erid in done if erid in emap))
        if len(self._hold):
            self._hold.tick()
        return finished

    def drain(self) -> List[Tuple[int, List[int]]]:
        """Tick until every live replica is empty and the hold queue
        has drained; returns ``[(router_rid, tokens)]`` in arrival
        order."""
        while (len(self._hold)
               or any(not self.replica_empty(i)
                      for i in self.live_replicas)):
            self.step()
        return [(rid, self.result(rid)) for rid in self._order]

    def result(self, rid: int) -> List[int]:
        i, erid = self._placed[rid]
        return self.engines[i].result(erid)

    def replica_of(self, rid: int) -> int:
        """Which replica serves router request ``rid`` (affinity probes
        in tests; a session's requests all map to one value)."""
        return self._placed[rid][0]

    # -- telemetry ---------------------------------------------------------

    def metrics(self) -> Dict[str, object]:
        """Per-replica engine snapshots plus the pooled aggregates
        (BASELINE.md multi-replica accounting): aggregate tok/s derives
        from ``tokens_generated`` summed over replicas; the pooled
        prefix hit rate re-divides summed hit tokens by summed admitted
        prompt tokens (NOT the mean of per-replica rates)."""
        per = [eng.metrics() for eng in self.engines]
        agg: Dict[str, object] = {
            "replicas": len(self.engines),
            "policy": self.policy,
            "tokens_generated": sum(m["tokens_generated"] for m in per),
            "requests_submitted": sum(m["requests_submitted"]
                                      for m in per),
            "requests_finished": sum(m["requests_finished"] for m in per),
            "submit_failovers": int(self._m_failovers.value()),
            "prefix_routed_tokens": int(self._m_prefix_tokens.value())}
        agg["control_plane"] = {
            "admission": str(_flags.flag("serving_admission")),
            "predictive_armed": self._predictive_armed(),
            "held_requests": len(self._hold),
            "draining": sorted(self._draining),
            "retired": sorted(self._retired),
            "live_replicas": len(self.live_replicas),
            "decisions": {
                str(c.labels["verdict"]): int(c.value())
                for c in self._f_admission.children()
                if c.labels.get("router") == self._router_id}}
        if all(eng.paged for eng in self.engines):
            hits = sum(eng.kv.stats["prefix_hit_tokens"]
                       for eng in self.engines)
            total = sum(eng.prefill_tokens_total for eng in self.engines)
            agg["prefix_hit_rate_pooled"] = (round(hits / total, 3)
                                             if total else 0.0)
            agg["prefix_hit_rate_per_replica"] = [
                m["kv_cache"]["prefix_hit_rate"] for m in per]
        return {"aggregate": agg, "per_replica": per}
