"""Short-time Fourier transforms (parity surface: upstream python/paddle/signal.py).

``stft``/``istft`` with paddle's conventions (frame_length/hop_length,
center padding, onesided default, window broadcast). Framing is expressed
as a gather over a precomputed (static) frame-index matrix rather than a
Python loop — under jit the gather plus batched ``rfft`` is two XLA HLOs,
batched over channels on the MXU-adjacent vector units; a per-frame
``lax.scan`` would serialize what is naturally one batched FFT.

Chip note: call these under ``jax.jit`` on the tunnel-attached bench chip —
eager ops on complex intermediates poison that backend's executable path
(tensor/fft.py documents the quirk; CPU and standard TPU runtimes are
unaffected).
"""

from __future__ import annotations

import jax.numpy as jnp

from .tensor import fft as _fft

__all__ = ["stft", "istft"]


def _frame_indices(n_samples: int, n_fft: int, hop: int):
    n_frames = 1 + (n_samples - n_fft) // hop
    if n_frames < 1:
        raise ValueError(
            f"signal length {n_samples} shorter than one n_fft={n_fft} frame")
    return (jnp.arange(n_frames)[:, None] * hop
            + jnp.arange(n_fft)[None, :])          # (n_frames, n_fft)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True):
    """paddle.signal.stft. x: (..., seq_len) real or complex.

    Returns (..., n_fft//2+1 or n_fft, n_frames) complex, matching the
    reference's output layout (freq before frames).
    """
    hop_length = hop_length if hop_length is not None else n_fft // 4
    win_length = win_length if win_length is not None else n_fft
    if window is None:
        window = jnp.ones((win_length,), dtype=jnp.result_type(x, jnp.float32))
    if win_length < n_fft:  # paddle zero-pads the window to n_fft, centered
        lpad = (n_fft - win_length) // 2
        window = jnp.pad(window, (lpad, n_fft - win_length - lpad))

    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)

    idx = _frame_indices(x.shape[-1], n_fft, hop_length)
    frames = x[..., idx] * window                  # (..., n_frames, n_fft)
    if jnp.iscomplexobj(x):
        onesided = False
    spec = (_fft.rfft(frames, axis=-1) if onesided
            else _fft.fft(frames, axis=-1))        # (..., n_frames, n_freq)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    return jnp.swapaxes(spec, -1, -2)              # (..., n_freq, n_frames)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False):
    """paddle.signal.istft — overlap-add inverse with window-envelope
    normalization (the standard NOLA reconstruction)."""
    hop_length = hop_length if hop_length is not None else n_fft // 4
    win_length = win_length if win_length is not None else n_fft
    if window is None:
        window = jnp.ones((win_length,), dtype=jnp.float32)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        window = jnp.pad(window, (lpad, n_fft - win_length - lpad))

    spec = jnp.swapaxes(x, -1, -2)                 # (..., n_frames, n_freq)
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    frames = (_fft.irfft(spec, n=n_fft, axis=-1) if onesided
              else _fft.ifft(spec, n=n_fft, axis=-1))
    if not return_complex:
        frames = frames.real if jnp.iscomplexobj(frames) else frames
    frames = frames * window                       # (..., n_frames, n_fft)

    n_frames = frames.shape[-2]
    out_len = n_fft + hop_length * (n_frames - 1)
    idx = _frame_indices(out_len, n_fft, hop_length)   # (n_frames, n_fft)
    batch_shape = frames.shape[:-2]
    flat = frames.reshape((-1, n_frames, n_fft))
    sig = jnp.zeros((flat.shape[0], out_len), dtype=flat.dtype)
    sig = sig.at[:, idx].add(flat)                 # overlap-add
    env = jnp.zeros((out_len,), dtype=window.dtype).at[idx].add(window ** 2)
    sig = sig / jnp.where(env > 1e-11, env, 1.0)
    sig = sig.reshape(batch_shape + (out_len,))

    if center:
        sig = sig[..., n_fft // 2: out_len - n_fft // 2]
    if length is not None:
        sig = (sig[..., :length] if sig.shape[-1] >= length
               else jnp.pad(sig, [(0, 0)] * (sig.ndim - 1)
                            + [(0, length - sig.shape[-1])]))
    return sig
