"""paddle.sparse parity namespace over jax.experimental.sparse (BCOO/BCSR).

The reference's sparse stack (upstream layout: python/paddle/sparse/ +
paddle/phi/kernels/sparse/) carries SparseCooTensor/SparseCsrTensor with
cuSPARSE-backed kernels. The TPU-native equivalent is jax's batched-COO
(``BCOO``) representation: indices+data arrays with static nse, so sparse
values trace through jit/grad/vmap, and ``bcoo_dot_general`` lowers to
gather+segment-sum HLOs that XLA tiles onto the MXU's neighbouring vector
units. Zero-preserving unary math acts on ``.data`` directly (free);
sparse-sparse elementwise ops ride BCOO's sum-duplicates machinery.

Absent (visible in the registry's work queue): masked_matmul, sparse
softmax/attention, sparse conv3d — these need a captured sparsity-pattern
kernel (cuSPARSE SDDMM equivalents) that we'd build in Pallas when a model
config demands them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from . import nn  # noqa: F401  (re-export submodule)

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "coalesce", "is_same_shape",
    "matmul", "addmm", "mv", "transpose", "reshape",
    "add", "subtract", "multiply", "divide",
    "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
    "sqrt", "square", "log1p", "abs", "expm1", "pow", "cast", "neg",
    "rad2deg", "deg2rad",
]


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient: bool = True):
    """Build a sparse COO tensor. indices: (ndim, nse); values: (nse,)."""
    indices = jnp.asarray(indices).T            # BCOO wants (nse, ndim)
    values = jnp.asarray(values, dtype=dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in jnp.max(indices, axis=0))
    return jsparse.BCOO((values, indices), shape=tuple(shape))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """Build a sparse CSR tensor (2-D). Stored as BCSR."""
    return jsparse.BCSR(
        (jnp.asarray(values, dtype=dtype), jnp.asarray(cols),
         jnp.asarray(crows)), shape=tuple(shape))


def _as_bcoo(x):
    if isinstance(x, jsparse.BCSR):
        return x.to_bcoo()
    return x


def coalesce(x):
    return jsparse.bcoo_sum_duplicates(_as_bcoo(x))


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def matmul(x, y):
    """Sparse @ dense (or dense @ sparse) → dense; sparse @ sparse → sparse."""
    x, y = _as_bcoo(x), _as_bcoo(y)
    return x @ y


def addmm(input, x, y, beta: float = 1.0, alpha: float = 1.0):
    return beta * input + alpha * matmul(x, y)


def mv(x, vec):
    return _as_bcoo(x) @ vec


def transpose(x, perm):
    return jsparse.bcoo_transpose(_as_bcoo(x), permutation=tuple(perm))


def reshape(x, shape):
    return jsparse.bcoo_reshape(_as_bcoo(x), new_sizes=tuple(shape))


# -- elementwise sparse-sparse ----------------------------------------------

def add(x, y):
    return _as_bcoo(x) + _as_bcoo(y)


def subtract(x, y):
    return _as_bcoo(x) + (-1.0) * _as_bcoo(y)


def multiply(x, y):
    x = _as_bcoo(x)
    if isinstance(y, (jsparse.BCOO, jsparse.BCSR)):
        return jsparse.bcoo_multiply_sparse(x, _as_bcoo(y))
    return jsparse.bcoo_multiply_dense(x, jnp.asarray(y))


def divide(x, y):
    x = _as_bcoo(x)
    if isinstance(y, (jsparse.BCOO, jsparse.BCSR)):
        y = jsparse.todense(_as_bcoo(y))
    return jsparse.bcoo_multiply_dense(x, 1.0 / jnp.asarray(y))


# -- zero-preserving unary math: act on .data, keep the pattern -------------

def _unary(fn):
    def op(x):
        x = _as_bcoo(x)
        return jsparse.BCOO((fn(x.data), x.indices), shape=x.shape,
                            indices_sorted=x.indices_sorted,
                            unique_indices=x.unique_indices)
    return op


sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
abs = _unary(jnp.abs)
expm1 = _unary(jnp.expm1)
neg = _unary(jnp.negative)
rad2deg = _unary(jnp.rad2deg)
deg2rad = _unary(jnp.deg2rad)


def pow(x, factor):
    x = _as_bcoo(x)
    return jsparse.BCOO((jnp.power(x.data, factor), x.indices), shape=x.shape)


def cast(x, index_dtype=None, value_dtype=None):
    x = _as_bcoo(x)
    data = x.data.astype(value_dtype) if value_dtype else x.data
    idx = x.indices.astype(index_dtype) if index_dtype else x.indices
    return jsparse.BCOO((data, idx), shape=x.shape)
