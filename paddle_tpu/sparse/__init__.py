"""paddle.sparse parity namespace over jax.experimental.sparse (BCOO/BCSR).

The reference's sparse stack (upstream layout: python/paddle/sparse/ +
paddle/phi/kernels/sparse/) carries SparseCooTensor/SparseCsrTensor with
cuSPARSE-backed kernels. The TPU-native equivalent is jax's batched-COO
(``BCOO``) representation: indices+data arrays with static nse, so sparse
values trace through jit/grad/vmap, and ``bcoo_dot_general`` lowers to
gather+segment-sum HLOs that XLA tiles onto the MXU's neighbouring vector
units. Zero-preserving unary math acts on ``.data`` directly (free);
sparse-sparse elementwise ops ride BCOO's sum-duplicates machinery.

Pattern-captured kernels (round-4 queue shrink): ``masked_matmul`` is the
SDDMM — gather rows/cols by the mask's indices and contract, O(nse·K),
never materialising the dense product; ``nn.softmax`` runs per-row over
stored values via segment max/sum; ``nn.attention`` and
``nn.(subm_)conv3d`` live in :mod:`.nn` (conv3d does its coordinate
matching host-side in NumPy — a parity surface, not a jit-traceable
point-cloud kernel; see its docstring for the boundary).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from . import nn  # noqa: F401  (re-export submodule)

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "coalesce", "is_same_shape",
    "matmul", "addmm", "mv", "transpose", "reshape",
    "add", "subtract", "multiply", "divide",
    "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
    "sqrt", "square", "log1p", "abs", "expm1", "pow", "cast", "neg",
    "rad2deg", "deg2rad",
    "sum", "slice", "mask_as", "masked_matmul",
]


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient: bool = True):
    """Build a sparse COO tensor. indices: (ndim, nse); values: (nse,)."""
    indices = jnp.asarray(indices).T            # BCOO wants (nse, ndim)
    values = jnp.asarray(values, dtype=dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in jnp.max(indices, axis=0))
    return jsparse.BCOO((values, indices), shape=tuple(shape))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """Build a sparse CSR tensor (2-D). Stored as BCSR."""
    return jsparse.BCSR(
        (jnp.asarray(values, dtype=dtype), jnp.asarray(cols),
         jnp.asarray(crows)), shape=tuple(shape))


def _as_bcoo(x):
    if isinstance(x, jsparse.BCSR):
        return x.to_bcoo()
    return x


def coalesce(x):
    return jsparse.bcoo_sum_duplicates(_as_bcoo(x))


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def matmul(x, y):
    """Sparse @ dense (or dense @ sparse) → dense; sparse @ sparse → sparse."""
    x, y = _as_bcoo(x), _as_bcoo(y)
    return x @ y


def addmm(input, x, y, beta: float = 1.0, alpha: float = 1.0):
    return beta * input + alpha * matmul(x, y)


def mv(x, vec):
    return _as_bcoo(x) @ vec


def transpose(x, perm):
    return jsparse.bcoo_transpose(_as_bcoo(x), permutation=tuple(perm))


def reshape(x, shape):
    return jsparse.bcoo_reshape(_as_bcoo(x), new_sizes=tuple(shape))


# -- elementwise sparse-sparse ----------------------------------------------

def add(x, y):
    return _as_bcoo(x) + _as_bcoo(y)


def subtract(x, y):
    return _as_bcoo(x) + (-1.0) * _as_bcoo(y)


def multiply(x, y):
    x = _as_bcoo(x)
    if isinstance(y, (jsparse.BCOO, jsparse.BCSR)):
        return jsparse.bcoo_multiply_sparse(x, _as_bcoo(y))
    return jsparse.bcoo_multiply_dense(x, jnp.asarray(y))


def divide(x, y):
    x = _as_bcoo(x)
    if isinstance(y, (jsparse.BCOO, jsparse.BCSR)):
        y = jsparse.todense(_as_bcoo(y))
    return jsparse.bcoo_multiply_dense(x, 1.0 / jnp.asarray(y))


# -- zero-preserving unary math: act on .data, keep the pattern -------------

def _unary(fn):
    def op(x):
        x = _as_bcoo(x)
        return jsparse.BCOO((fn(x.data), x.indices), shape=x.shape,
                            indices_sorted=x.indices_sorted,
                            unique_indices=x.unique_indices)
    return op


sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
abs = _unary(jnp.abs)
expm1 = _unary(jnp.expm1)
neg = _unary(jnp.negative)
rad2deg = _unary(jnp.rad2deg)
deg2rad = _unary(jnp.deg2rad)


def pow(x, factor):
    x = _as_bcoo(x)
    return jsparse.BCOO((jnp.power(x.data, factor), x.indices), shape=x.shape)


def cast(x, index_dtype=None, value_dtype=None):
    x = _as_bcoo(x)
    data = x.data.astype(value_dtype) if value_dtype else x.data
    idx = x.indices.astype(index_dtype) if index_dtype else x.indices
    return jsparse.BCOO((data, idx), shape=x.shape)


# -- round-4 queue shrink ----------------------------------------------------

def sum(x, axis=None, dtype=None, keepdim: bool = False):
    """paddle.sparse.sum: full reduction → dense scalar; axis reduction →
    sparse result (bcoo_reduce_sum keeps the sparse encoding)."""
    x = _as_bcoo(x)
    if axis is None:
        out = jnp.sum(x.data, dtype=dtype)
        return jnp.reshape(out, (1,) * x.ndim) if keepdim else out
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    axes = tuple(a % x.ndim for a in axes)
    out = jsparse.bcoo_reduce_sum(x, axes=axes)
    if dtype is not None:
        out = jsparse.BCOO((out.data.astype(dtype), out.indices),
                           shape=out.shape)
    if keepdim:
        kept = [1 if a in axes else s for a, s in enumerate(x.shape)]
        out = jsparse.bcoo_reshape(out, new_sizes=tuple(kept))
    return out


def slice(x, axes, starts, ends):
    """paddle.sparse.slice: static-bound slicing via bcoo_dynamic_slice."""
    x = _as_bcoo(x)
    start = [0] * x.ndim
    size = list(x.shape)
    for ax, s, e in zip(axes, starts, ends):
        ax = ax % x.ndim
        s = s % x.shape[ax] if s < 0 else min(s, x.shape[ax])
        e = e % x.shape[ax] if e < 0 else min(e, x.shape[ax])
        start[ax] = s
        size[ax] = e - s
    return jsparse.bcoo_dynamic_slice(x, start, size)


def mask_as(x, mask):
    """Project dense ``x`` onto sparse ``mask``'s pattern (paddle's
    mask_as / sparse_mask): values gathered at the mask's coordinates,
    keeping ``x``'s dtype."""
    mask = _as_bcoo(mask)
    coords = tuple(mask.indices[:, d] for d in range(mask.ndim))
    data = jnp.asarray(x)[coords]
    return jsparse.BCOO((data, mask.indices), shape=mask.shape,
                        indices_sorted=mask.indices_sorted,
                        unique_indices=mask.unique_indices)


def masked_matmul(x, y, mask):
    """SDDMM (parity: paddle.sparse.masked_matmul — cuSPARSE's sampled
    dense-dense matmul): compute (x @ y) only at ``mask``'s nonzero
    coordinates.  TPU shape: gather the needed rows of x and columns of y
    by the mask's indices and contract — O(nse · K) FLOPs and memory,
    never materialising the dense product."""
    mask = _as_bcoo(mask)
    rows = mask.indices[:, 0]
    cols = mask.indices[:, 1]
    data = jnp.einsum("nk,nk->n", jnp.asarray(x)[rows],
                      jnp.asarray(y).T[cols])
    return jsparse.BCOO((data, mask.indices), shape=mask.shape,
                        indices_sorted=mask.indices_sorted,
                        unique_indices=mask.unique_indices)
