"""paddle.sparse.nn.functional parity: zero-preserving activations on BCOO."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

__all__ = ["relu", "relu6", "leaky_relu"]


def _unary(fn):
    def op(x, *args):
        if isinstance(x, jsparse.BCSR):
            x = x.to_bcoo()
        return jsparse.BCOO((fn(x.data, *args), x.indices), shape=x.shape,
                            indices_sorted=x.indices_sorted,
                            unique_indices=x.unique_indices)
    return op


relu = _unary(jax.nn.relu)
relu6 = _unary(jax.nn.relu6)


def leaky_relu(x, negative_slope: float = 0.01):
    return _unary(lambda d: jax.nn.leaky_relu(d, negative_slope))(x)
