"""paddle.sparse.nn.functional parity: zero-preserving activations on BCOO."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

__all__ = ["relu", "relu6", "leaky_relu", "softmax"]


def _unary(fn):
    def op(x, *args):
        if isinstance(x, jsparse.BCSR):
            x = x.to_bcoo()
        return jsparse.BCOO((fn(x.data, *args), x.indices), shape=x.shape,
                            indices_sorted=x.indices_sorted,
                            unique_indices=x.unique_indices)
    return op


relu = _unary(jax.nn.relu)
relu6 = _unary(jax.nn.relu6)


def leaky_relu(x, negative_slope: float = 0.01):
    return _unary(lambda d: jax.nn.leaky_relu(d, negative_slope))(x)


def softmax(x, axis: int = -1):
    """Sparse softmax over the nonzeros of each row (parity:
    paddle.sparse.nn.functional.softmax, 2-D): zeros stay structural
    zeros; normalisation runs per-row over stored values only, via
    segment max/sum keyed by the row index."""
    if isinstance(x, jsparse.BCSR):
        x = x.to_bcoo()
    if x.ndim != 2 or axis not in (-1, 1):
        raise NotImplementedError("sparse softmax: 2-D, last axis only")
    rows = x.indices[:, 0]
    n = x.shape[0]
    import jax.ops  # noqa: F401  (segment ops live under jax.ops)
    row_max = jax.ops.segment_max(x.data, rows, num_segments=n,
                                  indices_are_sorted=x.indices_sorted)
    shifted = jnp.exp(x.data - row_max[rows])
    row_sum = jax.ops.segment_sum(shifted, rows, num_segments=n,
                                  indices_are_sorted=x.indices_sorted)
    return jsparse.BCOO((shifted / row_sum[rows], x.indices),
                        shape=x.shape, indices_sorted=x.indices_sorted,
                        unique_indices=x.unique_indices)
