"""paddle.sparse.nn.functional parity: zero-preserving activations on BCOO."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

__all__ = ["relu", "relu6", "leaky_relu", "softmax",
           "attention", "conv3d", "subm_conv3d"]


def _unary(fn):
    def op(x, *args):
        if isinstance(x, jsparse.BCSR):
            x = x.to_bcoo()
        return jsparse.BCOO((fn(x.data, *args), x.indices), shape=x.shape,
                            indices_sorted=x.indices_sorted,
                            unique_indices=x.unique_indices)
    return op


relu = _unary(jax.nn.relu)
relu6 = _unary(jax.nn.relu6)


def leaky_relu(x, negative_slope: float = 0.01):
    return _unary(lambda d: jax.nn.leaky_relu(d, negative_slope))(x)


def softmax(x, axis: int = -1):
    """Sparse softmax over the nonzeros of each row (parity:
    paddle.sparse.nn.functional.softmax, 2-D): zeros stay structural
    zeros; normalisation runs per-row over stored values only, via
    segment max/sum keyed by the row index."""
    if isinstance(x, jsparse.BCSR):
        x = x.to_bcoo()
    if x.ndim != 2 or axis not in (-1, 1):
        raise NotImplementedError("sparse softmax: 2-D, last axis only")
    rows = x.indices[:, 0]
    n = x.shape[0]
    import jax.ops  # noqa: F401  (segment ops live under jax.ops)
    row_max = jax.ops.segment_max(x.data, rows, num_segments=n,
                                  indices_are_sorted=x.indices_sorted)
    shifted = jnp.exp(x.data - row_max[rows])
    row_sum = jax.ops.segment_sum(shifted, rows, num_segments=n,
                                  indices_are_sorted=x.indices_sorted)
    return jsparse.BCOO((shifted / row_sum[rows], x.indices),
                        shape=x.shape, indices_sorted=x.indices_sorted,
                        unique_indices=x.unique_indices)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None):
    """Sparse-pattern attention (parity: paddle.sparse.nn.functional.
    attention): scores are computed ONLY at ``sparse_mask``'s nonzero
    (query, key) pairs — the SDDMM → sparse-softmax → SpMM pipeline this
    module already owns, composed.  O(nse·D) instead of O(L²·D).

    query/key/value: (B, H, L, D) dense; sparse_mask: a 2-D (L, L) BCOO/
    BCSR pattern shared across batch-heads (the reference's per-(b,h) CSR
    with identical row splits).  Additive masks: key_padding_mask (B, L),
    attn_mask (L, L) — applied at the sampled coordinates.
    Returns (B, H, L, D).
    """
    if isinstance(sparse_mask, jsparse.BCSR):
        sparse_mask = sparse_mask.to_bcoo()
    b, hn, L, d = query.shape
    scale = d ** -0.5
    rows = sparse_mask.indices[:, 0]
    cols = sparse_mask.indices[:, 1]

    def one(q, k, v, bias):
        """All-dense per-(batch, head) chain so the whole thing vmaps
        into ONE fused program: SDDMM as a gathered row-dot, softmax via
        segment max/sum on the row ids, SpMM as a scatter-add."""
        s = jnp.einsum("nk,nk->n", q[rows] * scale, k[cols]) + bias
        row_max = jax.ops.segment_max(s, rows, num_segments=L,
                                      indices_are_sorted=False)
        e = jnp.exp(s - row_max[rows])
        denom = jax.ops.segment_sum(e, rows, num_segments=L,
                                    indices_are_sorted=False)
        p = e / jnp.maximum(denom[rows], 1e-37)
        return jnp.zeros((L, d), q.dtype).at[rows].add(p[:, None] * v[cols])

    am = (jnp.asarray(attn_mask)[rows, cols]              # (nse,)
          if attn_mask is not None else jnp.zeros((), jnp.float32))
    kp = (jnp.asarray(key_padding_mask)[:, cols]          # (B, nse)
          if key_padding_mask is not None
          else jnp.zeros((b, 1), jnp.float32))
    bias = jnp.broadcast_to((am + kp)[:, None], (b, hn, len(rows)))
    return jax.vmap(jax.vmap(one))(query, key, value, bias)


def _sparse_conv3d_impl(x, weight, bias, stride, padding, dilation,
                        groups, subm):
    """Shared gather-scatter sparse 3-D convolution.

    x: BCOO with 4 sparse dims (N, D, H, W) + 1 dense channel dim;
    weight: (kd, kh, kw, Cin/groups, Cout), paddle's NDHWC layout.
    Coordinate matching (the rulebook/hashmap the reference's sparse
    kernels build on GPU) runs host-side — output coordinates are
    data-dependent; the per-tap contraction is a batched (nse, Cin) @
    (Cin, Cout) matmul on device.  Submanifold mode pins the output
    coordinate set to the input's, the sparsity-preserving variant.

    Boundary (op_registry.KNOWN_SCOPE_LIMITS): because the matching is
    host-side NumPy, this op is NOT jit-traceable or differentiable and
    rebuilds the rulebook per call — a parity surface for config-driven
    models, not a production point-cloud kernel.  ``groups > 1`` raises.
    """
    import numpy as np

    if isinstance(x, jsparse.BCSR):
        raise ValueError("sparse conv3d expects a COO tensor (NDHWC)")
    if x.n_dense != 1 or x.indices.shape[1] != 4:
        raise ValueError("x must have 4 sparse dims (N,D,H,W) + dense C; "
                         "build via Tensor.to_sparse_coo(sparse_dim=4)")
    if groups != 1:
        raise NotImplementedError("sparse conv3d: groups > 1")
    kd, kh, kw, cin, cout = weight.shape
    st = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    pd = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    dl = (dilation,) * 3 if isinstance(dilation, int) else tuple(dilation)
    n, D, H, W = x.shape[:4]
    coords = np.asarray(x.indices)                      # (nse, 4)
    vals = x.data                                       # (nse, Cin)

    if subm:
        if st != (1, 1, 1):
            raise ValueError("subm_conv3d requires stride 1")
        out_dims = (D, H, W)
    else:
        out_dims = tuple(
            (s + 2 * pd[i] - dl[i] * (k - 1) - 1) // st[i] + 1
            for i, (s, k) in enumerate(zip((D, H, W), (kd, kh, kw))))
    out_shape = (n,) + out_dims + (cout,)

    # per-tap geometry, computed once: (src row ids, output coords)
    taps = []
    for ti in range(kd):
        for tj in range(kh):
            for tk in range(kw):
                oc = coords[:, 1:] + np.asarray(pd) - \
                    np.asarray([ti * dl[0], tj * dl[1], tk * dl[2]])
                ok = (oc % np.asarray(st) == 0).all(1)
                oc = oc // np.asarray(st)
                ok &= (oc >= 0).all(1) & (oc < np.asarray(out_dims)).all(1)
                src = np.nonzero(ok)[0]
                taps.append(((ti, tj, tk), src,
                             np.concatenate([coords[src, :1], oc[src]],
                                            axis=1)))

    if subm:
        out_coords = coords
    else:
        all_oc = [oc for _, _, oc in taps if len(oc)]
        out_coords = (np.unique(np.concatenate(all_oc, axis=0), axis=0)
                      if all_oc else np.zeros((0, 4), coords.dtype))

    key = np.ravel_multi_index(out_coords.T, (n,) + out_dims)
    lookup = {k: i for i, k in enumerate(key.tolist())}
    m = len(out_coords)
    out_vals = jnp.zeros((m, cout), vals.dtype)
    for (ti, tj, tk), src, oc in taps:
        if src.size == 0:
            continue
        tgt_key = np.ravel_multi_index(oc.T, (n,) + out_dims)
        tgt = np.asarray([lookup.get(k, -1) for k in tgt_key.tolist()])
        hit = tgt >= 0                              # subm: drop off-pattern
        if not hit.any():
            continue
        contrib = vals[jnp.asarray(src[hit])] @ weight[ti, tj, tk]
        out_vals = out_vals.at[jnp.asarray(tgt[hit])].add(contrib)
    if bias is not None:
        out_vals = out_vals + bias
    return jsparse.BCOO((out_vals, jnp.asarray(out_coords)),
                        shape=out_shape)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups: int = 1, data_format: str = "NDHWC"):
    """Sparse 3-D convolution (parity: paddle.sparse.nn.functional.conv3d)."""
    return _sparse_conv3d_impl(x, weight, bias, stride, padding, dilation,
                               groups, subm=False)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups: int = 1, data_format: str = "NDHWC"):
    """Submanifold sparse conv (parity: subm_conv3d): output pattern ==
    input pattern, the sparsity-preserving 3-D conv of MinkowskiNet/
    SECOND-style point-cloud backbones."""
    return _sparse_conv3d_impl(x, weight, bias, stride, padding, dilation,
                               groups, subm=True)
