"""paddle_tpu.static — thin parity facade over the jit/tracing stack.

Parity surface: upstream python/paddle/static/ (~60k LoC: ``Program``,
``Executor``, ``program_guard``, ``static.data``, ``enable_static``) plus
the C++ ProgramDesc machinery it drives.  SURVEY §2.2 marks this layer
design-collapsed: under jax, "static graph mode" is not a mode — EVERY
jitted function is traced once into a static program (jaxpr → StableHLO)
and cached.  This module exists so reference users find the names, with
each name mapped onto the real jax equivalent rather than re-implementing
graph capture by Python side effects:

  * a :class:`Program` wraps a Python function + input specs; "building"
    the program is tracing it (``Program.trace``), and ``main_program``
    shows the jaxpr the way the reference prints a ProgramDesc;
  * graph construction by side effect (``with program_guard(): x =
    static.data(...); y = ops(x)``) is the one idiom that cannot map onto
    functional tracing — :func:`program_guard` therefore collects
    ``static.data`` declarations and the program body is supplied as a
    function (``Program.set_body`` or the ``@prog.body`` decorator), which
    is the same dataflow with the capture made explicit;
  * :class:`Executor` runs a Program with a feed dict / fetch list like
    the reference's ``exe.run(prog, feed=..., fetch_list=...)``; the
    "place" argument is accepted and ignored (device placement belongs to
    jax.sharding, not the executor);
  * :func:`enable_static` / :func:`disable_static` keep the mode flag for
    API compatibility; computation is identical either way.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..jit import InputSpec
from ..utils.logging import VLOG

__all__ = ["Program", "Executor", "program_guard", "data",
           "default_main_program", "default_startup_program",
           "enable_static", "disable_static", "in_static_mode",
           "InputSpec", "CPUPlace", "TPUPlace"]

_static_mode = False
_current_program: Optional["Program"] = None


def enable_static() -> None:
    """Parity no-op with a flag: jax programs are already traced-static
    under jit; there is no eager/graph dichotomy to switch."""
    global _static_mode
    _static_mode = True
    VLOG(1, "enable_static(): parity flag only — jit tracing is always "
            "the 'static graph' path on this backend")


def disable_static() -> None:
    global _static_mode
    _static_mode = False


def in_static_mode() -> bool:
    return _static_mode


class CPUPlace:
    """Parity placeholder; devices are owned by jax."""


class TPUPlace(CPUPlace):
    pass


class Program:
    """A traceable computation: body function + declared inputs.

    The reference's Program is a mutable op list built by side effects;
    here the body is a function and the "op list" is the jaxpr jax traces
    from it — one artifact, no builder state to corrupt.
    """

    def __init__(self):
        self._specs: Dict[str, InputSpec] = {}
        self._body: Optional[Callable] = None
        self._jitted = None

    # -- construction --------------------------------------------------------

    def add_input(self, name: str, spec: InputSpec) -> None:
        self._specs[name] = spec

    def set_body(self, fn: Callable) -> Callable:
        """``fn(**inputs)`` computes the program outputs (any pytree)."""
        self._body = fn
        self._jitted = None
        return fn

    body = set_body  # decorator alias: @prog.body

    # -- views ---------------------------------------------------------------

    @property
    def input_names(self) -> List[str]:
        return list(self._specs)

    def _avals(self) -> Dict[str, jax.ShapeDtypeStruct]:
        return {
            name: jax.ShapeDtypeStruct(
                tuple(1 if d is None else d for d in s.shape), s.dtype)
            for name, s in self._specs.items()}

    def trace(self):
        """The traced program (parity: ProgramDesc; here a ClosedJaxpr)."""
        if self._body is None:
            raise RuntimeError("Program has no body: call set_body(fn) or "
                               "use the @prog.body decorator")
        return jax.make_jaxpr(lambda kw: self._body(**kw))(self._avals())

    @property
    def main_program(self) -> str:
        return str(self.trace())

    def __str__(self) -> str:
        return self.main_program


def default_main_program() -> Program:
    global _current_program
    if _current_program is None:
        _current_program = Program()
    return _current_program


def default_startup_program() -> Program:
    """Parity shim: jax has no separate init program — parameter init is
    ordinary traced computation — so this returns an empty Program."""
    return Program()


class program_guard:
    """``with program_guard(prog):`` makes ``prog`` the target of
    :func:`data` declarations inside the block (parity signature keeps the
    unused startup_program argument)."""

    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self.main = main_program
        del startup_program  # no init program on this backend (see above)

    def __enter__(self):
        global _current_program
        self._prev = _current_program
        _current_program = self.main
        return self.main

    def __exit__(self, *exc):
        global _current_program
        _current_program = self._prev
        return False


def data(name: str, shape: Sequence[Optional[int]], dtype="float32"):
    """Declare a program input (parity: paddle.static.data).

    Registers an InputSpec on the current program and returns it.  The
    returned spec is a declaration, not a tensor — ops consume the real
    arrays the Executor feeds, inside the program body function.
    """
    spec = InputSpec(shape, dtype, name=name)
    default_main_program().add_input(name, spec)
    return spec


class Executor:
    """Run Programs with feed/fetch (parity: paddle.static.Executor)."""

    def __init__(self, place: Any = None):
        del place  # jax owns devices; kept for signature parity

    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            return_numpy: bool = True):
        """Execute ``program`` (default: the current/default one) on a feed
        dict; returns the body's outputs as a list (parity with the
        reference's fetched-var list).  ``fetch_list`` selects by index or
        dict key when the body returns a dict/tuple; None fetches all."""
        import numpy as np

        prog = program or default_main_program()
        if prog._body is None:
            raise RuntimeError("Program has no body to run")
        feed = {k: jnp.asarray(v) for k, v in (feed or {}).items()}
        missing = set(prog.input_names) - set(feed)
        if missing:
            raise ValueError(f"feed missing program inputs: {sorted(missing)}")
        if prog._jitted is None:
            prog._jitted = jax.jit(lambda kw: prog._body(**kw))
        out = prog._jitted(feed)
        if isinstance(out, dict):
            keys = fetch_list if fetch_list is not None else list(out)
            vals = [out[k] for k in keys]
        elif isinstance(out, (tuple, list)):
            vals = list(out)
            if fetch_list is not None:
                vals = [vals[i] for i in fetch_list]
        else:
            vals = [out]
        return [np.asarray(v) for v in vals] if return_numpy else vals
