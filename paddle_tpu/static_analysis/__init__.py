"""paddle_tpu.static_analysis — jaxpr graph lint for the serving hot path.

PAPER.md's sanitizer row ("XLA's checker + a shard_map collective-order
lint of our own") shipped its first rule as the collective-order lint in
``distributed/lint.py``; this package generalizes that one-off into a
static-analysis LAYER: one shared jaxpr walker (:mod:`.core` — the
collective lint is its first client) plus pluggable rules
(:mod:`.rules`) producing structured :class:`Finding`\\ s, each a class
of silent perf/memory bug that ONE abstract trace catches before any
device run:

  * **donation** (error) — jitted outputs whose aval matches a
    non-donated input: the serving step threads the full KV cache, so a
    missed ``donate_argnums`` double-buffers the dominant HBM consumer;
  * **dtype-promotion** (warning) — f32/f64 widenings of large
    low-precision operands (allowlist for softmax/norm accumulators);
  * **constant-capture** (error) — big arrays baked into the jaxpr as
    consts (weights closed over ⇒ HBM bloat + retrace on update);
  * **host-sync** (error) — ``pure_callback``/``io_callback``/
    ``debug_callback``/infeed/outfeed inside a step (would serialize the
    tick loop; observability hooks are allowlisted);
  * **retrace-hazard** (warning) — weak-typed scalar leaks and
    non-canonical dtypes in the call signature, the before-the-fact
    complement of the retrace watchdog's budget.

The MESH pre-flight layer (ISSUE 8, :mod:`.mesh_rules`) extends the
same one-trace framework to mesh-partitioned programs: a
sharding-propagation walker annotates operands with per-axis shardings
under an ABSTRACT mesh (``"mp2dp2"`` works on a laptop), three more
rules check the SPMD story — **replication-blowup** (error: a big
operand fully replicated along an axis it could shard),
**resharding-hazard** (warning: conflicting
``with_sharding_constraint``), **collective-deadlock** (error: the
collective-order lint folded into the rules framework;
``distributed/lint.py`` is now a shim over the shared walker) — and
two cost models report predicted per-axis collective bytes per step
(:func:`comm_report`) and donation-aware per-device peak HBM
(:func:`estimate_peak_hbm`), cross-checked against
``ServingEngine.cache_hbm_bytes`` by ``mesh_preflight``.

API mirrors the collective lint: :func:`analyze` returns findings,
:func:`check` raises :class:`GraphLintError` on any; both take
``mesh=`` / ``in_shardings=`` for the pre-flight path, and
:func:`preflight` returns findings + comm + HBM from one trace.
``FLAGS_graph_lint`` (off/warn/raise) arms the serving engines'
self-lint — every ``ServingEngine`` lints its own once-jitted step at
the first tick — and ``python -m paddle_tpu.static_analysis`` lints a
tiny-config engine step in every cache layout and prints the report
(``--mesh mp2dp2`` for the SPMD pre-flight).

A lint pass is ONE ``jax.make_jaxpr`` trace: abstract, no compile, no
device dispatch.
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Sequence

from .. import flags as _flags
from . import core, mesh_rules as _mesh_rules, rules
from . import kernel_registry, kernel_rules
from .core import (Finding, GraphLintError, GraphLintWarning,
                   LintContext, MeshInfo, MeshLintContext, trace_for_lint,
                   trace_for_mesh_lint)
from .kernel_registry import (KernelSpec, KernelSpecError,
                              decode_attention_spec, flash_attention_spec,
                              int8_matmul_spec, kv_streamed_bytes,
                              rms_norm_spec, registered_kernel_specs,
                              streamed_bytes, vmem_footprint)
from .kernel_rules import (KernelRule, KernelVmemRule, KernelBoundsRule,
                           KernelAlignRule, KernelScaleGranuleRule,
                           KernelStreamRule, analyze_kernels,
                           default_kernel_rules,
                           dispatch_agreement_findings, kernel_report)
from .mesh_rules import (CollectiveDeadlockRule, ReplicationBlowupRule,
                         ReshardingHazardRule, comm_report,
                         default_mesh_rules, estimate_peak_hbm)
from .rules import (ConstantCaptureRule, DonationRule, DtypePromotionRule,
                    HostSyncRule, RetraceHazardRule, Rule, default_rules)

__all__ = [
    "Finding", "GraphLintError", "GraphLintWarning", "LintContext",
    "MeshInfo", "MeshLintContext",
    "Rule", "DonationRule", "DtypePromotionRule", "ConstantCaptureRule",
    "HostSyncRule", "RetraceHazardRule", "default_rules",
    "ReplicationBlowupRule", "ReshardingHazardRule",
    "CollectiveDeadlockRule", "default_mesh_rules", "comm_report",
    "estimate_peak_hbm", "preflight",
    "analyze", "check", "enforce", "report", "trace_for_lint",
    "trace_for_mesh_lint",
    # kernel pre-flight (ISSUE 14)
    "KernelSpec", "KernelSpecError", "decode_attention_spec",
    "flash_attention_spec", "int8_matmul_spec", "rms_norm_spec",
    "registered_kernel_specs", "vmem_footprint", "streamed_bytes",
    "kv_streamed_bytes",
    "KernelRule", "KernelVmemRule", "KernelBoundsRule",
    "KernelAlignRule", "KernelScaleGranuleRule", "KernelStreamRule",
    "default_kernel_rules", "analyze_kernels", "kernel_report",
    "dispatch_agreement_findings",
]

# findings sort: errors first, then a total deterministic order so two
# runs of the same program produce byte-identical reports (the --json
# CLI contract CI diffs ride on)
_SEVERITY_ORDER = {"error": 0, "warning": 1}


def _sort_findings(findings: List[Finding]) -> List[Finding]:
    findings.sort(key=lambda f: (
        _SEVERITY_ORDER.get(f.severity, 2), f.rule, f.path,
        -1 if f.bytes is None else -int(f.bytes), f.message))
    return findings


def _unwrap(fn, donate_argnums, donate_argnames):
    """Resolve a ``track_retraces`` wrapper to its pre-jit python body
    and the donation marks of the real jit call site."""
    raw = getattr(fn, "python_fn", None)
    if raw is not None:                          # TrackedFunction
        jk = dict(getattr(fn, "jit_kwargs", None) or {})
        if donate_argnums is None:
            donate_argnums = jk.get("donate_argnums", ())
        if donate_argnames is None:
            donate_argnames = jk.get("donate_argnames", ())
        fn = raw
    return fn, (donate_argnums or ()), (donate_argnames or ())


def _trace(fn, args, kwargs, donate_argnums, donate_argnames,
           mesh, in_shardings):
    fn, dnums, dnames = _unwrap(fn, donate_argnums, donate_argnames)
    if mesh is None:
        return trace_for_lint(fn, *args, donate_argnums=dnums,
                              donate_argnames=dnames, **kwargs)
    return trace_for_mesh_lint(fn, *args, mesh=mesh,
                               in_shardings=in_shardings,
                               donate_argnums=dnums,
                               donate_argnames=dnames, **kwargs)


def analyze(fn, *args, donate_argnums=None, donate_argnames=None,
            rules: Optional[Sequence[Rule]] = None,
            mesh=None, in_shardings=None, kernels=None,
            **kwargs) -> List[Finding]:
    """Trace ``fn`` abstractly and run the graph-lint rules; returns
    findings (errors first, deterministically ordered) without raising.

    ``fn`` must be a PYTHON function (pre-jit).  A ``track_retraces``
    wrapper (observability/watchdog.py) is unwrapped automatically: its
    stored ``python_fn`` is traced — never the counted body, so a lint
    pass costs no watchdog budget — and its ``jit_kwargs`` supply
    ``donate_argnums``/``donate_argnames`` unless given explicitly, so
    ``analyze(engine._step_fn, *args)`` sees exactly what the real call
    site donates.

    ``mesh=`` selects the MESH pre-flight path (ISSUE 8): the trace is
    annotated with per-axis shardings (``in_shardings`` — per-arg specs
    — or the args' committed NamedShardings; undeclared = replicated),
    propagated through the jaxpr, and the mesh rule set
    (replication-blowup / resharding-hazard / collective-deadlock)
    runs alongside the base rules.  ``mesh`` may be a jax
    ``Mesh``/``AbstractMesh``, a ``{axis: size}`` dict, or a string
    like ``"mp2dp2"`` — no devices are needed.

    ``kernels=`` (ISSUE 14) adds the KERNEL pre-flight to the same
    pass: a sequence of :class:`KernelSpec`\\ s (usually the specs the
    traced program's dispatch would select —
    ``ServingEngine._kernel_specs``) run through the kernel rule set
    (VMEM footprint / index-map bounds / alignment / scale-granule /
    streamed-bytes); their findings merge into the same deterministic
    order."""
    ctx = _trace(fn, args, kwargs, donate_argnums, donate_argnames,
                 mesh, in_shardings)
    if rules is None:
        rules = default_rules() + (default_mesh_rules()
                                   if mesh is not None else ())
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.run(ctx))
    if kernels:
        findings.extend(kernel_rules.analyze_kernels(kernels))
    return _sort_findings(findings)


def report(findings: Sequence[Finding], context: str = "") -> str:
    """Human-readable multi-line report of a finding list."""
    head = (f"graph lint: {len(findings)} finding(s)"
            + (f" in {context}" if context else ""))
    return "\n".join([head] + [f"  {f}" for f in findings])


def check(fn, *args, **kwargs) -> List[Finding]:
    """Lint ``fn``; raise :class:`GraphLintError` on ANY finding, else
    return the (empty) finding list — the collective lint's
    ``check_collective_order`` contract."""
    findings = analyze(fn, *args, **kwargs)
    if findings:
        raise GraphLintError(report(findings))
    return findings


def preflight(fn, *args, mesh, in_shardings=None,
              donate_argnums=None, donate_argnames=None,
              rules: Optional[Sequence[Rule]] = None,
              kernels=None,
              **kwargs) -> dict:
    """Full mesh pre-flight of one traced program: findings (base +
    mesh rules), the per-axis collective-cost report, and the
    per-device HBM-liveness estimate — all from ONE abstract trace.
    This is the report ``ServingEngine.mesh_preflight`` wraps and the
    ``--mesh`` CLI prints; see BASELINE.md "Mesh pre-flight
    conventions" for the accounting definitions.

    ``kernels=``: optional :class:`KernelSpec` sequence to pre-flight
    alongside; their findings merge into ``"findings"`` and the
    per-spec reports ride under ``"kernels"``."""
    ctx = _trace(fn, args, kwargs, donate_argnums, donate_argnames,
                 mesh, in_shardings)
    if rules is None:
        rules = default_rules() + default_mesh_rules()
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.run(ctx))
    out = {"mesh": ctx.mesh.as_dict(),
           "fn": ctx.fn_name,
           "findings": findings,
           "comm": comm_report(ctx),
           "hbm": estimate_peak_hbm(ctx)}
    if kernels:
        findings.extend(kernel_rules.analyze_kernels(kernels))
        out["kernels"] = [kernel_rules.kernel_report(s) for s in kernels]
    _sort_findings(findings)
    return out


def enforce(findings: Sequence[Finding],
            context: str = "") -> Sequence[Finding]:
    """Apply ``FLAGS_graph_lint`` to a finding list: ``raise`` →
    :class:`GraphLintError`, ``warn`` → one :class:`GraphLintWarning`,
    ``off`` → pass through.  Serving engines call this on their
    first-tick self-lint."""
    if not findings:
        return findings
    action = str(_flags.flag("graph_lint"))
    if action == "off":
        return findings
    msg = report(findings, context)
    if action == "raise":
        raise GraphLintError(msg)
    warnings.warn(msg, GraphLintWarning, stacklevel=2)
    return findings
