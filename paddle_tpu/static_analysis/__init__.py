"""paddle_tpu.static_analysis — jaxpr graph lint for the serving hot path.

PAPER.md's sanitizer row ("XLA's checker + a shard_map collective-order
lint of our own") shipped its first rule as the collective-order lint in
``distributed/lint.py``; this package generalizes that one-off into a
static-analysis LAYER: one shared jaxpr walker (:mod:`.core` — the
collective lint is its first client) plus pluggable rules
(:mod:`.rules`) producing structured :class:`Finding`\\ s, each a class
of silent perf/memory bug that ONE abstract trace catches before any
device run:

  * **donation** (error) — jitted outputs whose aval matches a
    non-donated input: the serving step threads the full KV cache, so a
    missed ``donate_argnums`` double-buffers the dominant HBM consumer;
  * **dtype-promotion** (warning) — f32/f64 widenings of large
    low-precision operands (allowlist for softmax/norm accumulators);
  * **constant-capture** (error) — big arrays baked into the jaxpr as
    consts (weights closed over ⇒ HBM bloat + retrace on update);
  * **host-sync** (error) — ``pure_callback``/``io_callback``/
    ``debug_callback``/infeed/outfeed inside a step (would serialize the
    tick loop; observability hooks are allowlisted);
  * **retrace-hazard** (warning) — weak-typed scalar leaks and
    non-canonical dtypes in the call signature, the before-the-fact
    complement of the retrace watchdog's budget.

API mirrors the collective lint: :func:`analyze` returns findings,
:func:`check` raises :class:`GraphLintError` on any.  ``FLAGS_graph_lint``
(off/warn/raise) arms the serving engines' self-lint — every
``ServingEngine`` lints its own once-jitted step at the first tick —
and ``python -m paddle_tpu.static_analysis`` lints a tiny-config engine
step in every cache layout and prints the report.

A lint pass is ONE ``jax.make_jaxpr`` trace: abstract, no compile, no
device dispatch.
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Sequence

from .. import flags as _flags
from . import core, rules
from .core import (Finding, GraphLintError, GraphLintWarning,
                   LintContext, trace_for_lint)
from .rules import (ConstantCaptureRule, DonationRule, DtypePromotionRule,
                    HostSyncRule, RetraceHazardRule, Rule, default_rules)

__all__ = [
    "Finding", "GraphLintError", "GraphLintWarning", "LintContext",
    "Rule", "DonationRule", "DtypePromotionRule", "ConstantCaptureRule",
    "HostSyncRule", "RetraceHazardRule", "default_rules",
    "analyze", "check", "enforce", "report", "trace_for_lint",
]


def analyze(fn, *args, donate_argnums=None, donate_argnames=None,
            rules: Optional[Sequence[Rule]] = None,
            **kwargs) -> List[Finding]:
    """Trace ``fn`` abstractly and run the graph-lint rules; returns
    findings (errors first) without raising.

    ``fn`` must be a PYTHON function (pre-jit).  A ``track_retraces``
    wrapper (observability/watchdog.py) is unwrapped automatically: its
    stored ``python_fn`` is traced — never the counted body, so a lint
    pass costs no watchdog budget — and its ``jit_kwargs`` supply
    ``donate_argnums``/``donate_argnames`` unless given explicitly, so
    ``analyze(engine._step_fn, *args)`` sees exactly what the real call
    site donates."""
    raw = getattr(fn, "python_fn", None)
    if raw is not None:                          # TrackedFunction
        jk = dict(getattr(fn, "jit_kwargs", None) or {})
        if donate_argnums is None:
            donate_argnums = jk.get("donate_argnums", ())
        if donate_argnames is None:
            donate_argnames = jk.get("donate_argnames", ())
        fn = raw
    ctx = trace_for_lint(fn, *args,
                         donate_argnums=donate_argnums or (),
                         donate_argnames=donate_argnames or (), **kwargs)
    findings: List[Finding] = []
    for rule in (rules if rules is not None else default_rules()):
        findings.extend(rule.run(ctx))
    order = {"error": 0, "warning": 1}
    findings.sort(key=lambda f: order.get(f.severity, 2))
    return findings


def report(findings: Sequence[Finding], context: str = "") -> str:
    """Human-readable multi-line report of a finding list."""
    head = (f"graph lint: {len(findings)} finding(s)"
            + (f" in {context}" if context else ""))
    return "\n".join([head] + [f"  {f}" for f in findings])


def check(fn, *args, **kwargs) -> List[Finding]:
    """Lint ``fn``; raise :class:`GraphLintError` on ANY finding, else
    return the (empty) finding list — the collective lint's
    ``check_collective_order`` contract."""
    findings = analyze(fn, *args, **kwargs)
    if findings:
        raise GraphLintError(report(findings))
    return findings


def enforce(findings: Sequence[Finding],
            context: str = "") -> Sequence[Finding]:
    """Apply ``FLAGS_graph_lint`` to a finding list: ``raise`` →
    :class:`GraphLintError`, ``warn`` → one :class:`GraphLintWarning`,
    ``off`` → pass through.  Serving engines call this on their
    first-tick self-lint."""
    if not findings:
        return findings
    action = str(_flags.flag("graph_lint"))
    if action == "off":
        return findings
    msg = report(findings, context)
    if action == "raise":
        raise GraphLintError(msg)
    warnings.warn(msg, GraphLintWarning, stacklevel=2)
    return findings
