"""``python -m paddle_tpu.static_analysis`` — lint the serving step.

Builds a tiny-config llama ServingEngine in every cache layout
(contiguous / paged, wave / chunked admission, plus the
speculative-decode verify step in both cache layouts and its chunked
composition), runs the graph-lint suite over each once-jitted step
function via ``engine.lint_step()`` (one abstract trace per layout — no
compile, no device step), and prints the findings.

``--mesh mp2dp2`` runs the MESH pre-flight (ISSUE 8) instead: every
layout is linted under its declared shardings with the mesh rule set
armed (replication-blowup / resharding-hazard / collective-deadlock),
the per-axis collective-cost and per-device HBM-liveness numbers are
printed, the HBM prediction is cross-checked against the engine's
``cache_hbm_bytes``, and the in-tree mesh-native decode step (the
``generate()`` scan body under ``decode_mesh_specs``) is linted as one
more layout.  The mesh is ABSTRACT — the axes need not exist on this
host, so a laptop can pre-flight a pod topology.

``--kernels`` (ISSUE 14) arms the KERNEL pre-flight: every layout's
``kernel_preflight`` block (static VMEM/bounds/alignment/streamed-bytes
analysis of the Pallas kernels its dispatch would select), an int8-kv
twin of every layout (the quantized pool changes kernel signatures),
and a standalone ``registered_kernels`` sweep over the TPU-scale
registry plus the dispatch-agreement lint.  Composes with ``--mesh``.

This is the CI smoke for the "zero findings on the serving hot path"
contract (ISSUE 6/8/14 acceptance): the same lint the engines self-run
at their first tick under ``FLAGS_graph_lint``, invocable standalone.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

# --json output contract: bump when the blob SHAPE changes.  v1 was the
# unversioned ISSUE-6 {layout: [findings]} mapping; v2 nests per-layout
# reports under "layouts" and adds the mesh pre-flight blocks; v3 adds
# the optional per-layout "execute" block (--mesh ... --execute); v4
# (ISSUE 14) adds the contiguous+chunked+spec layout and, under
# --kernels, per-layout "kernel_preflight" blocks, int8-kv twin
# layouts, and the standalone "registered_kernels" entry.
SCHEMA_VERSION = 4

_EPILOG = """\
exit status: 0 = every layout linted clean (and, with --mesh, every
HBM cross-check passed; with --execute, every placed step ran with
greedy parity and no placement drift); 1 = at least one finding or
execute failure; 2 = bad usage (argparse).  --json prints one
deterministic JSON object (findings sorted by
severity/rule/path/bytes/message, schema_version=%d) for CI
artifact diffs.""" % SCHEMA_VERSION


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.static_analysis",
        description="Graph-lint a tiny-config ServingEngine step in "
                    "every cache layout; --mesh adds the SPMD "
                    "pre-flight (sharding, collective-cost, "
                    "HBM-liveness) under an abstract mesh",
        epilog=_EPILOG)
    ap.add_argument("--slots", type=int, default=2,
                    help="engine slots (default 2)")
    ap.add_argument("--max-length", type=int, default=64,
                    help="engine max_length (default 64)")
    ap.add_argument("--block-len", type=int, default=16,
                    help="paged block length (default 16)")
    ap.add_argument("--prefill-chunk", type=int, default=8,
                    help="chunked-prefill chunk (default 8)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="speculative draft window (default 4)")
    ap.add_argument("--mesh", default=None, metavar="AXES",
                    help="mesh pre-flight under an abstract mesh given "
                         "as <axis><size> pairs, e.g. mp2dp2 (axis "
                         "names: mp/dp/sharding/sep/pp); no devices "
                         "needed")
    ap.add_argument("--execute", action="store_true",
                    help="with --mesh: also RUN one mesh-placed trace "
                         "per engine layout on this host's devices "
                         "(ISSUE 9 smoke) — greedy outputs must be "
                         "token-identical to the single-chip engine, "
                         "the step must compile once, and the placed "
                         "footprints must match the pre-flight "
                         "prediction; any drift exits non-zero")
    ap.add_argument("--kernels", action="store_true",
                    help="also run the KERNEL pre-flight (ISSUE 14): "
                         "per-layout static VMEM/bounds/alignment/"
                         "streamed-bytes analysis of the Pallas kernels "
                         "each engine's dispatch would select, an "
                         "int8-kv twin of every layout, and the "
                         "registered-kernel registry sweep with the "
                         "dispatch-agreement lint — no compile, no "
                         "device")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report instead of text "
                         "(schema_version %d; see epilog)"
                         % SCHEMA_VERSION)
    args = ap.parse_args(argv)
    if args.execute and not args.mesh:
        ap.error("--execute requires --mesh")

    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaForCausalLM, tiny_llama_config
    from paddle_tpu.models.generation import decode_mesh_specs
    from paddle_tpu.nn.layer import bind_params
    from paddle_tpu.serving import ServingEngine

    from . import MeshInfo, analyze, preflight, report

    pt.seed(0)
    model = LlamaForCausalLM(tiny_llama_config())
    model.eval()
    minfo = MeshInfo.of(args.mesh) if args.mesh else None

    variants = [
        ("contiguous", {}),
        ("paged", dict(paged=True, block_len=args.block_len)),
        ("contiguous+chunked",
         dict(chunked=True, prefill_chunk=args.prefill_chunk)),
        ("paged+chunked",
         dict(paged=True, block_len=args.block_len, chunked=True,
              prefill_chunk=args.prefill_chunk)),
        # the spec-decode verify step (KV-cache donation must survive
        # the (s, k+1) window signature) in both cache layouts, plus the
        # chunked composition
        ("contiguous+spec",
         dict(spec_decode=True, spec_k=args.spec_k)),
        ("paged+spec",
         dict(paged=True, block_len=args.block_len, spec_decode=True,
              spec_k=args.spec_k)),
        ("paged+chunked+spec",
         dict(paged=True, block_len=args.block_len, chunked=True,
              prefill_chunk=args.prefill_chunk, spec_decode=True,
              spec_k=args.spec_k)),
        ("contiguous+chunked+spec",
         dict(chunked=True, prefill_chunk=args.prefill_chunk,
              spec_decode=True, spec_k=args.spec_k)),
    ]
    if args.kernels:
        # the int8 KV pool changes the kernel signatures (scale
        # operands, int8 streamed tiles) — pre-flight every layout's
        # quantized twin too, so the acceptance sweep covers both
        # cache dtypes
        variants += [(f"{name}+int8kv", dict(kw, kv_cache_dtype="int8"))
                     for name, kw in list(variants)]
    exec_trace = None
    if args.execute:
        import numpy as np
        rng = np.random.RandomState(0)
        v = model.config.vocab_size
        shared = rng.randint(0, v, 2 * args.block_len).astype(np.int32)
        exec_trace = [rng.randint(0, v, n).astype(np.int32)
                      for n in (5, 9)]
        # two shared-prefix prompts so paged layouts exercise trie
        # adoption under the mesh too
        exec_trace += [
            np.concatenate([shared,
                            rng.randint(0, v, k).astype(np.int32)])
            for k in (3, 4)]

    total = exec_failures = 0
    layouts = {}
    for name, kw in variants:
        eng = ServingEngine(model, num_slots=args.slots,
                            max_length=args.max_length, **kw)
        entry = {"cache_hbm_bytes": int(eng.cache_hbm_bytes)}
        if minfo is None:
            # lint_step already merges the kernel pre-flight findings
            findings = eng.lint_step()
        else:
            pf = eng.mesh_preflight(minfo)
            findings = list(pf["findings"])
            if args.kernels:
                findings += list(eng.kernel_preflight()["findings"])
            entry["comm_bytes_per_step"] = {
                a: row["bytes_per_step"]
                for a, row in pf["comm"]["per_axis"].items()}
            entry["peak_hbm_bytes_per_device"] = (
                pf["hbm"]["peak_bytes_per_device"])
            entry["cache_check"] = pf["cache_check"]
        if args.kernels:
            kp = eng.kernel_preflight()
            entry["kernel_preflight"] = {
                "vmem_bytes": kp["vmem_bytes"],
                "vmem_budget_frac": kp["vmem_budget_frac"],
                "streamed_bytes": kp["streamed_bytes"],
                "findings": [f.as_dict() for f in kp["findings"]]}
        entry["findings"] = [f.as_dict() for f in findings]
        if args.execute:
            entry["execute"], nfail = _execute_layout(
                model, kw, args, exec_trace, ServingEngine)
            exec_failures += nfail
        layouts[name] = entry
        total += len(findings)
        if not args.json:
            _print_layout(f"serving.step[{name}]", entry, findings,
                          report)

    if args.kernels:
        # the registry sweep: every registered TPU-scale kernel variant
        # plus satellite 1's dispatch-agreement lint over the shape
        # lattice — independent of any engine config
        from . import (analyze_kernels, dispatch_agreement_findings,
                       kernel_report, registered_kernel_specs)
        specs = registered_kernel_specs()
        reg_findings = (analyze_kernels(specs)
                        + dispatch_agreement_findings())
        layouts["registered_kernels"] = {
            "kernels": [kernel_report(s) for s in specs],
            "findings": [f.as_dict() for f in reg_findings]}
        total += len(reg_findings)
        if not args.json:
            status = "clean" if not reg_findings else "FINDINGS"
            print(f"[kernel-preflight] registered_kernels "
                  f"({len(specs)} specs + dispatch agreement): {status}")
            if reg_findings:
                print(report(reg_findings, context="registered_kernels"))

    if minfo is not None:
        entry, findings = _mesh_decode_step_entry(
            model, minfo, args.slots, args.max_length, jnp,
            bind_params, decode_mesh_specs, analyze, preflight)
        layouts["mesh_decode_step"] = entry
        total += len(findings)
        if not args.json:
            _print_layout("generate.decode_step[mesh]", entry, findings,
                          report)

    if args.json:
        blob = {"schema_version": SCHEMA_VERSION,
                "mesh": minfo.as_dict() if minfo else None,
                "total_findings": total,
                "layouts": layouts}
        if args.execute:
            blob["execute_failures"] = exec_failures
        print(json.dumps(blob, indent=1, sort_keys=True))
    elif not total:
        nrules = len(default_rule_names(mesh=minfo is not None))
        where = f" under mesh {minfo.as_dict()}" if minfo else ""
        ran = (f"; {len(layouts) - 1} placed layouts executed with "
               f"greedy parity" if args.execute and not exec_failures
               else "")
        print(f"[graph-lint] 0 findings across {len(layouts)} layouts"
              f"{where} ({nrules} rules armed){ran}")
    return 1 if total or exec_failures else 0


def _execute_layout(model, kw, args, trace, ServingEngine):
    """ISSUE 9 ``--execute`` smoke for one layout: run a small fixed
    trace through a single-chip engine and a mesh-placed engine on this
    host's devices; the mesh engine must produce token-identical greedy
    outputs, compile its step exactly once, pre-flight clean, and its
    placed footprints must match the prediction (mesh_placement_check).
    Returns the (deterministic) report block and 0/1 failures."""

    def run(extra):
        eng = ServingEngine(model, num_slots=args.slots,
                            max_length=args.max_length, **kw, **extra)
        rids = [eng.submit(p, max_new_tokens=4) for p in trace]
        out = dict(eng.drain())
        return [out[r] for r in rids], eng

    try:
        single, _ = run({})
        placed, eng = run({"mesh": args.mesh})
    except ValueError as e:           # e.g. not enough devices
        return {"error": str(e)}, 1
    pf = eng.mesh_preflight()
    pc = pf.get("placement_check") or {}
    entry = {"greedy_parity": bool(single == placed),
             "step_traces": int(eng.step_traces),
             "preflight_findings": len(pf["findings"]),
             "placement_ok": bool(pc.get("ok", False))}
    ok = (entry["greedy_parity"] and entry["step_traces"] == 1
          and not pf["findings"] and entry["placement_ok"])
    return entry, 0 if ok else 1


def _print_layout(label, entry, findings, report):
    cache_mb = entry["cache_hbm_bytes"] / 1e6
    status = "clean" if not findings else "FINDINGS"
    extra = ""
    kp = entry.get("kernel_preflight")
    if kp is not None:
        extra += (f", kernel vmem {kp['vmem_bytes'] / 1e6:.2f} MB "
                  f"({kp['vmem_budget_frac']:.1%} of budget)")
    if "peak_hbm_bytes_per_device" in entry:
        comm = sum(entry["comm_bytes_per_step"].values())
        extra += (f", comm {comm} B/step, "
                  f"peak {entry['peak_hbm_bytes_per_device'] / 1e6:.2f} "
                  f"MB/device")
    ex = entry.get("execute")
    if ex is not None:
        if "error" in ex:
            extra += f"; EXECUTE FAILED: {ex['error']}"
            status = "FINDINGS"
        else:
            ok = (ex["greedy_parity"] and ex["step_traces"] == 1
                  and ex["placement_ok"])
            extra += (f"; executed: parity={ex['greedy_parity']} "
                      f"traces={ex['step_traces']} "
                      f"placement_ok={ex['placement_ok']}")
            if not ok:
                status = "FINDINGS"
    print(f"[graph-lint] {label} (cache {cache_mb:.2f} MB{extra}): "
          f"{status}")
    if findings:
        print(report(findings, context=label))


def _mesh_decode_step_entry(model, minfo, slots, max_length, jnp,
                            bind_params, decode_mesh_specs, analyze,
                            preflight):
    """Lint the in-tree mesh-native decode step — the ``generate()``
    scan body (decode-at-depth, one token per row) under the declared
    ``decode_mesh_specs`` layout — as one more pre-flight target."""
    from ..models.generation import init_kv_cache

    bind = getattr(model, "unwrapped", model)
    prepare = getattr(model, "_prepare_params", lambda p: p)
    params = model.state_dict(include_buffers=True)
    cache = init_kv_cache(model.config, slots, max_length)

    def decode_step(params, cache, tokens, positions):
        with bind_params(bind, prepare(params)):
            logits, cache = model.decode_step(
                tokens[:, None], cache, positions)
        return jnp.argmax(logits[:, -1], axis=-1), cache

    param_specs, cache_spec, ids_spec = decode_mesh_specs(
        model, params, minfo.names)
    toks = jnp.zeros((slots,), jnp.int32)
    pos = jnp.zeros((slots,), jnp.int32)
    pf = preflight(decode_step, params, cache, toks, pos,
                   mesh=minfo, donate_argnums=(1,),
                   in_shardings=(param_specs, cache_spec, ids_spec,
                                 ids_spec))
    findings = pf["findings"]
    entry = {
        "cache_hbm_bytes": int(cache.nbytes),
        "comm_bytes_per_step": {
            a: row["bytes_per_step"]
            for a, row in pf["comm"]["per_axis"].items()},
        "peak_hbm_bytes_per_device": pf["hbm"]["peak_bytes_per_device"],
        "findings": [f.as_dict() for f in findings]}
    return entry, findings


def default_rule_names(mesh: bool = False) -> List[str]:
    from . import default_mesh_rules, default_rules
    rules = default_rules() + (default_mesh_rules() if mesh else ())
    return [r.name for r in rules]


if __name__ == "__main__":
    sys.exit(main())
