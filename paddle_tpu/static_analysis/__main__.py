"""``python -m paddle_tpu.static_analysis`` — lint the serving step.

Builds a tiny-config llama ServingEngine in every cache layout
(contiguous / paged, wave / chunked admission, plus the
speculative-decode verify step in both cache layouts and its chunked
composition), runs the graph-lint suite over each once-jitted step
function via ``engine.lint_step()`` (one abstract trace per layout — no
compile, no device step), and prints the findings.  Exit status 0 =
clean, 1 = findings.

This is the CI smoke for the "zero findings on the serving hot path"
contract (ISSUE 6 acceptance): the same lint the engines self-run at
their first tick under ``FLAGS_graph_lint``, invocable standalone.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.static_analysis",
        description="Graph-lint a tiny-config ServingEngine step in "
                    "every cache layout")
    ap.add_argument("--slots", type=int, default=2,
                    help="engine slots (default 2)")
    ap.add_argument("--max-length", type=int, default=64,
                    help="engine max_length (default 64)")
    ap.add_argument("--block-len", type=int, default=16,
                    help="paged block length (default 16)")
    ap.add_argument("--prefill-chunk", type=int, default=8,
                    help="chunked-prefill chunk (default 8)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="speculative draft window (default 4)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings instead of the report")
    args = ap.parse_args(argv)

    import paddle_tpu as pt
    from paddle_tpu.models import LlamaForCausalLM, tiny_llama_config
    from paddle_tpu.serving import ServingEngine

    from . import report

    pt.seed(0)
    model = LlamaForCausalLM(tiny_llama_config())
    model.eval()

    variants = [
        ("contiguous", {}),
        ("paged", dict(paged=True, block_len=args.block_len)),
        ("contiguous+chunked",
         dict(chunked=True, prefill_chunk=args.prefill_chunk)),
        ("paged+chunked",
         dict(paged=True, block_len=args.block_len, chunked=True,
              prefill_chunk=args.prefill_chunk)),
        # the spec-decode verify step (KV-cache donation must survive
        # the (s, k+1) window signature) in both cache layouts, plus the
        # chunked composition
        ("contiguous+spec",
         dict(spec_decode=True, spec_k=args.spec_k)),
        ("paged+spec",
         dict(paged=True, block_len=args.block_len, spec_decode=True,
              spec_k=args.spec_k)),
        ("paged+chunked+spec",
         dict(paged=True, block_len=args.block_len, chunked=True,
              prefill_chunk=args.prefill_chunk, spec_decode=True,
              spec_k=args.spec_k)),
    ]
    total = 0
    blob = {}
    for name, kw in variants:
        eng = ServingEngine(model, num_slots=args.slots,
                            max_length=args.max_length, **kw)
        findings = eng.lint_step()
        total += len(findings)
        if args.json:
            blob[name] = [f.as_dict() for f in findings]
        else:
            cache_mb = eng.cache_hbm_bytes / 1e6
            status = "clean" if not findings else "FINDINGS"
            print(f"[graph-lint] serving.step[{name}] "
                  f"(cache {cache_mb:.2f} MB): {status}")
            if findings:
                print(report(findings, context=f"serving.step[{name}]"))
    if args.json:
        print(json.dumps(blob, indent=1))
    elif not total:
        print(f"[graph-lint] 0 findings across {len(variants)} layouts "
              f"({len(default_rule_names())} rules armed)")
    return 1 if total else 0


def default_rule_names() -> List[str]:
    from . import default_rules
    return [r.name for r in default_rules()]


if __name__ == "__main__":
    sys.exit(main())
