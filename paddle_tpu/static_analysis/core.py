"""Shared jaxpr-walking core for paddle_tpu's static analyzers.

PAPER.md's sanitizer row names the TPU-native answer to upstream
Paddle's NCCL watchdog + StreamSafeCUDAAllocator as "XLA's checker + a
shard_map collective-order lint of our own".  The collective lint
(distributed/lint.py) was the first such rule; this module is the
machinery it and every later rule share, factored out so there is ONE
version-compat surface for jax's primitive renames, ONE sub-jaxpr
discovery convention, and ONE structured :class:`Finding` shape:

  * :func:`sub_jaxprs` / :func:`iter_eqns` — duck-typed discovery and
    recursive walking of the jaxprs hiding in eqn params (pjit bodies,
    scan/cond/while branches, shard_map, remat, custom_* rules);
  * :data:`CANONICAL` / :func:`canonical_name` — the jax-rename-tolerant
    primitive-name mapping (``psum``/``psum2``/``psum_invariant`` are one
    collective across jax releases);
  * :func:`install_rep_rule_fallbacks` — the 0.4.x shard_map rep-checker
    shims without which linting a while_loop under shard_map explodes
    before any walk starts;
  * :func:`trace_for_lint` — one abstract trace of a python function
    into a :class:`LintContext` (closed jaxpr + flat labelled inputs +
    donation marks), the input every graph-lint rule consumes.

Nothing here runs device code: ``jax.make_jaxpr`` is abstract, so a lint
pass costs one trace, before any compile or dispatch.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax

__all__ = ["Finding", "GraphLintError", "GraphLintWarning", "CANONICAL",
           "canonical_name", "sub_jaxprs", "iter_eqns", "aval_bytes",
           "install_rep_rule_fallbacks", "FlatInput", "LintContext",
           "trace_for_lint", "MeshInfo", "canon_spec", "spec_axes",
           "sharded_bytes", "EqnRecord", "propagate_shardings",
           "MeshLintContext", "trace_for_mesh_lint"]


class GraphLintError(RuntimeError):
    """Static-analysis findings promoted to an error (``check`` /
    ``enforce`` under ``FLAGS_graph_lint='raise'``)."""


class GraphLintWarning(UserWarning):
    """Findings surfaced under ``FLAGS_graph_lint='warn'``."""


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured lint finding.

    ``rule``: the rule id (``donation``, ``dtype-promotion``, ...);
    ``severity``: ``error`` (a perf/memory bug on the serving hot path)
    or ``warning`` (a hazard worth a look); ``path``: the eqn path
    through the jaxpr (``""`` = the traced function's top level /
    its input-output signature); ``bytes``: estimated HBM at stake,
    where the rule can size it.
    """

    rule: str
    severity: str
    path: str
    message: str
    bytes: Optional[int] = None

    def as_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"rule": self.rule, "severity": self.severity,
                             "path": self.path, "message": self.message}
        if self.bytes is not None:
            d["bytes"] = int(self.bytes)
        return d

    def __str__(self) -> str:
        b = f" [{self.bytes} bytes]" if self.bytes is not None else ""
        return (f"{self.rule}({self.severity}) "
                f"{self.path or '<signature>'}: {self.message}{b}")


# version-specific primitive name -> the canonical name schedules report
# (and tests pin): jax renames collectives across releases — lax.psum
# traces as "psum2" under the 0.4.x shard_map rewrite and as
# "psum_invariant" under the vma type system (jax >= 0.8) — so analyzers
# match through this table instead of pinning one release's strings.
CANONICAL: Dict[str, str] = {
    "psum": "psum_invariant",
    "psum2": "psum_invariant",
    "psum_invariant": "psum_invariant",
    "all_gather_invariant": "all_gather",
}


def canonical_name(name: str) -> str:
    """Canonical primitive name across jax releases."""
    return CANONICAL.get(name, name)


def install_rep_rule_fallbacks() -> None:
    """jax 0.4.x's shard_map rep-checker has no rule for ``while`` (and
    raises NotImplementedError at trace time), so linting a while_loop
    under shard_map — the exact pattern the collective lint exists to
    inspect — would explode before the walk even starts.  Register a
    conservative fallback (outputs replicated over NO axes: never claims
    a replication it can't prove, so it is sound for any out_specs that
    mention every mesh axis) for the control-flow primitives the checker
    is missing.  vma-era jax (>= 0.8) has real rules and is left
    untouched.  Idempotent."""
    try:
        from jax.experimental import shard_map as _sm
        rules = getattr(_sm, "_check_rules", None)
        if rules is None:
            return
        import jax.extend.core as _core  # noqa: F401  (presence probe)
        from jax import lax as _lax
        for prim_name in ("while_p",):
            prim = getattr(_lax, prim_name, None)
            if prim is None:
                from jax._src.lax import control_flow as _cf
                prim = getattr(_cf, prim_name, None)
            if prim is not None and prim not in rules:
                rules[prim] = lambda mesh, *in_rep, **params: set()
                # the efficient-transpose rewrite trace keeps a second
                # rule table; "bind unchanged, rep from the check rule"
                # is the registered no-op there
                if hasattr(_sm, "register_norewrite"):
                    _sm.register_norewrite(prim)
    except Exception:       # pragma: no cover - newer jax needs nothing
        pass


install_rep_rule_fallbacks()


def sub_jaxprs(eqn) -> List[Tuple[str, Any]]:
    """(param_name, jaxpr) pairs hiding in an eqn's params (duck-typed: a
    ClosedJaxpr exposes ``.jaxpr``, a raw Jaxpr exposes ``.eqns``)."""
    out = []
    for k, v in eqn.params.items():
        vals = v if isinstance(v, (tuple, list)) else [v]
        for item in vals:
            if hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                out.append((k, item.jaxpr))
            elif hasattr(item, "eqns"):          # raw Jaxpr
                out.append((k, item))
    return out


def iter_eqns(jaxpr, path: str = "") -> Iterator[Tuple[str, Any]]:
    """Yield ``(path, eqn)`` for every equation reachable from ``jaxpr``,
    descending into sub-jaxprs (pjit bodies, scan/cond/while branches,
    shard_map, remat, custom_* rules).  Path components are primitive
    names; primitives carrying a string ``name`` param (pjit, remat)
    append it as ``pjit[softmax]`` so rules can allowlist regions by the
    traced function's own name."""
    for eqn in jaxpr.eqns:
        yield path, eqn
        name = eqn.primitive.name
        tag = eqn.params.get("name")
        comp = f"{name}[{tag}]" if isinstance(tag, str) else name
        for _, sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub, f"{path}/{comp}")


def aval_bytes(aval) -> Optional[int]:
    """Byte size of an abstract value, or None when it has no static
    numeric size (extended dtypes like PRNG keys, symbolic dims)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return None
    try:
        if jax.dtypes.issubdtype(dtype, jax.dtypes.extended):
            return None
        size = 1
        for d in shape:
            size *= int(d)
        return int(size * dtype.itemsize)
    except Exception:
        return None


@dataclasses.dataclass(frozen=True)
class FlatInput:
    """One flattened input leaf of the traced call: its position in
    ``closed.in_avals``, a human label (argname + pytree keypath), its
    aval, and whether the caller donates it."""

    index: int
    label: str
    aval: Any
    donated: bool


@dataclasses.dataclass
class LintContext:
    """Everything a rule needs from ONE abstract trace."""

    closed: Any                      # ClosedJaxpr from jax.make_jaxpr
    inputs: List[FlatInput]
    out_avals: List[Any]
    fn_name: str


def _arg_names(fn, nargs: int) -> List[str]:
    """Positional parameter names of ``fn`` (labels + donate_argnames
    resolution); falls back to argN for builtins/odd signatures."""
    import inspect
    try:
        params = [p for p in inspect.signature(fn).parameters.values()
                  if p.kind in (p.POSITIONAL_ONLY,
                                p.POSITIONAL_OR_KEYWORD)]
        names = [p.name for p in params[:nargs]]
    except (TypeError, ValueError):
        names = []
    names += [f"arg{i}" for i in range(len(names), nargs)]
    return names


def trace_for_lint(fn, *args, donate_argnums=(), donate_argnames=(),
                   **kwargs) -> LintContext:
    """One abstract trace of ``fn`` into a :class:`LintContext`.

    ``fn`` must be the PYTHON function (pre-jit) — pass a
    ``track_retraces`` wrapper's ``python_fn``, never the counted/jitted
    callable, or the lint trace itself would burn a watchdog budget.
    ``donate_argnums``/``donate_argnames`` describe what the real call
    site's ``jax.jit`` donates; they do not change the trace, only the
    donation marks rules read."""
    from jax import tree_util as jtu

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    names = _arg_names(fn, len(args))
    donated_pos = {int(i) for i in (donate_argnums or ())}
    donated_names = {str(n) for n in (donate_argnames or ())}
    for nm in donated_names:
        if nm in names:
            donated_pos.add(names.index(nm))

    leaves = jtu.tree_flatten_with_path((tuple(args), dict(kwargs)))[0]
    inputs: List[FlatInput] = []
    for idx, (kp, _leaf) in enumerate(leaves):
        if idx >= len(closed.in_avals):      # defensive: never misalign
            break
        head, rest = kp[1], kp[2:]           # kp[0] is the (args, kwargs)
        if isinstance(head, jtu.SequenceKey):  # positional arg
            nm = names[head.idx] if head.idx < len(names) \
                else f"arg{head.idx}"
            donated = head.idx in donated_pos
        else:                                  # keyword arg
            nm = str(getattr(head, "key", head))
            donated = nm in donated_names
        label = nm + jtu.keystr(tuple(rest))
        inputs.append(FlatInput(idx, label, closed.in_avals[idx], donated))

    fn_name = getattr(fn, "__qualname__", None) or getattr(
        fn, "__name__", type(fn).__name__)
    return LintContext(closed=closed, inputs=inputs,
                       out_avals=list(closed.out_avals), fn_name=fn_name)


# ---------------------------------------------------------------------------
# Mesh-aware layer (ISSUE 8): sharding specs, propagation, mesh trace
# ---------------------------------------------------------------------------
#
# A "spec" below is the canonical per-dimension sharding of one array:
# a tuple with one entry per dim, each entry the tuple of mesh axis names
# that dim is split over (() = replicated dim).  ``None`` stands for
# UNKNOWN — propagation could not prove anything — which every consumer
# must treat conservatively (replicated for byte accounting, silent for
# hazard rules).  Inputs are never unknown: an input with no declared or
# committed sharding is replicated, which is exactly what jit does with
# an unconstrained operand and exactly the waste the replication-blowup
# rule exists to flag.

Spec = Tuple[Tuple[str, ...], ...]


@dataclasses.dataclass(frozen=True)
class MeshInfo:
    """Abstract mesh for the lint: ordered (axis, size) pairs.  No
    devices — built from a jax ``Mesh``/``AbstractMesh``, a dict, or a
    compact string like ``"mp2dp2"`` — so a pre-flight runs on a laptop
    for a topology that only exists in the cluster."""

    axes: Tuple[Tuple[str, int], ...]

    @classmethod
    def of(cls, mesh) -> "MeshInfo":
        if isinstance(mesh, MeshInfo):
            return mesh
        if isinstance(mesh, str):
            import re
            pairs = re.findall(r"([a-zA-Z_]+?)(\d+)", mesh)
            if not pairs or "".join(a + n for a, n in pairs) != mesh:
                raise ValueError(
                    f"cannot parse mesh string {mesh!r}; expected "
                    f"<axis><size> pairs like 'mp2dp2'")
            return cls(tuple((a, int(n)) for a, n in pairs))
        if isinstance(mesh, dict):
            return cls(tuple((str(a), int(n)) for a, n in mesh.items()))
        names = getattr(mesh, "axis_names", None)
        if names is not None:            # jax Mesh / AbstractMesh
            shape = mesh.shape           # mapping axis -> size
            return cls(tuple((str(a), int(shape[a])) for a in names))
        raise TypeError(f"cannot build MeshInfo from {type(mesh)}")

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(a for a, _ in self.axes)

    def size(self, name: str) -> int:
        for a, n in self.axes:
            if a == name:
                return n
        raise KeyError(name)

    def nshards(self, spec: Optional[Spec]) -> int:
        """Devices one shard of an array with this spec is divided
        over (product of the sizes of every axis the spec uses);
        unknown spec = replicated = 1."""
        if spec is None:
            return 1
        n = 1
        for entry in spec:
            for a in entry:
                n *= self.size(a)
        return n

    def as_dict(self) -> Dict[str, int]:
        return {a: n for a, n in self.axes}


def canon_spec(spec, ndim: int,
               axis_names: Optional[Tuple[str, ...]] = None
               ) -> Optional[Spec]:
    """Canonicalize a PartitionSpec / tuple into the per-dim form,
    padded with replicated dims to ``ndim`` and filtered to
    ``axis_names`` when given.  None passes through (unknown)."""
    if spec is None:
        return None
    entries = list(spec)[:ndim]
    out = []
    for e in entries:
        if e is None:
            out.append(())
        elif isinstance(e, (tuple, list)):
            out.append(tuple(str(a) for a in e
                             if axis_names is None or str(a) in axis_names))
        else:
            a = str(e)
            out.append((a,) if axis_names is None or a in axis_names
                       else ())
    out += [()] * (ndim - len(out))
    return tuple(out)


def spec_axes(spec: Optional[Spec]) -> Tuple[str, ...]:
    """Every mesh axis a spec uses, in first-appearance order."""
    if spec is None:
        return ()
    seen = []
    for entry in spec:
        for a in entry:
            if a not in seen:
                seen.append(a)
    return tuple(seen)


def sharded_bytes(aval, spec: Optional[Spec], mesh: MeshInfo
                  ) -> Optional[int]:
    """Per-device bytes of an abstract value under a sharding spec
    (replicated / unknown = the full buffer on every device)."""
    b = aval_bytes(aval)
    if b is None:
        return None
    return -(-b // mesh.nshards(spec))        # ceil division


@dataclasses.dataclass(frozen=True)
class EqnRecord:
    """One equation the propagation walker visited, with the specs it
    proved for the eqn's operands and outputs (None = unknown)."""

    path: str
    eqn: Any
    in_specs: Tuple[Optional[Spec], ...]
    out_specs: Tuple[Optional[Spec], ...]
    multiplier: int        # static trip count (scan length) enclosing it


# reduce-style primitives whose params carry the reduced dims in "axes"
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin",
})


def _prop_eqn(eqn, ins: List[Optional[Spec]], mesh: MeshInfo
              ) -> List[Optional[Spec]]:
    """Local GSPMD-style propagation: given operand specs, what can we
    prove about the outputs?  Conservative — anything not covered by a
    rule falls back to the shape-match heuristic, then to unknown."""
    name = eqn.primitive.name
    out_avals = [getattr(v, "aval", None) for v in eqn.outvars]

    if name == "sharding_constraint":
        sh = eqn.params.get("sharding")
        spec = getattr(sh, "spec", None)
        return [canon_spec(spec, out_avals[0].ndim, mesh.names)]

    if name == "transpose" and ins and ins[0] is not None:
        perm = eqn.params.get("permutation")
        if perm is not None:
            return [tuple(ins[0][int(p)] for p in perm)]

    if name == "broadcast_in_dim" and ins and ins[0] is not None:
        bdims = eqn.params.get("broadcast_dimensions", ())
        src = ins[0]
        out = [()] * out_avals[0].ndim
        for i, d in enumerate(bdims):
            if i < len(src):
                out[int(d)] = src[i]
        return [tuple(out)]

    if name in _REDUCE_PRIMS and ins and ins[0] is not None:
        axes = set(int(a) for a in eqn.params.get("axes", ()))
        kept = tuple(s for d, s in enumerate(ins[0]) if d not in axes)
        return [kept for _ in out_avals]

    if name == "squeeze" and ins and ins[0] is not None:
        dims = set(int(d) for d in eqn.params.get("dimensions", ()))
        return [tuple(s for d, s in enumerate(ins[0]) if d not in dims)]

    if name == "dot_general" and len(ins) >= 2:
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        l, r = ins[0], ins[1]
        if l is not None and r is not None:
            lnd = len(l)
            rnd = len(r)
            batch = tuple(l[int(d)] for d in lb)
            lfree = tuple(l[d] for d in range(lnd)
                          if d not in set(map(int, lc))
                          and d not in set(map(int, lb)))
            rfree = tuple(r[d] for d in range(rnd)
                          if d not in set(map(int, rc))
                          and d not in set(map(int, rb)))
            return [batch + lfree + rfree]

    if name in ("dynamic_update_slice", "scatter", "scatter-add",
                "scatter-mul", "scatter-min", "scatter-max") and ins:
        return [ins[0]]

    if name == "dynamic_slice" and ins and ins[0] is not None:
        src_aval = getattr(eqn.invars[0], "aval", None)
        out = []
        for d, s in enumerate(ins[0]):
            same = (src_aval is not None
                    and out_avals[0].shape[d] == src_aval.shape[d])
            out.append(s if same else ())
        return [tuple(out)]

    if name == "concatenate" and ins and all(s is not None for s in ins):
        if len({tuple(s) for s in ins}) == 1:
            dim = int(eqn.params.get("dimension", 0))
            base = list(ins[0])
            base[dim] = ()
            return [tuple(base)]

    if name == "reshape" and ins and ins[0] is not None:
        src_aval = getattr(eqn.invars[0], "aval", None)
        if (src_aval is not None
                and tuple(src_aval.shape) == tuple(out_avals[0].shape)):
            return [ins[0]]

    # shape-match fallback: an output the same shape as a known operand
    # (elementwise chains, convert_element_type, select, where, ...)
    out: List[Optional[Spec]] = []
    for av in out_avals:
        if av is None or getattr(av, "shape", None) is None:
            out.append(None)
            continue
        if av.ndim == 0:
            out.append(())
            continue
        cands = []
        for s, v in zip(ins, eqn.invars):
            va = getattr(v, "aval", None)
            if (s is not None and va is not None
                    and tuple(getattr(va, "shape", ())) == tuple(av.shape)):
                cands.append(tuple(s))
        out.append(cands[0] if cands and len(set(cands)) == 1 else None)
    return out


# eqn params that carry descendable call bodies whose operands map 1:1
# onto the sub-jaxpr's invars (pjit, remat, custom_* forward rules)
_TRANSPARENT_CALLS = frozenset({
    "pjit", "remat", "remat2", "checkpoint", "custom_jvp_call",
    "custom_vjp_call", "custom_vjp_call_jaxpr", "closed_call",
    "core_call", "xla_call",
})


def propagate_shardings(closed, in_specs: List[Optional[Spec]],
                        mesh: MeshInfo
                        ) -> Tuple[Dict[Any, Optional[Spec]],
                                   List[EqnRecord]]:
    """Walk the jaxpr forward, assigning every var the sharding spec
    propagation can prove from the input specs, the rule table above,
    and ``with_sharding_constraint`` annotations.  Returns the var->spec
    environment (top level + transparently-descended call bodies) and
    the visit records (one per eqn, with the specs at that site).

    shard_map bodies are recorded (for the collective walk) but their
    operands are per-shard values — specs inside are deliberately
    unknown; the eqn's own outputs take their specs from ``out_names``.
    Control-flow bodies (scan/while/cond) are recorded with a static
    trip-count multiplier (scan length; while = 1, a lower bound) and
    unknown internal specs."""
    env: Dict[Any, Optional[Spec]] = {}
    records: List[EqnRecord] = []

    def read(v) -> Optional[Spec]:
        if hasattr(v, "val"):            # Literal
            nd = getattr(getattr(v, "aval", None), "ndim", 0)
            return ((),) * nd
        return env.get(v)

    def walk(jaxpr, specs_in: List[Optional[Spec]], path: str,
             mult: int) -> List[Optional[Spec]]:
        for var, s in zip(jaxpr.invars, specs_in):
            env[var] = s
        for cv in jaxpr.constvars:
            nd = getattr(getattr(cv, "aval", None), "ndim", 0)
            env[cv] = ((),) * nd
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            ins = [read(v) for v in eqn.invars]
            tag = eqn.params.get("name")
            comp = f"{name}[{tag}]" if isinstance(tag, str) else name
            outs: List[Optional[Spec]]
            if name in _TRANSPARENT_CALLS:
                subs = sub_jaxprs(eqn)
                outs = [None] * len(eqn.outvars)
                if subs:
                    _, body = subs[0]
                    n_extra = len(body.invars) - len(ins)
                    body_in = ([None] * n_extra + ins if n_extra >= 0
                               else ins[:len(body.invars)])
                    outs = walk(body, body_in, f"{path}/{comp}", mult)
                    outs = (outs + [None] * len(eqn.outvars)
                            )[:len(eqn.outvars)]
            elif name == "shard_map":
                out_names = eqn.params.get("out_names") or ()
                outs = []
                for i, v in enumerate(eqn.outvars):
                    nd = getattr(getattr(v, "aval", None), "ndim", 0)
                    try:
                        names_map = out_names[i]
                        spec = [()] * nd
                        for d, axes in dict(names_map).items():
                            spec[int(d)] = tuple(
                                a for a in axes if a in mesh.names)
                        outs.append(tuple(spec))
                    except Exception:
                        outs.append(None)
                for _, body in sub_jaxprs(eqn):
                    walk(body, [None] * len(body.invars),
                         f"{path}/{comp}", mult)
            elif name == "scan":
                length = int(eqn.params.get("length", 1) or 1)
                outs = [None] * len(eqn.outvars)
                for _, body in sub_jaxprs(eqn):
                    walk(body, [None] * len(body.invars),
                         f"{path}/{comp}", mult * max(length, 1))
            elif name in ("while", "cond"):
                outs = [None] * len(eqn.outvars)
                for _, body in sub_jaxprs(eqn):
                    walk(body, [None] * len(body.invars),
                         f"{path}/{comp}", mult)
            else:
                try:
                    outs = _prop_eqn(eqn, ins, mesh)
                except Exception:
                    outs = [None] * len(eqn.outvars)
                outs = (list(outs) + [None] * len(eqn.outvars)
                        )[:len(eqn.outvars)]
            records.append(EqnRecord(path, eqn, tuple(ins), tuple(outs),
                                     mult))
            for v, s in zip(eqn.outvars, outs):
                env[v] = s
        return [read(v) for v in jaxpr.outvars]

    walk(closed.jaxpr, list(in_specs), "", 1)
    return env, records


@dataclasses.dataclass
class MeshLintContext(LintContext):
    """A LintContext traced under an abstract mesh: per-input sharding
    specs (aligned with ``inputs``), the propagated var->spec
    environment, and the eqn visit records the mesh rules and the
    collective-cost model consume."""

    mesh: MeshInfo = None
    in_specs: List[Optional[Spec]] = dataclasses.field(
        default_factory=list)
    var_specs: Dict[Any, Optional[Spec]] = dataclasses.field(
        default_factory=dict)
    records: List[EqnRecord] = dataclasses.field(default_factory=list)
    out_specs: List[Optional[Spec]] = dataclasses.field(
        default_factory=list)

    def input_spec(self, fi: FlatInput) -> Optional[Spec]:
        return self.in_specs[fi.index]


def _declared_specs(args, kwargs, in_shardings, mesh: MeshInfo
                    ) -> List[Spec]:
    """Flatten ``in_shardings`` (a per-positional-arg sequence whose
    entries are None, a single PartitionSpec applied to every leaf of
    that arg, or a spec pytree matching the arg) — or, when None, read
    each leaf's committed NamedSharding — into one canonical spec per
    flat input leaf.  Undeclared/uncommitted leaves are REPLICATED."""
    from jax import tree_util as jtu
    from jax.sharding import PartitionSpec

    def is_spec(x):
        return x is None or isinstance(x, PartitionSpec)

    def leaf_committed(leaf):
        sh = getattr(leaf, "sharding", None)
        spec = getattr(sh, "spec", None)
        m = getattr(sh, "mesh", None)
        if spec is not None and m is not None and any(
                str(a) in mesh.names for a in getattr(m, "axis_names", ())):
            return spec
        return None

    flat: List[Spec] = []
    if in_shardings is not None:
        in_shardings = tuple(in_shardings)
        if len(in_shardings) != len(args):
            raise ValueError(
                f"in_shardings has {len(in_shardings)} entries for "
                f"{len(args)} positional args")
        for arg, sh in zip(args, in_shardings):
            leaves = jtu.tree_leaves(arg)
            if is_spec(sh):
                specs = [sh] * len(leaves)
            else:
                specs = jtu.tree_leaves(sh, is_leaf=is_spec)
                if len(specs) != len(leaves):
                    raise ValueError(
                        f"in_shardings entry with {len(specs)} specs "
                        f"does not match an arg with {len(leaves)} "
                        f"array leaves")
            for leaf, s in zip(leaves, specs):
                nd = getattr(leaf, "ndim", 0)
                flat.append(canon_spec(s, nd, mesh.names)
                            or ((),) * nd)
        for leaf in jtu.tree_leaves(dict(kwargs)):
            flat.append(((),) * getattr(leaf, "ndim", 0))
    else:
        for leaf in jtu.tree_leaves((tuple(args), dict(kwargs))):
            nd = getattr(leaf, "ndim", 0)
            flat.append(canon_spec(leaf_committed(leaf), nd, mesh.names)
                        or ((),) * nd)
    return flat


def trace_for_mesh_lint(fn, *args, mesh, in_shardings=None,
                        donate_argnums=(), donate_argnames=(),
                        **kwargs) -> MeshLintContext:
    """One abstract trace of ``fn`` under an abstract mesh: the base
    :func:`trace_for_lint` context, plus per-input sharding specs
    (declared via ``in_shardings`` or read from the args' committed
    NamedShardings) propagated through the jaxpr.  No devices are
    touched — the mesh may be a jax ``Mesh``/``AbstractMesh``, a dict,
    or a string like ``"mp2dp2"`` for hardware that isn't attached."""
    minfo = MeshInfo.of(mesh)
    base = trace_for_lint(fn, *args, donate_argnums=donate_argnums,
                          donate_argnames=donate_argnames, **kwargs)
    specs = _declared_specs(args, kwargs, in_shardings, minfo)
    specs = (specs + [((),)] * len(base.inputs))[:len(base.inputs)]
    env, records = propagate_shardings(base.closed, specs, minfo)
    out_specs = [env.get(v) for v in base.closed.jaxpr.outvars]
    return MeshLintContext(closed=base.closed, inputs=base.inputs,
                           out_avals=base.out_avals, fn_name=base.fn_name,
                           mesh=minfo, in_specs=specs, var_specs=env,
                           records=records, out_specs=out_specs)
