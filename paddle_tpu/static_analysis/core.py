"""Shared jaxpr-walking core for paddle_tpu's static analyzers.

PAPER.md's sanitizer row names the TPU-native answer to upstream
Paddle's NCCL watchdog + StreamSafeCUDAAllocator as "XLA's checker + a
shard_map collective-order lint of our own".  The collective lint
(distributed/lint.py) was the first such rule; this module is the
machinery it and every later rule share, factored out so there is ONE
version-compat surface for jax's primitive renames, ONE sub-jaxpr
discovery convention, and ONE structured :class:`Finding` shape:

  * :func:`sub_jaxprs` / :func:`iter_eqns` — duck-typed discovery and
    recursive walking of the jaxprs hiding in eqn params (pjit bodies,
    scan/cond/while branches, shard_map, remat, custom_* rules);
  * :data:`CANONICAL` / :func:`canonical_name` — the jax-rename-tolerant
    primitive-name mapping (``psum``/``psum2``/``psum_invariant`` are one
    collective across jax releases);
  * :func:`install_rep_rule_fallbacks` — the 0.4.x shard_map rep-checker
    shims without which linting a while_loop under shard_map explodes
    before any walk starts;
  * :func:`trace_for_lint` — one abstract trace of a python function
    into a :class:`LintContext` (closed jaxpr + flat labelled inputs +
    donation marks), the input every graph-lint rule consumes.

Nothing here runs device code: ``jax.make_jaxpr`` is abstract, so a lint
pass costs one trace, before any compile or dispatch.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax

__all__ = ["Finding", "GraphLintError", "GraphLintWarning", "CANONICAL",
           "canonical_name", "sub_jaxprs", "iter_eqns", "aval_bytes",
           "install_rep_rule_fallbacks", "FlatInput", "LintContext",
           "trace_for_lint"]


class GraphLintError(RuntimeError):
    """Static-analysis findings promoted to an error (``check`` /
    ``enforce`` under ``FLAGS_graph_lint='raise'``)."""


class GraphLintWarning(UserWarning):
    """Findings surfaced under ``FLAGS_graph_lint='warn'``."""


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured lint finding.

    ``rule``: the rule id (``donation``, ``dtype-promotion``, ...);
    ``severity``: ``error`` (a perf/memory bug on the serving hot path)
    or ``warning`` (a hazard worth a look); ``path``: the eqn path
    through the jaxpr (``""`` = the traced function's top level /
    its input-output signature); ``bytes``: estimated HBM at stake,
    where the rule can size it.
    """

    rule: str
    severity: str
    path: str
    message: str
    bytes: Optional[int] = None

    def as_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"rule": self.rule, "severity": self.severity,
                             "path": self.path, "message": self.message}
        if self.bytes is not None:
            d["bytes"] = int(self.bytes)
        return d

    def __str__(self) -> str:
        b = f" [{self.bytes} bytes]" if self.bytes is not None else ""
        return (f"{self.rule}({self.severity}) "
                f"{self.path or '<signature>'}: {self.message}{b}")


# version-specific primitive name -> the canonical name schedules report
# (and tests pin): jax renames collectives across releases — lax.psum
# traces as "psum2" under the 0.4.x shard_map rewrite and as
# "psum_invariant" under the vma type system (jax >= 0.8) — so analyzers
# match through this table instead of pinning one release's strings.
CANONICAL: Dict[str, str] = {
    "psum": "psum_invariant",
    "psum2": "psum_invariant",
    "psum_invariant": "psum_invariant",
    "all_gather_invariant": "all_gather",
}


def canonical_name(name: str) -> str:
    """Canonical primitive name across jax releases."""
    return CANONICAL.get(name, name)


def install_rep_rule_fallbacks() -> None:
    """jax 0.4.x's shard_map rep-checker has no rule for ``while`` (and
    raises NotImplementedError at trace time), so linting a while_loop
    under shard_map — the exact pattern the collective lint exists to
    inspect — would explode before the walk even starts.  Register a
    conservative fallback (outputs replicated over NO axes: never claims
    a replication it can't prove, so it is sound for any out_specs that
    mention every mesh axis) for the control-flow primitives the checker
    is missing.  vma-era jax (>= 0.8) has real rules and is left
    untouched.  Idempotent."""
    try:
        from jax.experimental import shard_map as _sm
        rules = getattr(_sm, "_check_rules", None)
        if rules is None:
            return
        import jax.extend.core as _core  # noqa: F401  (presence probe)
        from jax import lax as _lax
        for prim_name in ("while_p",):
            prim = getattr(_lax, prim_name, None)
            if prim is None:
                from jax._src.lax import control_flow as _cf
                prim = getattr(_cf, prim_name, None)
            if prim is not None and prim not in rules:
                rules[prim] = lambda mesh, *in_rep, **params: set()
                # the efficient-transpose rewrite trace keeps a second
                # rule table; "bind unchanged, rep from the check rule"
                # is the registered no-op there
                if hasattr(_sm, "register_norewrite"):
                    _sm.register_norewrite(prim)
    except Exception:       # pragma: no cover - newer jax needs nothing
        pass


install_rep_rule_fallbacks()


def sub_jaxprs(eqn) -> List[Tuple[str, Any]]:
    """(param_name, jaxpr) pairs hiding in an eqn's params (duck-typed: a
    ClosedJaxpr exposes ``.jaxpr``, a raw Jaxpr exposes ``.eqns``)."""
    out = []
    for k, v in eqn.params.items():
        vals = v if isinstance(v, (tuple, list)) else [v]
        for item in vals:
            if hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                out.append((k, item.jaxpr))
            elif hasattr(item, "eqns"):          # raw Jaxpr
                out.append((k, item))
    return out


def iter_eqns(jaxpr, path: str = "") -> Iterator[Tuple[str, Any]]:
    """Yield ``(path, eqn)`` for every equation reachable from ``jaxpr``,
    descending into sub-jaxprs (pjit bodies, scan/cond/while branches,
    shard_map, remat, custom_* rules).  Path components are primitive
    names; primitives carrying a string ``name`` param (pjit, remat)
    append it as ``pjit[softmax]`` so rules can allowlist regions by the
    traced function's own name."""
    for eqn in jaxpr.eqns:
        yield path, eqn
        name = eqn.primitive.name
        tag = eqn.params.get("name")
        comp = f"{name}[{tag}]" if isinstance(tag, str) else name
        for _, sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub, f"{path}/{comp}")


def aval_bytes(aval) -> Optional[int]:
    """Byte size of an abstract value, or None when it has no static
    numeric size (extended dtypes like PRNG keys, symbolic dims)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return None
    try:
        if jax.dtypes.issubdtype(dtype, jax.dtypes.extended):
            return None
        size = 1
        for d in shape:
            size *= int(d)
        return int(size * dtype.itemsize)
    except Exception:
        return None


@dataclasses.dataclass(frozen=True)
class FlatInput:
    """One flattened input leaf of the traced call: its position in
    ``closed.in_avals``, a human label (argname + pytree keypath), its
    aval, and whether the caller donates it."""

    index: int
    label: str
    aval: Any
    donated: bool


@dataclasses.dataclass
class LintContext:
    """Everything a rule needs from ONE abstract trace."""

    closed: Any                      # ClosedJaxpr from jax.make_jaxpr
    inputs: List[FlatInput]
    out_avals: List[Any]
    fn_name: str


def _arg_names(fn, nargs: int) -> List[str]:
    """Positional parameter names of ``fn`` (labels + donate_argnames
    resolution); falls back to argN for builtins/odd signatures."""
    import inspect
    try:
        params = [p for p in inspect.signature(fn).parameters.values()
                  if p.kind in (p.POSITIONAL_ONLY,
                                p.POSITIONAL_OR_KEYWORD)]
        names = [p.name for p in params[:nargs]]
    except (TypeError, ValueError):
        names = []
    names += [f"arg{i}" for i in range(len(names), nargs)]
    return names


def trace_for_lint(fn, *args, donate_argnums=(), donate_argnames=(),
                   **kwargs) -> LintContext:
    """One abstract trace of ``fn`` into a :class:`LintContext`.

    ``fn`` must be the PYTHON function (pre-jit) — pass a
    ``track_retraces`` wrapper's ``python_fn``, never the counted/jitted
    callable, or the lint trace itself would burn a watchdog budget.
    ``donate_argnums``/``donate_argnames`` describe what the real call
    site's ``jax.jit`` donates; they do not change the trace, only the
    donation marks rules read."""
    from jax import tree_util as jtu

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    names = _arg_names(fn, len(args))
    donated_pos = {int(i) for i in (donate_argnums or ())}
    donated_names = {str(n) for n in (donate_argnames or ())}
    for nm in donated_names:
        if nm in names:
            donated_pos.add(names.index(nm))

    leaves = jtu.tree_flatten_with_path((tuple(args), dict(kwargs)))[0]
    inputs: List[FlatInput] = []
    for idx, (kp, _leaf) in enumerate(leaves):
        if idx >= len(closed.in_avals):      # defensive: never misalign
            break
        head, rest = kp[1], kp[2:]           # kp[0] is the (args, kwargs)
        if isinstance(head, jtu.SequenceKey):  # positional arg
            nm = names[head.idx] if head.idx < len(names) \
                else f"arg{head.idx}"
            donated = head.idx in donated_pos
        else:                                  # keyword arg
            nm = str(getattr(head, "key", head))
            donated = nm in donated_names
        label = nm + jtu.keystr(tuple(rest))
        inputs.append(FlatInput(idx, label, closed.in_avals[idx], donated))

    fn_name = getattr(fn, "__qualname__", None) or getattr(
        fn, "__name__", type(fn).__name__)
    return LintContext(closed=closed, inputs=inputs,
                       out_avals=list(closed.out_avals), fn_name=fn_name)
