"""Static kernel geometry registry — every Pallas entry point as data.

The graph-lint layer (ISSUE 6/8) stops at the jaxpr: a ``pallas_call``
is one opaque eqn, so the kernels the serving stack rides — the q-tiled
flash-decode kernel with scalar-prefetch-clamped index maps, the paged
block-table dereference, the int8 scale operands — were validated only
by running them.  This module re-expresses each kernel's GEOMETRY as a
:class:`KernelSpec`: the grid, every BlockSpec's block shape and index
map (rewritten over closed integer intervals, :class:`Iv`), the
scalar-prefetch operands with their DECLARED value ranges, the VMEM
scratch, and the derived tile dims.  ``kernel_rules.py`` walks a spec
WITHOUT compiling anything: VMEM footprint, index-map bounds over the
full grid domain, alignment/tiling, and the streamed-bytes model.

The builders mirror the kernels LINE FOR LINE — ``bq``/``tile_p``/
``chunks`` come from the same arithmetic, the 128-lane and row-cap
gates import :mod:`paddle_tpu.ops.pallas.limits` (the same constants
the kernels and the dispatch rules read), and the block-picking helpers
(``_pick_block_kv``, ``_block_sizes``, ``_pick``, ``_pick_block_rows``)
are imported from the kernel modules themselves, so the spec cannot
drift from the kernel without a test catching it
(tests/test_kernel_preflight.py cross-checks the q-tiled paged decode
footprint against a hand-computed tile sum).

Interval soundness: every index-map operation used here (+, - const,
* positive const, // positive const, elementwise min) is monotone on
non-negative operands, so pushing interval ENDPOINTS through the map
yields exact bounds of the map's range over the domain — no widening,
no false positives on the committed kernels.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..ops.pallas import limits as _limits

DTYPE_BYTES = {"float32": 4, "int32": 4, "bfloat16": 2, "float16": 2,
               "int8": 1, "bool": 1}


class KernelSpecError(ValueError):
    """A shape the registry cannot express as a KernelSpec at all —
    mirrors the kernel's own structural NotImplementedError gates (the
    dispatch-agreement sweep uses :func:`decode_kernel_rejects` to
    compare these against the dispatch decision)."""


# ---------------------------------------------------------------------------
# intervals
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Iv:
    """Closed integer interval [lo, hi] — the abstract value the bounds
    checker pushes through BlockSpec index maps."""

    lo: int
    hi: int

    def __post_init__(self):
        if self.lo > self.hi:
            raise ValueError(f"empty interval [{self.lo}, {self.hi}]")

    @staticmethod
    def const(v: int) -> "Iv":
        return Iv(int(v), int(v))

    def __add__(self, o):
        o = iv(o)
        return Iv(self.lo + o.lo, self.hi + o.hi)

    __radd__ = __add__

    def __sub__(self, o: int):
        return Iv(self.lo - int(o), self.hi - int(o))

    def __mul__(self, o: int):
        if int(o) < 0:
            raise ValueError("interval * negative is not monotone")
        return Iv(self.lo * int(o), self.hi * int(o))

    __rmul__ = __mul__

    def __floordiv__(self, o: int):
        if int(o) <= 0:
            raise ValueError("interval // non-positive")
        return Iv(self.lo // int(o), self.hi // int(o))


def iv(v) -> Iv:
    return v if isinstance(v, Iv) else Iv(int(v), int(v))


def iv_min(a, b) -> Iv:
    """min is monotone in both args: [min(lo), min(hi)] is exact."""
    a, b = iv(a), iv(b)
    return Iv(min(a.lo, b.lo), min(a.hi, b.hi))


# ---------------------------------------------------------------------------
# spec dataclasses
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ScalarOperand:
    """A scalar-prefetch operand with its DECLARED value range —
    the bounds-domain assumption the serving engine upholds
    (BASELINE.md "Kernel pre-flight conventions"): block-table entries
    in [0, num_blocks), per-row pos in [0, max_length - s]."""

    name: str
    shape: Tuple[int, ...]
    lo: int
    hi: int


class ScalarEnv:
    """Interval environment over a spec's scalar operands.  ``lookup``
    records every (operand, index-interval) access so the bounds rule
    can check indices against the operand's shape; the returned
    interval is the operand's declared VALUE range (pinned per-run for
    the clamp corner checks)."""

    def __init__(self, scalars: Sequence[ScalarOperand], pins=None):
        self._sc = {s.name: s for s in scalars}
        self._pins = dict(pins or {})
        self.accesses: List[Tuple[str, Tuple[Iv, ...]]] = []

    def lookup(self, name: str, *idx) -> Iv:
        sc = self._sc[name]
        self.accesses.append((name, tuple(iv(i) for i in idx)))
        pin = self._pins.get(name)
        return iv(pin) if pin is not None else Iv(sc.lo, sc.hi)


@dataclasses.dataclass(frozen=True)
class ClampCheck:
    """Declares that an index map's dereference of ``table`` is the
    dead-tail clamp: with the row position pinned to ``p`` and the
    q-tile grid axis ``pin_axis`` pinned to ``q``, the table COLUMN the
    map touches must top out at exactly ``expected(p, q)`` — the last
    live block.  Higher = unclamped (the dead tail streams, and its
    null-filled entries alias block 0 into live rows); lower =
    over-clamped (live KV silently truncated)."""

    table: str
    pin_scalar: str
    pin_axis: int
    expected: Callable[[int, int], int]


@dataclasses.dataclass(frozen=True)
class BlockOperand:
    """One BlockSpec'd operand (input or output) of a kernel call.

    ``index_map`` takes ``(grid_ivs, ScalarEnv)`` — the grid indices as
    intervals — and returns one interval per block dim, in BLOCK units
    (exactly what the real index map returns per grid step).
    ``streamed`` operands are DMA'd per grid step and double-buffered
    by Pallas (x2 in the VMEM model); ``fetches`` is the number of
    DISTINCT block fetches per kernel call for the streamed-bytes model
    (None = one per grid step; the dead-tail clamp's DMA elision makes
    the decode KV operands' count smaller); ``sublane_padded`` marks
    blocks the kernel explicitly pads to the sublane tile (the decode
    q tiles), exempting them from the sublane lint."""

    name: str
    block_shape: Tuple[int, ...]
    array_shape: Tuple[int, ...]
    dtype: str
    index_map: Callable
    streamed: bool = True
    sublane_padded: bool = False
    fetches: Optional[int] = None
    kv_stream: bool = False
    clamp: Optional[ClampCheck] = None

    def block_bytes(self) -> int:
        n = 1
        for d in self.block_shape:
            n *= int(d)
        return n * DTYPE_BYTES[self.dtype]


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """Everything the static analyzer needs about one kernel call."""

    op: str
    variant: str
    grid: Tuple[int, ...]
    operands: Tuple[BlockOperand, ...]
    scratch: Tuple[Tuple[Tuple[int, ...], str], ...] = ()
    scalars: Tuple[ScalarOperand, ...] = ()
    dims: Dict[str, object] = dataclasses.field(default_factory=dict)

    @property
    def path(self) -> str:
        return f"{self.op}[{self.variant}]"


# ---------------------------------------------------------------------------
# estimators (BASELINE.md "Kernel pre-flight conventions")
# ---------------------------------------------------------------------------

def vmem_footprint(spec: KernelSpec) -> int:
    """Per-grid-step VMEM bytes: every block-shaped operand tile
    (streamed operands x2 for Pallas's DMA double-buffering) plus the
    scratch accumulators, which persist across the grid walk."""
    total = 0
    for op in spec.operands:
        total += op.block_bytes() * (2 if op.streamed else 1)
    for shape, dtype in spec.scratch:
        n = 1
        for d in shape:
            n *= int(d)
        total += n * DTYPE_BYTES[dtype]
    return total


def _grid_size(spec: KernelSpec) -> int:
    n = 1
    for g in spec.grid:
        n *= int(g)
    return n


def streamed_bytes(spec: KernelSpec) -> int:
    """HBM bytes one kernel call moves: per operand, distinct block
    fetches x block bytes.  ``fetches`` encodes the dead-tail clamp's
    DMA elision (consecutive grid steps mapping to the same block cost
    one fetch); operands without it fetch once per grid step."""
    total = 0
    grid_n = _grid_size(spec)
    for op in spec.operands:
        n = grid_n if op.fetches is None else int(op.fetches)
        total += n * op.block_bytes()
    return total


def kv_streamed_bytes(spec: KernelSpec) -> int:
    """Cache-side streamed bytes only (KV blocks + their scale rows) —
    the quantity the committed int8_serving <=0.55x claim bounds."""
    total = 0
    grid_n = _grid_size(spec)
    for op in spec.operands:
        if not op.kv_stream:
            continue
        n = grid_n if op.fetches is None else int(op.fetches)
        total += n * op.block_bytes()
    return total


# ---------------------------------------------------------------------------
# decode_attention_pallas (ops/pallas/decode_attention.py)
# ---------------------------------------------------------------------------

def decode_kernel_rejects(b: int, s: int, hq: int, hkv: int, d: int,
                          kv_len: int, *, paged_block_len=None,
                          quantized: bool = False, n_granules=None,
                          block_kv=None) -> Optional[str]:
    """Mirror of ``decode_attention_pallas``'s NotImplementedError
    gates, in declaration form: the reason the kernel would refuse this
    shape, or None.  The dispatch-agreement lint sweeps this against
    ``ops.attention.decode_shape_gate`` — both derive from
    ops/pallas/limits.py, so a drift is a lint error, not a runtime
    NotImplementedError on the serving hot path."""
    if paged_block_len is not None and paged_block_len % _limits.LANES:
        return f"paged block_len {paged_block_len} is not 128-aligned"
    if hkv == 0 or hq % hkv:
        return f"q heads ({hq}) must be a multiple of kv heads ({hkv})"
    if hq // hkv > _limits.MAX_Q_ROWS:
        return f"GQA group size {hq // hkv} > {_limits.MAX_Q_ROWS}"
    if s > _limits.MAX_Q_LEN:
        return f"q_len {s} > {_limits.MAX_Q_LEN}"
    if d > _limits.MAX_HEAD_DIM:
        return f"head_dim {d} > {_limits.MAX_HEAD_DIM}"
    if paged_block_len is None:
        if quantized:
            ng = int(n_granules or 1)
            bk = kv_len // ng
            if bk * ng != kv_len or bk % _limits.LANES:
                return (f"int8 scale granule {kv_len}/{ng} is not a "
                        f"128-aligned divisor of the cache length")
        else:
            from ..ops.pallas.decode_attention import _pick_block_kv
            if block_kv is None:
                from .. import flags as _flags
                block_kv = int(_flags.flag("decode_attention_block_kv"))
            if not _pick_block_kv(kv_len, int(block_kv)):
                return (f"max_length {kv_len} has no 128-aligned chunk "
                        f"divisor <= {block_kv}")
    return None


def decode_attention_spec(b: int, s: int, hq: int, hkv: int, d: int, *,
                          kv_len: Optional[int] = None,
                          block_len: Optional[int] = None,
                          max_blocks: Optional[int] = None,
                          num_blocks: Optional[int] = None,
                          block_kv: Optional[int] = None,
                          quantized: bool = False,
                          n_granules: Optional[int] = None,
                          q_dtype: str = "bfloat16",
                          variant: Optional[str] = None) -> KernelSpec:
    """KernelSpec for one ``decode_attention_pallas`` call.

    Contiguous layout: pass ``kv_len`` (the cache max_length; the pool
    is the identity-table view ``(b*chunks, bk, hkv*d)``).  Paged: pass
    ``block_len`` + ``max_blocks`` (+ ``num_blocks``, default the
    serving engine's ``num_slots*max_blocks + 1`` null-block pool).
    ``quantized`` adds the two f32 scale operands; contiguous int8 pins
    the KV chunk to the scale granule (``n_granules`` — the
    init_kv_cache layout).  Alignment/granule violations are RECORDED
    in ``dims`` for the rules to flag (the kernel would raise at call
    time; the pre-flight's job is to say so beforehand) — only shapes
    with no expressible geometry raise :class:`KernelSpecError`.

    Mesh-sharded callers (the shard_map fast path) must pass PER-SHARD
    geometry — ``hq/mp`` and ``hkv/mp`` heads — and tag ``variant``
    with an ``mpN-shard`` suffix: under ``shard_map`` each shard runs
    its own kernel instance, so whole-model head counts would overstate
    VMEM by the mp degree (BASELINE.md "Rejection-sampling accounting
    conventions")."""
    if hkv == 0 or hq % hkv:
        raise KernelSpecError(
            f"q heads ({hq}) must be a multiple of kv heads ({hkv})")
    g = hq // hkv
    if g > _limits.MAX_Q_ROWS:
        raise KernelSpecError(f"GQA group size {g} > {_limits.MAX_Q_ROWS}")
    if s > _limits.MAX_Q_LEN:
        raise KernelSpecError(f"q_len {s} > {_limits.MAX_Q_LEN}")
    if d > _limits.MAX_HEAD_DIM:
        raise KernelSpecError(f"head_dim {d} > {_limits.MAX_HEAD_DIM}")

    paged = block_len is not None
    lanes_128 = []
    dims: Dict[str, object] = {}
    if paged:
        if max_blocks is None:
            raise KernelSpecError("paged spec needs max_blocks")
        bk = int(block_len)
        kv_len = bk * int(max_blocks)
        chunks = int(max_blocks)
        n_pool = int(num_blocks or b * max_blocks + 1)
        lanes_128.append(("block_len", bk))
        dims["block_len"] = bk
    else:
        if kv_len is None:
            raise KernelSpecError("contiguous spec needs kv_len")
        kv_len = int(kv_len)
        if quantized:
            ng = int(n_granules or 1)
            bk = max(1, kv_len // ng)
            dims["scale_granule"] = bk
            dims["scale_granules"] = ng
            lanes_128.append(("scale_granule", bk))
        else:
            from ..ops.pallas.decode_attention import _pick_block_kv
            if block_kv is None:
                from .. import flags as _flags
                block_kv = int(_flags.flag("decode_attention_block_kv"))
            bk = _pick_block_kv(kv_len, int(block_kv))
            if not bk:
                raise KernelSpecError(
                    f"max_length {kv_len} has no 128-aligned chunk "
                    f"divisor <= {block_kv}")
        chunks = max(1, kv_len // bk)
        n_pool = b * chunks

    # the kernel's own tiling arithmetic, verbatim
    bq = min(s, max(1, _limits.MAX_Q_ROWS // g))
    nq = -(-s // bq)
    tile_p = max(8, -(-(bq * g) // 8) * 8)
    kv_dtype = "int8" if quantized else q_dtype

    pos_hi = max(0, kv_len - s)
    scalars = (
        ScalarOperand("pos", (b,), 0, pos_hi),
        # every entry a valid pool index; dead-tail columns are
        # null-filled (block 0) — live rows must never dereference them
        ScalarOperand("bt", (b, chunks), 0, max(0, n_pool - 1)),
    )

    def expected_last(p: int, q: int) -> int:
        # last chunk holding a key visible to ANY row of q tile q at
        # row position p — the kernel's `last_live`, clamped to the grid
        return min(chunks - 1, (p + min((q + 1) * bq, s) - 1) // bk)

    def q_idx(grid_ivs, sc):
        bi, qi, ki = grid_ivs
        return (bi, Iv.const(0), qi, Iv.const(0))

    def kv_idx(grid_ivs, sc):
        bi, qi, ki = grid_ivs
        pos = sc.lookup("pos", bi)
        last = (pos + iv_min((qi + 1) * bq, Iv.const(s)) - 1) // bk
        col = iv_min(ki, last)
        blk = sc.lookup("bt", bi, col)
        return (blk, Iv.const(0), Iv.const(0))

    def sc_idx(grid_ivs, sc):
        bi, qi, ki = grid_ivs
        pos = sc.lookup("pos", bi)
        last = (pos + iv_min((qi + 1) * bq, Iv.const(s)) - 1) // bk
        col = iv_min(ki, last)
        blk = sc.lookup("bt", bi, col)
        return (blk, Iv.const(0))

    clamp = ClampCheck(table="bt", pin_scalar="pos", pin_axis=1,
                       expected=expected_last)
    # streamed-bytes model: per (bi, qi) the clamp's DMA elision fetches
    # only the tile's live prefix; the worst case (pos at its declared
    # max) is the committed per-step bound
    kv_fetches = b * sum(expected_last(pos_hi, q) + 1 for q in range(nq))
    q_fetches = b * nq

    q_block = (1, hkv, tile_p, d)
    q_array = (b, hkv, nq * tile_p, d)
    kv_block = (1, bk, hkv * d)
    kv_array = (n_pool, bk, hkv * d)
    operands = [
        BlockOperand("q", q_block, q_array, q_dtype, q_idx,
                     sublane_padded=True, fetches=q_fetches),
        BlockOperand("k", kv_block, kv_array, kv_dtype, kv_idx,
                     fetches=kv_fetches, kv_stream=True, clamp=clamp),
        BlockOperand("v", kv_block, kv_array, kv_dtype, kv_idx,
                     fetches=kv_fetches, kv_stream=True, clamp=clamp),
    ]
    if quantized:
        operands += [
            BlockOperand("k_scale", (1, hkv), (n_pool, hkv), "float32",
                         sc_idx, fetches=kv_fetches, kv_stream=True,
                         clamp=clamp),
            BlockOperand("v_scale", (1, hkv), (n_pool, hkv), "float32",
                         sc_idx, fetches=kv_fetches, kv_stream=True,
                         clamp=clamp),
        ]
    operands.append(
        BlockOperand("out", q_block, q_array, q_dtype, q_idx,
                     sublane_padded=True, fetches=q_fetches))

    scratch = (((hkv, tile_p, d), "float32"),
               ((hkv, tile_p, _limits.LANES), "float32"),
               ((hkv, tile_p, _limits.LANES), "float32"))

    dims.update({
        "b": b, "s": s, "g": g, "hkv": hkv, "d": d, "bq": bq, "nq": nq,
        "tile_p": tile_p, "bk": bk, "chunks": chunks, "kv_len": kv_len,
        "paged": paged, "quantized": quantized,
        "lane_slice": (d, hkv), "lanes_128": tuple(lanes_128),
    })
    spec = KernelSpec(
        op="decode_attention", grid=(b, nq, chunks),
        variant=variant or (f"{'paged' if paged else 'contiguous'}"
                            f"{'+int8' if quantized else ''},s={s}"),
        operands=tuple(operands), scratch=scratch, scalars=scalars,
        dims=dims)
    # the quantized variants' streamed-bytes claim rides the bf16 twin:
    # same fetch pattern, bf16 payload, no scale rows
    kvb = kv_streamed_bytes(spec)
    bf16 = kv_fetches * 2 * bk * hkv * d * DTYPE_BYTES["bfloat16"]
    dims["kv_streamed_bytes"] = kvb
    dims["kv_streamed_bytes_bf16_equiv"] = bf16
    return spec


# ---------------------------------------------------------------------------
# flash_attention forward (ops/pallas/flash_attention.py)
# ---------------------------------------------------------------------------

def flash_attention_spec(b: int, hq: int, hkv: int, sq: int, skv: int,
                         d: int, *, dtype: str = "bfloat16",
                         variant: Optional[str] = None) -> KernelSpec:
    """KernelSpec for the flash-attention forward kernel (the prefill
    path): grid ``(b, hq, sq//bq, skv//bk)``, GQA folded into the K/V
    index maps (``h // g`` — grouped KV is never broadcast in HBM)."""
    if hkv == 0 or hq % hkv:
        raise KernelSpecError(
            f"q heads ({hq}) must be a multiple of kv heads ({hkv})")
    g = hq // hkv
    from ..ops.pallas.flash_attention import _block_sizes
    bq, bk = _block_sizes(sq, skv, d)
    if sq % bq or skv % bk:
        raise KernelSpecError(
            f"flash kernel needs seq divisible by block ({sq}%{bq}, "
            f"{skv}%{bk})")

    def q_idx(grid_ivs, sc):
        b_, h, qi, ki = grid_ivs
        return (b_, h, qi, Iv.const(0))

    def kv_idx(grid_ivs, sc):
        b_, h, qi, ki = grid_ivs
        return (b_, h // g, ki, Iv.const(0))

    operands = (
        BlockOperand("q", (1, 1, bq, d), (b, hq, sq, d), dtype, q_idx),
        BlockOperand("k", (1, 1, bk, d), (b, hkv, skv, d), dtype, kv_idx,
                     kv_stream=True),
        BlockOperand("v", (1, 1, bk, d), (b, hkv, skv, d), dtype, kv_idx,
                     kv_stream=True),
        BlockOperand("out", (1, 1, bq, d), (b, hq, sq, d), dtype, q_idx),
        BlockOperand("lse", (1, 1, bq, _limits.LANES),
                     (b, hq, sq, _limits.LANES), "float32", q_idx),
    )
    scratch = (((bq, d), "float32"),
               ((bq, _limits.LANES), "float32"),
               ((bq, _limits.LANES), "float32"))
    dims = {"b": b, "g": g, "hkv": hkv, "d": d, "bq": bq, "bk": bk,
            "lanes_128": (("block_kv", bk),),
            "sublanes_8": (("block_q", bq),)}
    return KernelSpec(
        op="flash_attention", variant=variant or f"fwd,sq={sq},skv={skv}",
        grid=(b, hq, sq // bq, skv // bk), operands=operands,
        scratch=scratch, dims=dims)


# ---------------------------------------------------------------------------
# int8_matmul (ops/pallas/int8_matmul.py)
# ---------------------------------------------------------------------------

def int8_matmul_spec(rows: int, k: int, n: int, *,
                     x_dtype: str = "bfloat16",
                     block_k: Optional[int] = None,
                     block_n: Optional[int] = None,
                     variant: Optional[str] = None) -> KernelSpec:
    """KernelSpec for the weight-only-int8 GEMM: grid (N blocks,
    K blocks) with the f32 accumulator persisting over the K walk."""
    rows_p = max(8, -(-rows // 8) * 8)
    if rows_p > _limits.MAX_GEMM_ROWS:
        raise KernelSpecError(
            f"decode-shaped kernel: row count {rows} > "
            f"{_limits.MAX_GEMM_ROWS}")
    from ..ops.pallas.int8_matmul import _pick
    bk = int(block_k or _pick(k, 2048))
    bn = int(block_n or _pick(n, 512))

    def x_idx(grid_ivs, sc):
        ni, ki = grid_ivs
        return (Iv.const(0), ki)

    def w_idx(grid_ivs, sc):
        ni, ki = grid_ivs
        return (ki, ni)

    def n_idx(grid_ivs, sc):
        ni, ki = grid_ivs
        return (Iv.const(0), ni)

    operands = (
        BlockOperand("x", (rows_p, bk), (rows_p, k), x_dtype, x_idx),
        BlockOperand("w8", (bk, bn), (k, n), "int8", w_idx),
        BlockOperand("scale", (1, bn), (1, n), "float32", n_idx),
        BlockOperand("out", (rows_p, bn), (rows_p, n), x_dtype, n_idx),
    )
    dims = {"rows": rows, "rows_p": rows_p, "k": k, "n": n,
            "bk": bk, "bn": bn, "lanes_128": (("K", k), ("N", n))}
    return KernelSpec(
        op="int8_matmul", variant=variant or f"rows={rows},k={k},n={n}",
        grid=(max(1, n // bn), max(1, k // bk)), operands=operands,
        scratch=(((rows_p, bn), "float32"),), dims=dims)


# ---------------------------------------------------------------------------
# rms_norm (ops/pallas/rms_norm.py)
# ---------------------------------------------------------------------------

def rms_norm_spec(rows: int, d: int, *, dtype: str = "bfloat16",
                  weight: bool = True,
                  variant: Optional[str] = None) -> KernelSpec:
    """KernelSpec for the row-resident RMSNorm kernel: 1-D grid over
    row blocks; the weight row's constant index map means Pallas elides
    its re-fetch after the first step (fetches=1)."""
    from ..ops.pallas.rms_norm import _pick_block_rows
    br = _pick_block_rows(rows, d)

    def x_idx(grid_ivs, sc):
        (i,) = grid_ivs
        return (i, Iv.const(0))

    def w_idx(grid_ivs, sc):
        return (Iv.const(0), Iv.const(0))

    operands = [
        BlockOperand("x", (br, d), (rows, d), dtype, x_idx),
        BlockOperand("out", (br, d), (rows, d), dtype, x_idx),
    ]
    if weight:
        operands.insert(
            1, BlockOperand("weight", (1, d), (1, d), dtype, w_idx,
                            fetches=1))
    dims = {"rows": rows, "d": d, "br": br, "lanes_128": (("d", d),)}
    return KernelSpec(
        op="rms_norm", variant=variant or f"rows={rows},d={d}",
        grid=(max(1, rows // br),), operands=tuple(operands), dims=dims)


# ---------------------------------------------------------------------------
# the registry sweep
# ---------------------------------------------------------------------------

def registered_kernel_specs() -> List[KernelSpec]:
    """One representative TPU-scale spec per registered kernel entry
    point — the shapes the committed benches measured (serving head
    geometry 32/8/128, kv_len 8192, 128-token paged blocks).  The CLI's
    ``--kernels`` sweep and the guard test require every one of these
    to pre-flight clean."""
    out = [
        decode_attention_spec(8, 1, 32, 8, 128, kv_len=8192,
                              variant="contiguous,decode"),
        decode_attention_spec(8, 1, 32, 8, 128, kv_len=8192,
                              quantized=True, n_granules=8192 // 128,
                              variant="contiguous+int8,decode"),
        decode_attention_spec(8, 1, 32, 8, 128, block_len=128,
                              max_blocks=64, variant="paged,decode"),
        decode_attention_spec(8, 1, 32, 8, 128, block_len=128,
                              max_blocks=64, quantized=True,
                              variant="paged+int8,decode"),
        # the q-tiled modes: a chunked-prefill q chunk and the
        # speculative verify window, through the same kernel
        decode_attention_spec(1, 256, 32, 8, 128, block_len=128,
                              max_blocks=64,
                              variant="paged,chunked_prefill"),
        decode_attention_spec(8, 5, 32, 8, 128, block_len=128,
                              max_blocks=64, quantized=True,
                              variant="paged+int8,spec_verify"),
        flash_attention_spec(1, 32, 8, 2048, 2048, 128),
        int8_matmul_spec(8, 4096, 4096),
        rms_norm_spec(256, 4096),
    ]
    return out
