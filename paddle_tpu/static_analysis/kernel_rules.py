"""Kernel pre-flight rules — static VMEM/bounds/alignment analysis of
:class:`~paddle_tpu.static_analysis.kernel_registry.KernelSpec`s.

Each rule takes one spec and returns structured
:class:`~paddle_tpu.static_analysis.core.Finding`s (the same dataclass
the graph lint and mesh pre-flight emit, so the CLI/engine/bench wiring
is shared).  Nothing here compiles or touches a device: the rules walk
the declared grid, block shapes, index maps (over integer intervals),
and scalar-prefetch value ranges.

Rules (BASELINE.md "Kernel pre-flight conventions"):

  * ``kernel-vmem`` — per-grid-step footprint (streamed operand tiles
    x2 for DMA double-buffering + scratch) vs
    ``FLAGS_kernel_lint_vmem_bytes`` (default 16 MiB/core);
  * ``kernel-bounds`` — interval evaluation of every index map over the
    full grid domain: block indices within the array, scalar-prefetch
    accesses within the operand shape, and the dead-tail ClampCheck
    corners (unclamped = dead-tail DMA streaming null (block 0)
    entries; over-clamped = live KV silently truncated);
  * ``kernel-align`` — array%block divisibility, last-dim %128 lanes,
    second-minor sublane multiples per dtype, declared 128-lane dims
    (paged block_len, flash block_kv), and the head-slice layout
    (hkv*d last dim with d not lane-aligned straddles lane tiles);
  * ``kernel-scale-granule`` — contiguous-int8 scale granule must tile
    the cache length, be 128-aligned, and agree with the KV chunk;
  * ``kernel-stream`` — the quantized KV streamed-bytes model vs the
    committed int8_serving claim (<= 0.55x the bf16-equivalent bytes).

``dispatch_agreement_findings`` is satellite 1's lint: sweep a shape
lattice and fail if ``ops.attention.decode_shape_gate`` would route a
shape to the Pallas kernel that ``decode_kernel_rejects`` refuses.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from .. import flags as _flags
from ..ops.pallas import limits as _limits
from . import core
from . import kernel_registry as _kr

__all__ = ["KernelRule", "KernelVmemRule", "KernelBoundsRule",
           "KernelAlignRule", "KernelScaleGranuleRule",
           "KernelStreamRule", "default_kernel_rules",
           "analyze_kernels", "kernel_report",
           "dispatch_agreement_findings", "STREAM_RATIO_BOUND"]

# committed int8_serving claim: quantized KV moves <= 0.55x the bytes of
# the bf16 cache for the same fetch pattern (int8 payload + f32 scale
# rows; the +0.05 covers the per-block scale overhead at block_len 128)
STREAM_RATIO_BOUND = 0.55

_SEVERITY_ORDER = {"error": 0, "warning": 1}


def _sort(findings: List[core.Finding]) -> List[core.Finding]:
    # identical key to static_analysis._sort_findings so merged
    # graph+kernel output stays deterministic under one ordering
    return sorted(findings, key=lambda f: (
        _SEVERITY_ORDER.get(f.severity, 2), f.rule, f.path,
        -1 if f.bytes is None else -int(f.bytes), f.message))


class KernelRule:
    """Base: ``name``/``severity`` class attrs + ``run(spec)``."""

    name = "kernel-rule"
    severity = "error"

    def run(self, spec: _kr.KernelSpec) -> List[core.Finding]:
        raise NotImplementedError


@dataclasses.dataclass
class KernelVmemRule(KernelRule):
    """Per-grid-step VMEM footprint must fit the per-core budget."""

    budget_bytes: Optional[int] = None
    name = "kernel-vmem"
    severity = "error"

    def run(self, spec):
        budget = self.budget_bytes
        if budget is None:
            budget = int(_flags.flag("kernel_lint_vmem_bytes"))
        total = _kr.vmem_footprint(spec)
        if total <= budget:
            return []
        return [core.Finding(
            rule=self.name, severity=self.severity, path=spec.path,
            message=(f"per-grid-step VMEM footprint {total} bytes "
                     f"exceeds the {budget}-byte per-core budget "
                     f"(FLAGS_kernel_lint_vmem_bytes); shrink block_kv "
                     f"or the q tile"),
            bytes=int(total))]


@dataclasses.dataclass
class KernelBoundsRule(KernelRule):
    """Interval-evaluate every index map over the full grid domain and
    the declared scalar ranges; run the dead-tail ClampCheck corners."""

    name = "kernel-bounds"
    severity = "error"

    def _eval(self, spec, op, pins, out: List[core.Finding],
              seen: set) -> None:
        env = _kr.ScalarEnv(spec.scalars, pins=pins)
        grid_ivs = []
        for d, g in enumerate(spec.grid):
            pin = pins.get(("grid", d)) if pins else None
            grid_ivs.append(_kr.iv(pin) if pin is not None
                            else _kr.Iv(0, max(0, int(g) - 1)))
        idx = op.index_map(tuple(grid_ivs), env)
        # every returned block index must land inside the array
        for d, (span, blk, arr) in enumerate(
                zip(idx, op.block_shape, op.array_shape)):
            span = _kr.iv(span)
            hi = max(0, arr // blk - 1)
            if span.lo < 0 or span.hi > hi:
                msg = (f"operand '{op.name}' dim {d}: index map spans "
                       f"[{span.lo}, {span.hi}] outside block range "
                       f"[0, {hi}] of array shape {op.array_shape}")
                if msg not in seen:
                    seen.add(msg)
                    out.append(core.Finding(
                        rule=self.name, severity=self.severity,
                        path=spec.path, message=msg))
        # every recorded scalar-prefetch access must be in-shape
        sc_shapes = {s.name: s.shape for s in spec.scalars}
        for sc_name, access in env.accesses:
            shape = sc_shapes[sc_name]
            for d, span in enumerate(access):
                hi = max(0, shape[d] - 1)
                if span.lo < 0 or span.hi > hi:
                    msg = (f"operand '{op.name}': scalar-prefetch "
                           f"'{sc_name}' dim {d} access "
                           f"[{span.lo}, {span.hi}] outside shape "
                           f"{shape}")
                    if msg not in seen:
                        seen.add(msg)
                        out.append(core.Finding(
                            rule=self.name, severity=self.severity,
                            path=spec.path, message=msg))

    def _clamp_corners(self, spec, op, out, seen) -> None:
        cl = op.clamp
        sc = {s.name: s for s in spec.scalars}[cl.pin_scalar]
        table = {s.name: s for s in spec.scalars}[cl.table]
        for p in {sc.lo, sc.hi}:
            for q in {0, max(0, spec.grid[cl.pin_axis] - 1)}:
                env = _kr.ScalarEnv(spec.scalars, pins={cl.pin_scalar: p})
                grid_ivs = []
                for d, g in enumerate(spec.grid):
                    grid_ivs.append(_kr.iv(q) if d == cl.pin_axis
                                    else _kr.Iv(0, max(0, int(g) - 1)))
                op.index_map(tuple(grid_ivs), env)
                cols = [a for name, a in env.accesses if name == cl.table]
                if not cols:
                    msg = (f"operand '{op.name}': declared ClampCheck "
                           f"on table '{cl.table}' but the index map "
                           f"never dereferences it")
                    if msg not in seen:
                        seen.add(msg)
                        out.append(core.Finding(
                            rule=self.name, severity=self.severity,
                            path=spec.path, message=msg))
                    continue
                want = int(cl.expected(p, q))
                got = max(a[-1].hi for a in cols)
                if got > want:
                    msg = (f"operand '{op.name}': unclamped table "
                           f"dereference — '{cl.table}' column reaches "
                           f"{got} past last live block {want} at "
                           f"pos={p}; the dead tail streams, and its "
                           f"null-filled (block 0) entries would alias "
                           f"pad data into live rows")
                elif got < want:
                    msg = (f"operand '{op.name}': over-clamped table "
                           f"dereference — '{cl.table}' column tops out "
                           f"at {got} below last live block {want} at "
                           f"pos={p}; live KV is silently truncated")
                else:
                    continue
                if msg not in seen:
                    seen.add(msg)
                    out.append(core.Finding(
                        rule=self.name, severity=self.severity,
                        path=spec.path, message=msg))

    def run(self, spec):
        out: List[core.Finding] = []
        seen: set = set()
        for op in spec.operands:
            self._eval(spec, op, {}, out, seen)
            if op.clamp is not None:
                self._clamp_corners(spec, op, out, seen)
        return out


@dataclasses.dataclass
class KernelAlignRule(KernelRule):
    """Tiling lint: array%block divisibility, %128-lane last dims,
    per-dtype sublane multiples, and declared lane-critical dims."""

    name = "kernel-align"
    severity = "error"

    def run(self, spec):
        out: List[core.Finding] = []
        for op in spec.operands:
            for d, (blk, arr) in enumerate(
                    zip(op.block_shape, op.array_shape)):
                if blk <= 0 or arr % blk:
                    out.append(core.Finding(
                        rule=self.name, severity=self.severity,
                        path=spec.path,
                        message=(f"operand '{op.name}' dim {d}: block "
                                 f"{blk} does not tile array dim "
                                 f"{arr}")))
            last_b, last_a = op.block_shape[-1], op.array_shape[-1]
            if last_b % _limits.LANES and last_b != last_a:
                out.append(core.Finding(
                    rule=self.name, severity=self.severity,
                    path=spec.path,
                    message=(f"operand '{op.name}': last block dim "
                             f"{last_b} is not a multiple of "
                             f"{_limits.LANES} lanes")))
            if len(op.block_shape) >= 2 and not op.sublane_padded:
                sub_b = op.block_shape[-2]
                sub_a = op.array_shape[-2]
                sl = _limits.sublanes(op.dtype)
                # a 1-row block (the int8 scale rows) is a degenerate
                # tile Mosaic pads internally; the lint targets
                # multi-row blocks that straddle sublane tiles
                if sub_b > 1 and sub_b % sl and sub_b != sub_a:
                    out.append(core.Finding(
                        rule=self.name, severity=self.severity,
                        path=spec.path,
                        message=(f"operand '{op.name}': second-minor "
                                 f"block dim {sub_b} is not a multiple "
                                 f"of the {op.dtype} sublane tile "
                                 f"{sl}")))
        for label, v in spec.dims.get("lanes_128", ()):
            if int(v) % _limits.LANES:
                out.append(core.Finding(
                    rule=self.name, severity=self.severity,
                    path=spec.path,
                    message=(f"{label} {v} is not 128-aligned "
                             f"(lane-width DMA granularity)")))
        for label, v in spec.dims.get("sublanes_8", ()):
            if int(v) % 8:
                out.append(core.Finding(
                    rule=self.name, severity=self.severity,
                    path=spec.path,
                    message=f"{label} {v} is not a multiple of 8 rows"))
        lane_slice = spec.dims.get("lane_slice")
        if lane_slice is not None:
            d, hkv = lane_slice
            if hkv > 1 and int(d) % _limits.LANES:
                out.append(core.Finding(
                    rule=self.name, severity=self.severity,
                    path=spec.path,
                    message=(f"head_dim {d} with {hkv} kv heads folded "
                             f"into the last dim: per-head slices "
                             f"straddle {_limits.LANES}-lane tiles "
                             f"(misaligned head_dim)")))
        return out


@dataclasses.dataclass
class KernelScaleGranuleRule(KernelRule):
    """Contiguous-int8 scale layout must agree with the KV chunking:
    granule x granules == cache length, granule 128-aligned, and equal
    to the kernel's KV chunk (one scale row per streamed chunk)."""

    name = "kernel-scale-granule"
    severity = "error"

    def run(self, spec):
        gran = spec.dims.get("scale_granule")
        if gran is None:
            return []
        out: List[core.Finding] = []
        ng = int(spec.dims.get("scale_granules", 0))
        kv_len = int(spec.dims.get("kv_len", 0))
        bk = int(spec.dims.get("bk", 0))
        gran = int(gran)
        if gran * ng != kv_len:
            out.append(core.Finding(
                rule=self.name, severity=self.severity, path=spec.path,
                message=(f"int8 scale granule {gran} x {ng} granules "
                         f"!= cache length {kv_len}")))
        if gran % _limits.LANES:
            out.append(core.Finding(
                rule=self.name, severity=self.severity, path=spec.path,
                message=(f"int8 scale granule {gran} is not "
                         f"128-aligned")))
        if gran != bk:
            out.append(core.Finding(
                rule=self.name, severity=self.severity, path=spec.path,
                message=(f"int8 scale granule {gran} disagrees with "
                         f"the KV chunk {bk}: dequant would mix "
                         f"granules inside one streamed block")))
        return _sort(out)


@dataclasses.dataclass
class KernelStreamRule(KernelRule):
    """Quantized decode kernels must honour the committed int8_serving
    streamed-bytes claim: KV-side bytes <= STREAM_RATIO_BOUND x the
    bf16-equivalent bytes for the same fetch pattern."""

    max_ratio: Optional[float] = None
    name = "kernel-stream"
    severity = "error"

    def run(self, spec):
        if not spec.dims.get("quantized"):
            return []
        bound = self.max_ratio if self.max_ratio is not None \
            else STREAM_RATIO_BOUND
        kvb = int(spec.dims.get("kv_streamed_bytes", 0))
        bf16 = int(spec.dims.get("kv_streamed_bytes_bf16_equiv", 0))
        if bf16 <= 0 or kvb <= bound * bf16:
            return []
        return [core.Finding(
            rule=self.name, severity=self.severity, path=spec.path,
            message=(f"quantized KV streams {kvb} bytes = "
                     f"{kvb / bf16:.3f}x the bf16-equivalent {bf16} "
                     f"bytes, above the committed int8_serving bound "
                     f"{bound}x (scale layout too fat per token?)"),
            bytes=int(kvb))]


def default_kernel_rules() -> Tuple[KernelRule, ...]:
    return (KernelVmemRule(), KernelBoundsRule(), KernelAlignRule(),
            KernelScaleGranuleRule(), KernelStreamRule())


def analyze_kernels(specs: Sequence[_kr.KernelSpec],
                    rules: Optional[Sequence[KernelRule]] = None
                    ) -> List[core.Finding]:
    """Run every kernel rule over every spec; deterministic order."""
    if rules is None:
        rules = default_kernel_rules()
    out: List[core.Finding] = []
    for spec in specs:
        for rule in rules:
            out.extend(rule.run(spec))
    return _sort(out)


def kernel_report(spec: _kr.KernelSpec,
                  rules: Optional[Sequence[KernelRule]] = None
                  ) -> Dict[str, object]:
    """Per-kernel JSON-able report — the bench/CLI row payload."""
    findings = analyze_kernels([spec], rules=rules)
    return {
        "op": spec.op,
        "variant": spec.variant,
        "vmem_bytes": int(_kr.vmem_footprint(spec)),
        "streamed_bytes": int(_kr.streamed_bytes(spec)),
        "findings": [f.as_dict() for f in findings],
    }


# ---------------------------------------------------------------------------
# satellite 1: dispatch <-> kernel agreement
# ---------------------------------------------------------------------------

def _default_shape_lattice() -> List[Dict[str, object]]:
    # a small lattice over the dims the gates actually read: q_len
    # (decode / spec-verify / chunk / whole-prefill edge), GQA group,
    # head_dim, cache length alignment, paged block_len
    shapes: List[Dict[str, object]] = []
    for s in (1, 5, 256, _limits.MAX_Q_LEN):
        for hq, hkv in ((32, 8), (64, 1), (8, 8)):
            for d in (64, 128, _limits.MAX_HEAD_DIM):
                for kv_len in (4096, 8192):
                    shapes.append(dict(b=4, s=s, hq=hq, hkv=hkv, d=d,
                                       kv_len=kv_len))
                    shapes.append(dict(b=4, s=s, hq=hq, hkv=hkv, d=d,
                                       kv_len=kv_len,
                                       paged_block_len=128))
    return shapes


def dispatch_agreement_findings(shapes=None) -> List[core.Finding]:
    """Satellite-1 lint: for every lattice shape the dispatch gate
    routes to the Pallas kernel, the kernel spec must accept it (and
    quantized twins of the contiguous shapes with the standard
    128-token scale granule).  A disagreement is a routing bug — a
    runtime NotImplementedError waiting on the serving hot path."""
    from ..ops.attention import decode_shape_gate
    if shapes is None:
        shapes = _default_shape_lattice()
    out: List[core.Finding] = []
    for sh in shapes:
        b = int(sh.get("b", 1))
        s, hq, hkv, d = (int(sh["s"]), int(sh["hq"]), int(sh["hkv"]),
                         int(sh["d"]))
        kv_len = int(sh["kv_len"])
        pbl = sh.get("paged_block_len")
        path, why = decode_shape_gate(s, hq, hkv, d, kv_len,
                                      paged_block_len=pbl)
        quant_arms = [(False, None)]
        if kv_len % _limits.LANES == 0:
            quant_arms.append((True, kv_len // _limits.LANES))
        for quantized, ng in quant_arms:
            if pbl is not None and quantized:
                ng = None
            reject = _kr.decode_kernel_rejects(
                b, s, hq, hkv, d, kv_len, paged_block_len=pbl,
                quantized=quantized, n_granules=ng)
            if path == "pallas_decode" and reject is not None:
                out.append(core.Finding(
                    rule="kernel-dispatch", severity="error",
                    path=f"decode_attention[{sh}]",
                    message=(f"dispatch routes this shape to the Pallas "
                             f"kernel but the kernel spec rejects it: "
                             f"{reject}")))
            elif path != "pallas_decode" and reject is None and \
                    why.startswith(("GQA", "q_len", "head_dim",
                                    "q heads", "paged block_len",
                                    "max_length")):
                # shape-gate refusals only; environment refusals
                # (mesh trace, min_len, masks) are not disagreements
                out.append(core.Finding(
                    rule="kernel-dispatch", severity="error",
                    path=f"decode_attention[{sh}]",
                    message=(f"dispatch refuses a shape the kernel "
                             f"accepts ({why}): perf left on the "
                             f"floor")))
    return _sort(out)
