"""Mesh pre-flight rules + cost models (ISSUE 8).

The graph-lint suite (rules.py) checks one-device programs; this module
checks the program's *mesh story* before any multi-chip compile — the
three classes of silent SPMD disaster plus the two numbers a capacity
plan needs:

  * **replication-blowup** (error) — a step operand big enough to
    matter, fully replicated along a mesh axis it could shard (a KV
    cache or weight replicated over ``mp`` multiplies its HBM by the
    axis size);
  * **resharding-hazard** (warning) — a ``with_sharding_constraint``
    conflicting with the operand's propagated sharding: GSPMD obeys it
    by inserting a cross-device reshard on the hot path;
  * **collective-deadlock** (error) — the collective-order lint
    (distributed/lint.py) folded into the rules framework: cond
    branches with different collective sequences or axis sets, and
    while-loop predicates that can diverge across ranks;
  * :func:`comm_report` — Megatron-style per-axis communication
    accounting: explicit collectives in the trace (shard_map programs)
    plus the psums GSPMD must insert for dot_generals whose contracted
    dimension is sharded, plus resharding transfers, each costed in
    bytes per step per mesh axis;
  * :func:`estimate_peak_hbm` — donation-aware liveness over the
    top-level eqn buffer lifetimes, yielding predicted peak bytes per
    device given the shardings.  Cross-checked against
    ``ServingEngine.cache_hbm_bytes`` by the engines' pre-flight.

Everything here consumes ONE abstract trace (a
:class:`~.core.MeshLintContext`); no devices, no compile.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from .. import flags as _flags
from . import core
from .rules import Rule

__all__ = ["COLLECTIVE_PRIMS", "collective_sig", "walk_collectives",
           "CollectiveDeadlockRule", "ReplicationBlowupRule",
           "ReshardingHazardRule", "default_mesh_rules",
           "collective_cost_bytes",
           "comm_report", "estimate_peak_hbm"]


# primitive names that lower to cross-replica communication.  jax renames
# these across versions — matching goes through the shared core.CANONICAL
# table instead of pinning one release's strings.  The replication
# *casts* ("pbroadcast" on 0.4.x, "pvary" on vma jax) move no data and
# are deliberately absent.
COLLECTIVE_PRIMS = {
    "psum", "psum_invariant", "pmax", "pmin", "all_gather",
    "all_to_all", "ppermute", "reduce_scatter", "psum_scatter", "pgather",
}
COLLECTIVE_PRIMS |= set(core.CANONICAL)

# params that (a) are not sub-jaxprs and (b) identify the collective
_ID_PARAMS = ("axes", "axis_name", "axis_index_groups", "perm",
              "all_gather_dimension", "scatter_dimension", "split_axis",
              "concat_axis", "tiled")


def collective_sig(eqn) -> Tuple:
    """(canonical name, identifying params, input shapes) — the schedule
    entry tests pin and branch comparison matches on.  Axis SETS are part
    of the identity: a psum over ``mp`` in one branch and over ``dp`` in
    the other is a cross-rank mismatch even though the op name agrees."""
    params = {k: v for k, v in eqn.params.items() if k in _ID_PARAMS}
    shapes = tuple(getattr(v.aval, "shape", ()) for v in eqn.invars)
    name = core.canonical_name(eqn.primitive.name)
    return (name, tuple(sorted(
        (k, str(v)) for k, v in params.items())), shapes)


def _uses_axis_index(jaxpr) -> bool:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "axis_index":
            return True
        for _, sub in core.sub_jaxprs(eqn):
            if _uses_axis_index(sub):
                return True
    return False


def walk_collectives(jaxpr, path: str = "",
                     schedule: Optional[List] = None,
                     violations: Optional[List] = None
                     ) -> Tuple[List, List]:
    """Extract the ordered collective schedule and the rank-divergence
    violations from a jaxpr (recursing through pjit/shard_map/scan/
    cond/while/remat sub-jaxprs).

    schedule: [(path, sig)] in program order — identical for every rank
    on the straight-line path.  violations: [(path, message)] for the
    control-flow patterns that can deadlock on hardware:

      * ``lax.cond`` branches issuing different collective sequences
        (order, identifying params, or axis sets);
      * a collective inside a ``lax.while_loop`` predicate (ranks can
        disagree on the final failing evaluation);
      * collectives in a while body whose predicate reads
        ``axis_index`` (a statically-visible rank-divergent trip
        count).
    """
    schedule = [] if schedule is None else schedule
    violations = [] if violations is None else violations
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            schedule.append((path, collective_sig(eqn)))
            continue
        if name == "cond":
            # every branch must issue the SAME collective sequence: the
            # predicate may be rank-divergent, so any difference is a
            # potential cross-rank deadlock
            branch_scheds = []
            for i, (_, sub) in enumerate(core.sub_jaxprs(eqn)):
                s: List = []
                walk_collectives(sub, f"{path}/cond.branch{i}", s,
                                 violations)
                branch_scheds.append([sig for _, sig in s])
                schedule.extend(s)
            if len({tuple(map(repr, b)) for b in branch_scheds}) > 1:
                violations.append((path, (
                    f"lax.cond branches issue different collective "
                    f"sequences {branch_scheds} — deadlocks if the "
                    "predicate diverges across ranks")))
            continue
        if name == "while":
            body_colls: List = []
            cond_rank_divergent = False
            for k, sub in core.sub_jaxprs(eqn):
                s: List = []
                walk_collectives(sub, f"{path}/while.{k}", s, violations)
                schedule.extend(s)
                if k == "cond_jaxpr":
                    if s:
                        violations.append((path, (
                            f"collective inside a while_loop predicate "
                            f"({[sig[0] for _, sig in s]}) — ranks can "
                            "disagree on the final (failing) "
                            "evaluation")))
                    if _uses_axis_index(sub):
                        cond_rank_divergent = True
                else:
                    body_colls.extend(s)
            if cond_rank_divergent and body_colls:
                violations.append((path, (
                    "while_loop predicate reads axis_index (a "
                    "rank-divergent trip count) with collectives in the "
                    f"body ({[sig[0] for _, sig in body_colls]}) — ranks "
                    "issue different collective counts")))
            continue
        # transparent containers: pjit, shard_map, scan, remat, custom_*…
        for _, sub in core.sub_jaxprs(eqn):
            walk_collectives(sub, f"{path}/{name}", schedule, violations)
    return schedule, violations


@dataclasses.dataclass
class CollectiveDeadlockRule(Rule):
    """The collective-order lint as a Finding-emitting rule: mismatched
    collective order or axis sets across ``cond`` branches, collectives
    in ``while`` predicates, and rank-divergent while-body collective
    counts.  Works on any LintContext (mesh or not) — the collective
    schedule is a property of the traced program, not of the
    shardings.  ``distributed.lint.check_collective_order`` is now a
    thin shim over :func:`walk_collectives`, so the two surfaces can
    never drift."""

    name = "collective-deadlock"
    severity = "error"

    def run(self, ctx: core.LintContext) -> List[core.Finding]:
        _, violations = walk_collectives(ctx.closed.jaxpr)
        return [self._finding(path, msg) for path, msg in violations]


@dataclasses.dataclass
class ReplicationBlowupRule(Rule):
    """Step operands fully replicated along a mesh axis they could
    shard.  A replicated buffer costs its full bytes on EVERY device of
    that axis — for the KV cache or the weights over ``mp`` that is the
    difference between "the model fits" and an OOM at engine start.

    ``axes`` limits which mesh axes are checked: by default every mesh
    axis EXCEPT ``dp`` (replicating params over dp IS data parallelism;
    replicating anything big over mp/sharding/sep is a blowup).
    ``allow`` matches input-label substrings for buffers that are
    deliberately replicated (rope sin/cos tables: small, read-only,
    sharding them buys nothing)."""

    min_bytes: Optional[int] = None
    axes: Optional[Tuple[str, ...]] = None
    allow: Tuple[str, ...] = ("rope",)

    name = "replication-blowup"
    severity = "error"

    def run(self, ctx: core.LintContext) -> List[core.Finding]:
        if not isinstance(ctx, core.MeshLintContext):
            return []
        thr = (self.min_bytes if self.min_bytes is not None
               else int(_flags.flag("graph_lint_replication_min_bytes")))
        check = (self.axes if self.axes is not None
                 else tuple(a for a in ctx.mesh.names if a != "dp"))
        out: List[core.Finding] = []
        for fi in ctx.inputs:
            b = core.aval_bytes(fi.aval)
            if b is None or b < thr:
                continue
            if any(a in fi.label for a in self.allow):
                continue
            spec = ctx.input_spec(fi)
            used = set(core.spec_axes(spec))
            shape = tuple(getattr(fi.aval, "shape", ()))
            for axis in check:
                n = ctx.mesh.size(axis)
                if n <= 1 or axis in used:
                    continue
                shardable = any(
                    d >= n and d % n == 0
                    for d, e in zip(shape, spec or ((),) * len(shape))
                    if e == ())
                if not shardable:
                    continue
                out.append(self._finding(
                    "",
                    f"input '{fi.label}' ({fi.aval.str_short()}, "
                    f"{b} bytes) is fully replicated along mesh axis "
                    f"'{axis}' ({n}-way) though a dimension divides "
                    f"evenly — every device of that axis keeps the "
                    f"whole buffer, {n}x the HBM a sharded layout "
                    f"needs; add '{axis}' to its PartitionSpec or "
                    f"allowlist a deliberate broadcast",
                    bytes=b))
        return out


@dataclasses.dataclass
class ReshardingHazardRule(Rule):
    """``with_sharding_constraint`` annotations that CONFLICT with the
    operand's propagated sharding: GSPMD honours the constraint by
    materialising a resharding transfer (an all-to-all-shaped data
    movement) right there — silent on a cold path, a per-step tax on a
    hot one.  Only proven conflicts fire: an operand whose spec
    propagation could not establish stays silent."""

    min_bytes: Optional[int] = None

    name = "resharding-hazard"
    severity = "warning"

    def run(self, ctx: core.LintContext) -> List[core.Finding]:
        if not isinstance(ctx, core.MeshLintContext):
            return []
        thr = (self.min_bytes if self.min_bytes is not None
               else int(_flags.flag("graph_lint_reshard_min_bytes")))
        out: List[core.Finding] = []
        for rec in ctx.records:
            if rec.eqn.primitive.name != "sharding_constraint":
                continue
            have = rec.in_specs[0] if rec.in_specs else None
            want = rec.out_specs[0] if rec.out_specs else None
            if have is None or want is None or have == want:
                continue
            av = getattr(rec.eqn.invars[0], "aval", None)
            b = core.aval_bytes(av)
            if b is None or b < thr:
                continue
            out.append(self._finding(
                rec.path,
                f"with_sharding_constraint reshards "
                f"{av.str_short()} from {have} to {want} — GSPMD "
                f"inserts a cross-device transfer here every step; "
                f"align the producer's sharding or drop the "
                f"constraint",
                bytes=b))
        return out


def default_mesh_rules() -> Tuple[Rule, ...]:
    """Fresh instances of the mesh-aware rule set (thresholds read the
    graph-lint flags at run time); run alongside ``default_rules()``
    whenever ``analyze``/``check`` get a ``mesh=``."""
    return (ReplicationBlowupRule(), ReshardingHazardRule(),
            CollectiveDeadlockRule())


# ---------------------------------------------------------------------------
# Collective-cost model
# ---------------------------------------------------------------------------

def collective_cost_bytes(prim: str, nbytes: int, n: int) -> int:
    """Bytes one device moves for a collective over an ``n``-way axis
    group, ring-algorithm accounting (BASELINE.md "Mesh pre-flight
    conventions"): psum/pmax/pmin (all-reduce) 2(n-1)/n·B;
    all_gather (n-1)·B of its per-shard input; reduce_scatter and
    all_to_all (n-1)/n·B; ppermute B (each device forwards its shard
    once)."""
    if n <= 1:
        return 0
    name = core.canonical_name(prim)
    if name in ("psum_invariant", "pmax", "pmin"):
        return int(2 * (n - 1) * nbytes / n)
    if name in ("all_gather", "pgather"):
        return int((n - 1) * nbytes)
    if name in ("reduce_scatter", "psum_scatter", "all_to_all"):
        return int((n - 1) * nbytes / n)
    if name == "ppermute":
        return int(nbytes)
    return int(nbytes)


def _eqn_axes(eqn, mesh: core.MeshInfo) -> Tuple[str, ...]:
    axes = eqn.params.get("axes", None)
    if axes is None:
        axes = eqn.params.get("axis_name", ())
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(a) for a in axes if str(a) in mesh.names)


def _group_size(mesh: core.MeshInfo, axes: Tuple[str, ...]) -> int:
    n = 1
    for a in axes:
        n *= mesh.size(a)
    return n


def comm_report(ctx: core.MeshLintContext) -> Dict[str, Any]:
    """Per-mesh-axis communication accounting for one step of the traced
    program.  Three site kinds:

      * ``collective`` — explicit collectives in the trace (shard_map /
        pmapped code; operand bytes are PER-SHARD, as traced);
      * ``implied_psum`` — a ``dot_general`` whose contracted dimension
        is sharded over an axis: GSPMD completes the partial products
        with an all-reduce of the output over that axis (the
        Megatron-LM row-parallel pattern);
      * ``reshard`` — a proven sharding_constraint conflict (see
        ReshardingHazardRule), costed as an all_to_all of the tensor.

    Sites inside ``scan`` bodies are multiplied by the static trip
    count; ``while`` bodies count once (a documented lower bound).
    """
    mesh = ctx.mesh
    per_axis: Dict[str, Dict[str, Any]] = {
        a: {"bytes_per_step": 0, "collectives": defaultdict(int)}
        for a, n in mesh.axes}
    sites: List[Dict[str, Any]] = []

    def add(kind, path, prim, axes, bytes_moved, count):
        if not axes or bytes_moved <= 0:
            return
        sites.append({"kind": kind, "path": path, "prim": prim,
                      "axes": list(axes),
                      "bytes_per_step": int(bytes_moved * count),
                      "count": int(count)})
        for a in axes:
            per_axis[a]["bytes_per_step"] += int(bytes_moved * count)
            per_axis[a]["collectives"][prim] += int(count)

    # explicit collectives (records cover every region the propagation
    # walker visited, shard_map/scan/while bodies included)
    for rec in ctx.records:
        name = rec.eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            axes = _eqn_axes(rec.eqn, mesh)
            nbytes = sum(core.aval_bytes(getattr(v, "aval", None)) or 0
                         for v in rec.eqn.invars)
            cost = collective_cost_bytes(name, nbytes, _group_size(mesh,
                                                                   axes))
            add("collective", rec.path, core.canonical_name(name), axes,
                cost, rec.multiplier)
        elif name == "dot_general":
            (lc, rc), _ = rec.eqn.params["dimension_numbers"]
            axes: List[str] = []
            for side, dims in ((0, lc), (1, rc)):
                spec = (rec.in_specs[side]
                        if side < len(rec.in_specs) else None)
                if spec is None:
                    continue
                for d in dims:
                    if int(d) < len(spec):
                        axes.extend(a for a in spec[int(d)]
                                    if a not in axes)
            if axes:
                out_b = sum(
                    core.aval_bytes(getattr(v, "aval", None)) or 0
                    for v in rec.eqn.outvars)
                cost = collective_cost_bytes(
                    "psum", out_b, _group_size(mesh, tuple(axes)))
                add("implied_psum", rec.path, "psum_invariant",
                    tuple(axes), cost, rec.multiplier)
        elif name == "sharding_constraint":
            have = rec.in_specs[0] if rec.in_specs else None
            want = rec.out_specs[0] if rec.out_specs else None
            if have is None or want is None or have == want:
                continue
            changed = tuple(sorted(
                set(core.spec_axes(have)) ^ set(core.spec_axes(want))))
            av = getattr(rec.eqn.invars[0], "aval", None)
            b = core.aval_bytes(av) or 0
            cost = collective_cost_bytes(
                "all_to_all", b, _group_size(mesh, changed))
            add("reshard", rec.path, "all_to_all", changed, cost,
                rec.multiplier)

    sites.sort(key=lambda s: (-s["bytes_per_step"], s["path"], s["prim"]))
    for a in per_axis:
        per_axis[a]["collectives"] = dict(per_axis[a]["collectives"])
    return {"per_axis": per_axis,
            "total_bytes_per_step": sum(v["bytes_per_step"]
                                        for v in per_axis.values()),
            "num_sites": len(sites),
            "sites": sites}


# ---------------------------------------------------------------------------
# HBM-liveness estimator
# ---------------------------------------------------------------------------

def estimate_peak_hbm(ctx: core.LintContext) -> Dict[str, Any]:
    """Donation-aware peak-HBM estimate over the top-level eqn buffer
    lifetimes, per device under the propagated shardings (a plain
    LintContext estimates the single-device program).

    Model: every input is resident at entry.  A NON-donated input
    belongs to the caller and stays resident for the whole call (this
    is why a missed donation shows up here as +1x the carry, the HBM
    view of the donation rule's finding).  A donated input is freeable
    after its last use — and an equation producing an output of the
    same aval as an operand dying at that equation updates IN PLACE
    (XLA's buffer reuse), so a KV cache threaded through per-layer
    scatters counts once, not once per layer.  Sub-jaxpr internals are
    not expanded: transients inside a fused region are invisible, so
    the estimate is a lower bound (documented in BASELINE.md, with the
    tolerance the cross-check uses)."""
    mesh = getattr(ctx, "mesh", None) or core.MeshInfo(())
    var_specs = getattr(ctx, "var_specs", {})
    jaxpr = ctx.closed.jaxpr

    def pd_bytes(v) -> int:
        av = getattr(v, "aval", None)
        return core.sharded_bytes(av, var_specs.get(v), mesh) or 0

    donated_idx = {fi.index for fi in ctx.inputs if fi.donated}
    invars = list(jaxpr.invars)
    donated_vars = {v for i, v in enumerate(invars) if i in donated_idx}
    caller_owned = {v for i, v in enumerate(invars)
                    if i not in donated_idx}

    n_eqns = len(jaxpr.eqns)
    last_use: Dict[Any, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not hasattr(v, "val"):
                last_use[v] = i
    for v in jaxpr.outvars:
        if not hasattr(v, "val"):
            last_use[v] = n_eqns            # live through the end

    input_pd = sum(pd_bytes(v) for v in invars)
    donated_pd = sum(pd_bytes(v) for v in donated_vars)
    current = input_pd + sum(pd_bytes(cv) for cv in jaxpr.constvars)
    peak = current

    live = set(invars) | set(jaxpr.constvars)
    for i, eqn in enumerate(jaxpr.eqns):
        dying: List[Any] = []
        for v in eqn.invars:
            if (not hasattr(v, "val") and last_use.get(v) == i
                    and v in live and v not in caller_owned
                    and v not in dying):
                dying.append(v)
        # in-place matching: an output with the aval (and per-device
        # bytes) of an operand dying at this eqn reuses its buffer —
        # the threaded-carry case (per-layer KV scatter) nets zero
        outs = list(eqn.outvars)
        reused = set()
        matched_out = set()
        for o in outs:
            ob = pd_bytes(o)
            oa = getattr(o, "aval", None)
            for v in dying:
                if v in reused:
                    continue
                va = getattr(v, "aval", None)
                if (oa is not None and va is not None
                        and getattr(oa, "shape", None) == getattr(
                            va, "shape", None)
                        and getattr(oa, "dtype", None) == getattr(
                            va, "dtype", None)
                        and pd_bytes(v) == ob):
                    reused.add(v)
                    matched_out.add(o)
                    break
        current += sum(pd_bytes(o) for o in outs
                       if o not in matched_out)
        peak = max(peak, current)
        for v in dying:
            live.discard(v)
            if v not in reused:
                current -= pd_bytes(v)
        for o in outs:
            if o in last_use:       # consumed later (or a result)
                live.add(o)
            else:                   # dead on arrival: buffer freed now
                current -= pd_bytes(o)
        # matched pairs: buffer ownership transfers v -> o; bytes stay
        # in `current` (counted once) until o itself dies

    def _in_spec(fi):
        specs = getattr(ctx, "in_specs", None)
        return (specs[fi.index]
                if specs is not None and fi.index < len(specs) else None)

    cache_pd = sum(core.sharded_bytes(fi.aval, _in_spec(fi), mesh) or 0
                   for fi in ctx.inputs if fi.label.startswith("cache"))
    cache_shards = max([mesh.nshards(_in_spec(fi))
                        for fi in ctx.inputs
                        if fi.label.startswith("cache")] or [1])
    params_pd = sum(core.sharded_bytes(fi.aval, _in_spec(fi), mesh) or 0
                    for fi in ctx.inputs
                    if fi.label.startswith("params"))
    return {"peak_bytes_per_device": int(peak),
            "input_bytes_per_device": int(input_pd),
            "donated_bytes_per_device": int(donated_pd),
            "params_bytes_per_device": int(params_pd),
            "cache_bytes_per_device": int(cache_pd),
            "cache_shards": int(cache_shards),
            "top_level_eqns": n_eqns}
