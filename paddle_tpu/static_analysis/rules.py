"""Pluggable graph-lint rules over one abstract trace (LintContext).

Each rule is a small dataclass with a ``run(ctx) -> [Finding]`` method;
byte thresholds default to ``FLAGS_graph_lint_donation_min_bytes`` /
``FLAGS_graph_lint_widen_bytes`` / ``FLAGS_graph_lint_const_bytes`` but
can be pinned per-instance (tests pass explicit rule instances to
``analyze`` instead of moving global thresholds).  Severity convention: ``error`` = a
perf/memory bug on a serving hot path (missed donation, captured weight,
host callback in a step), ``warning`` = a hazard worth a look (a
widening that might be a deliberate accumulator, a weak-typed scalar
that has not retraced *yet*).

The motivating catch (ISSUE 6): the serving engines' once-jitted step
functions take and return the full KV cache; without buffer donation
every tick double-buffers the dominant HBM consumer.  That is invisible
at runtime (no error, no wrong tokens — just 2x cache HBM) and exactly
the class of bug a trace-time aval check finds for free.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import List, Optional, Tuple

import jax
import numpy as np

from .. import flags as _flags
from . import core

__all__ = ["Rule", "DonationRule", "DtypePromotionRule",
           "ConstantCaptureRule", "HostSyncRule", "RetraceHazardRule",
           "default_rules"]

# primitives that round-trip through the host mid-graph: callbacks block
# the device stream on Python, infeed/outfeed block on host buffers —
# inside a serving step any of them serializes the tick loop
HOST_SYNC_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed",
})

_WIDE_PAIRS = {("bfloat16", "float32"), ("bfloat16", "float64"),
               ("float16", "float32"), ("float16", "float64"),
               ("float32", "float64")}

# int8 operands widening to float: the quantized KV cache DEQUANTIZES
# inside the decode-attention kernel (on-chip, post-load) and its
# reference parity path — deliberate and scoped by name.  Anywhere else
# an int8->float convert materializes exactly the full-precision copy
# the quantized store existed to avoid (4x the streamed bytes).
_INT8_WIDE_PAIRS = {("int8", "bfloat16"), ("int8", "float16"),
                    ("int8", "float32"), ("int8", "float64")}


class Rule:
    """Base: ``name``/``severity`` class attrs + ``run(ctx)``."""

    name = "rule"
    severity = "warning"

    def run(self, ctx: core.LintContext) -> List[core.Finding]:
        raise NotImplementedError

    def _finding(self, path: str, message: str,
                 bytes: Optional[int] = None) -> core.Finding:
        return core.Finding(self.name, self.severity, path, message, bytes)


@dataclasses.dataclass
class DonationRule(Rule):
    """Jitted outputs whose aval matches a NON-donated input.

    XLA aliases a donated input's buffer to a matching output in place;
    without the donation the runtime must keep both live across the call
    — for a step function that threads a big carry (the serving KV
    cache), that is a silent 2x on the dominant HBM consumer.  Matching
    is by aval (shape+dtype) multiset: an output first consumes a
    donated input of its aval (fine), then a non-donated one (finding,
    sized at the buffer it double-buffers)."""

    min_bytes: Optional[int] = None

    name = "donation"
    severity = "error"

    def run(self, ctx: core.LintContext) -> List[core.Finding]:
        thr = (self.min_bytes if self.min_bytes is not None
               else int(_flags.flag("graph_lint_donation_min_bytes")))
        free = defaultdict(list)      # aval key -> un-donated FlatInputs
        donated = defaultdict(int)    # aval key -> donated input count
        for fi in ctx.inputs:
            if core.aval_bytes(fi.aval) is None:
                continue
            key = (tuple(fi.aval.shape), str(fi.aval.dtype))
            if fi.donated:
                donated[key] += 1
            else:
                free[key].append(fi)
        out: List[core.Finding] = []
        for i, av in enumerate(ctx.out_avals):
            b = core.aval_bytes(av)
            if b is None or b < thr:
                continue
            key = (tuple(av.shape), str(av.dtype))
            if donated[key] > 0:      # rides a donated buffer: fine
                donated[key] -= 1
                continue
            if free[key]:
                fi = free[key].pop(0)
                out.append(self._finding(
                    "",
                    f"output {i} ({av.str_short()}) has the same aval as "
                    f"un-donated input '{fi.label}' — without "
                    f"donate_argnums both buffers stay live across the "
                    f"call, double-buffering {b} bytes of HBM; donate "
                    f"the input to alias it in place",
                    bytes=b))
        return out


@dataclasses.dataclass
class DtypePromotionRule(Rule):
    """f32/f64 ``convert_element_type`` widenings of large low-precision
    operands — on a bf16 decode path a stray ``.astype(float32)`` doubles
    the bytes a weight-stream-bound step must move.  Deliberate
    accumulators (softmax/norm reductions) live inside named regions;
    the ``allow`` list matches path substrings (pjit/remat regions carry
    the traced function's name — see ``core.iter_eqns``).

    int8 operands get their own ``allow_int8`` scope: the quantized KV
    cache's dequant widening belongs inside the decode-attention kernel
    and its named reference path (``pjit[_dequant_decode_attention]``)
    or the scatter-time quantize regions (``pjit[_quantized_*_write]``)
    — an int8->float convert anywhere else rematerializes the bf16 copy
    the int8 store was bought to avoid, and is flagged."""

    min_bytes: Optional[int] = None
    allow: Tuple[str, ...] = ("softmax", "norm", "logsumexp",
                              "quantized_")
    allow_int8: Tuple[str, ...] = ("decode_attention", "quantized_")

    name = "dtype-promotion"
    severity = "warning"

    def run(self, ctx: core.LintContext) -> List[core.Finding]:
        thr = (self.min_bytes if self.min_bytes is not None
               else int(_flags.flag("graph_lint_widen_bytes")))
        out: List[core.Finding] = []
        for path, eqn in core.iter_eqns(ctx.closed.jaxpr):
            if eqn.primitive.name != "convert_element_type":
                continue
            src = getattr(eqn.invars[0], "aval", None)
            new = eqn.params.get("new_dtype")
            sd = getattr(src, "dtype", None)
            if sd is None or new is None:
                continue
            pair = (str(sd), str(new))
            if pair in _WIDE_PAIRS:
                allow, hint = self.allow, (
                    "if this is a softmax/norm accumulator, put it in a "
                    "named region on the allowlist; otherwise it "
                    "double-charges the memory-bound step")
            elif pair in _INT8_WIDE_PAIRS:
                allow, hint = self.allow_int8, (
                    "quantized-KV dequantization belongs inside the "
                    "decode_attention kernel/reference — dequantizing "
                    "here rematerializes the full-precision copy the "
                    "int8 store exists to avoid")
            else:
                continue
            nb = core.aval_bytes(src)
            if nb is None or nb < thr:
                continue
            if any(a in path for a in allow):
                continue
            wide = nb // sd.itemsize * np.dtype(new).itemsize
            out.append(self._finding(
                path,
                f"{src.str_short()} widened to {new} ({nb} -> {wide} "
                f"bytes) on a low-precision path — {hint}",
                bytes=wide))
        return out


@dataclasses.dataclass
class ConstantCaptureRule(Rule):
    """Large arrays baked into the jaxpr as consts: a weight closed over
    instead of passed as an argument costs HBM alongside the live copy
    (XLA embeds or uploads it per-executable) and forces a RETRACE when
    the python value is swapped — the before-the-fact twin of the
    retrace watchdog's budget."""

    min_bytes: Optional[int] = None

    name = "constant-capture"
    severity = "error"

    def run(self, ctx: core.LintContext) -> List[core.Finding]:
        thr = (self.min_bytes if self.min_bytes is not None
               else int(_flags.flag("graph_lint_const_bytes")))
        out: List[core.Finding] = []

        def scan(constvars, consts, path):
            for cv, c in zip(constvars, consts):
                b = core.aval_bytes(getattr(cv, "aval", None))
                if b is None:
                    b = getattr(c, "nbytes", None)
                if b is None or b < thr:
                    continue
                out.append(self._finding(
                    path,
                    f"large constant {cv.aval.str_short()} captured into "
                    f"the jaxpr — closed-over arrays are re-uploaded per "
                    f"executable and retrace when replaced; pass it as "
                    f"an argument",
                    bytes=int(b)))

        scan(ctx.closed.jaxpr.constvars, ctx.closed.consts, "")
        seen = set()
        for path, eqn in core.iter_eqns(ctx.closed.jaxpr):
            for v in eqn.params.values():
                vals = v if isinstance(v, (tuple, list)) else [v]
                for item in vals:
                    if (hasattr(item, "consts") and hasattr(item, "jaxpr")
                            and id(item) not in seen):
                        seen.add(id(item))
                        scan(item.jaxpr.constvars, item.consts,
                             f"{path}/{eqn.primitive.name}")
        return out


@dataclasses.dataclass
class HostSyncRule(Rule):
    """Host round-trips inside a traced program: ``pure_callback`` /
    ``io_callback`` / ``debug_callback`` / infeed / outfeed block the
    device pipeline on Python — inside a serving step they serialize the
    tick loop.  ``allow`` substrings match the callback target's
    ``module.qualname`` (paddle_tpu.observability is allowlisted: its
    trace-TIME counter hooks are python side effects that never lower to
    callback primitives, but any future observability callback is a
    deliberate one)."""

    allow: Tuple[str, ...] = ("paddle_tpu.observability",)

    name = "host-sync"
    severity = "error"

    @staticmethod
    def _target(eqn) -> str:
        cb = eqn.params.get("callback")
        inner = getattr(cb, "callback_func", None) or cb
        if inner is None:
            return ""
        mod = getattr(inner, "__module__", "") or ""
        qual = (getattr(inner, "__qualname__", "")
                or type(inner).__name__)
        return f"{mod}.{qual}"

    def run(self, ctx: core.LintContext) -> List[core.Finding]:
        out: List[core.Finding] = []
        for path, eqn in core.iter_eqns(ctx.closed.jaxpr):
            nm = eqn.primitive.name
            if nm not in HOST_SYNC_PRIMS:
                continue
            target = self._target(eqn)
            if target and any(a in target for a in self.allow):
                continue
            out.append(self._finding(
                path,
                f"{nm}{' -> ' + target if target else ''} inside the "
                f"traced graph — a host round-trip serializes the device "
                f"pipeline (a serving tick would block on Python every "
                f"step); hoist it out or allowlist a deliberate hook"))
        return out


@dataclasses.dataclass
class RetraceHazardRule(Rule):
    """Weak-typed scalars and non-canonical dtypes in the traced call's
    INPUTS — the shapes of retrace bugs the watchdog (observability/
    watchdog.py) catches after the fact, checked before it: a python
    scalar leaking into a jitted call signature is one strong-typed
    caller away from a second compilation."""

    name = "retrace-hazard"
    severity = "warning"

    def run(self, ctx: core.LintContext) -> List[core.Finding]:
        out: List[core.Finding] = []
        for fi in ctx.inputs:
            av = fi.aval
            dt = getattr(av, "dtype", None)
            if dt is None:
                continue
            try:
                if jax.dtypes.issubdtype(dt, jax.dtypes.extended):
                    continue                     # PRNG keys etc.
            except Exception:
                continue
            if getattr(av, "weak_type", False):
                out.append(self._finding(
                    "",
                    f"input '{fi.label}' is weak-typed "
                    f"({av.str_short()}): a Python scalar leaked into "
                    f"the call — the same site called with a "
                    f"strongly-typed value retraces; pass np/jnp-typed "
                    f"scalars"))
                continue
            try:
                canon = jax.dtypes.canonicalize_dtype(dt)
            except Exception:
                continue
            if canon != dt:
                out.append(self._finding(
                    "",
                    f"input '{fi.label}' carries non-canonical dtype "
                    f"{dt} (canonicalizes to {canon}) — mixed x64/x32 "
                    f"callers retrace against each other"))
        return out


def default_rules() -> Tuple[Rule, ...]:
    """Fresh instances of the full rule set (thresholds read the
    graph-lint byte-threshold flags at run time)."""
    return (DonationRule(), DtypePromotionRule(), ConstantCaptureRule(),
            HostSyncRule(), RetraceHazardRule())
