"""paddle_tpu.tensor — the tensor-ops parity surface.

TPU-native equivalent of the reference's ``python/paddle/tensor/`` package
(creation / manipulation / math / logic / search / linalg / random ops) and
of the C++ ``eager_method.cc`` Tensor-method table (see
:mod:`.tensor_facade`).

Everything here is a thin, convention-matching adapter from paddle's call
signatures (``x``/``y``, ``axis``, ``keepdim``, explicit ``perm``) onto
jnp/lax — the compute goes straight to XLA, which owns fusion and layout.
All public names are re-exported at the package top level
(``paddle_tpu.concat`` works like ``paddle.concat``).
"""

from .creation import *  # noqa: F401,F403
from .creation import __all__ as _creation_all
from .linalg import *  # noqa: F401,F403
from .linalg import __all__ as _linalg_all
from .logic import *  # noqa: F401,F403
from .logic import __all__ as _logic_all
from .manipulation import *  # noqa: F401,F403
from .manipulation import __all__ as _manipulation_all
from .math import *  # noqa: F401,F403
from .math import __all__ as _math_all
from .random import *  # noqa: F401,F403
from .random import __all__ as _random_all
from .search import *  # noqa: F401,F403
from .search import __all__ as _search_all
from .tensor_facade import Tensor  # noqa: F401

__all__ = (list(_creation_all) + list(_manipulation_all) + list(_math_all)
           + list(_logic_all) + list(_search_all) + list(_linalg_all)
           + list(_random_all) + ["Tensor"])
