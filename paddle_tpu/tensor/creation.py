"""Tensor creation ops (parity surface: upstream python/paddle/tensor/creation.py).

Thin, convention-matching wrappers over jnp: paddle argument names
(``x``/``y``, ``axis``, ``keepdim``), paddle dtype defaults.  The heavy
lifting — layout, fusion, device placement — is XLA's job.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.dtype import to_jax_dtype

__all__ = [
    "zeros", "ones", "full", "zeros_like", "ones_like", "full_like",
    "empty", "empty_like", "arange", "linspace", "logspace", "eye",
    "tril", "triu", "diag", "diagflat", "meshgrid", "clone", "assign",
    # breadth (round 4)
    "complex", "polar", "tril_indices", "triu_indices",
]


def _dt(dtype, default=None):
    if dtype is None:
        return default
    return to_jax_dtype(dtype)


def zeros(shape, dtype=None):
    return jnp.zeros(shape, _dt(dtype, jnp.float32))


def ones(shape, dtype=None):
    return jnp.ones(shape, _dt(dtype, jnp.float32))


def full(shape, fill_value, dtype=None):
    return jnp.full(shape, fill_value, _dt(dtype))


def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, _dt(dtype))


def ones_like(x, dtype=None):
    return jnp.ones_like(x, _dt(dtype))


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, _dt(dtype))


def empty(shape, dtype=None):
    # XLA has no uninitialised buffers; zeros compiles to a broadcast
    return jnp.zeros(shape, _dt(dtype, jnp.float32))


def empty_like(x, dtype=None):
    return jnp.zeros_like(x, _dt(dtype))


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step, _dt(dtype))


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, num, dtype=_dt(dtype))


def logspace(start, stop, num, base=10.0, dtype=None):
    return jnp.logspace(start, stop, num, base=base, dtype=_dt(dtype))


def eye(num_rows, num_columns=None, dtype=None):
    return jnp.eye(num_rows, num_columns, dtype=_dt(dtype, jnp.float32))


def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def diag(x, offset=0, padding_value=0):
    x = jnp.asarray(x)
    if x.ndim == 1 and padding_value != 0:
        n = x.shape[0] + abs(offset)
        base = jnp.full((n, n), padding_value, x.dtype)
        idx = jnp.arange(x.shape[0])
        r = idx if offset >= 0 else idx - offset
        c = idx + offset if offset >= 0 else idx
        return base.at[r, c].set(x)
    return jnp.diag(x, k=offset)


def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


def meshgrid(*args):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    return jnp.meshgrid(*args, indexing="ij")


def clone(x):
    return jnp.array(x, copy=True)


def assign(x, output=None):
    out = jnp.asarray(x)
    if output is not None:
        raise ValueError("assign(output=) in-place form is not supported on "
                         "immutable jax arrays; use the return value")
    return out


# -- breadth (round 4) -------------------------------------------------------

def complex(real, imag):
    return jax.lax.complex(jnp.asarray(real, jnp.float32)
                           if jnp.asarray(real).dtype not in
                           (jnp.float32, jnp.float64)
                           else jnp.asarray(real),
                           jnp.asarray(imag, jnp.float32)
                           if jnp.asarray(imag).dtype not in
                           (jnp.float32, jnp.float64)
                           else jnp.asarray(imag))


def polar(abs, angle):
    return jax.lax.complex(abs * jnp.cos(angle), abs * jnp.sin(angle))


def tril_indices(row: int, col=None, offset: int = 0, dtype="int64"):
    col = row if col is None else col
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return jnp.stack([r, c]).astype(to_jax_dtype(dtype))


def triu_indices(row: int, col=None, offset: int = 0, dtype="int64"):
    col = row if col is None else col
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return jnp.stack([r, c]).astype(to_jax_dtype(dtype))
