"""Discrete Fourier transforms (parity surface: upstream python/paddle/fft.py).

Paddle's fft namespace is a thin convention layer (``n``/``axis``/``norm``
keyword names, hermitian variants) over the backend FFT. On TPU the backend
is XLA's FftOp — batched, fused into surrounding elementwise work, and
differentiable through jax — so every function here is a calling-convention
shim over ``jnp.fft``. No custom kernels: FFT is one of the ops XLA already
lowers well, and a Pallas rewrite would have to re-derive Cooley-Tukey for
the MXU with no expected win.

Chip notes (found by the TPU-lane probe, round 4) — two quirks of the
tunnel-attached bench chip's backend, both absent on CPU:

  * complex64 *computation* compiles and runs, but *host transfer* of
    complex arrays is UNIMPLEMENTED — ``np.asarray`` on an fft result
    raises (and wedges the client).  Fetch spectra as
    ``paddle_tpu.tensor.manipulation.as_real(z)`` (a (…, 2) float array)
    and view them complex host-side.
  * an *eager complex-scalar constant* (``jnp.full(shape, 1+0j)``)
    poisons the backend's scalar-constant path: every later eager
    ``convert_element_type`` — even ``jnp.ones(2)`` — dies UNIMPLEMENTED.
    Build complex values inside compiled programs (fft, ``lax.complex``
    on arrays), never from Python complex scalars.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _norm(norm):
    norm = norm or "backward"
    if norm not in _NORMS:
        raise ValueError(f"norm must be one of {_NORMS}, got {norm!r}")
    return norm


def fft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.fft(x, n=n, axis=axis, norm=_norm(norm))


def ifft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=_norm(norm))


def fft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.fft2(x, s=s, axes=axes, norm=_norm(norm))


def ifft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.ifft2(x, s=s, axes=axes, norm=_norm(norm))


def fftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=_norm(norm))


def ifftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=_norm(norm))


def rfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=_norm(norm))


def irfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=_norm(norm))


def rfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.rfft2(x, s=s, axes=axes, norm=_norm(norm))


def irfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.irfft2(x, s=s, axes=axes, norm=_norm(norm))


def rfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=_norm(norm))


def irfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=_norm(norm))


def hfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=_norm(norm))


def ihfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=_norm(norm))


def fftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.fftfreq(n, d=d)
    return out.astype(dtype) if dtype is not None else out


def rfftfreq(n, d=1.0, dtype=None):
    out = jnp.fft.rfftfreq(n, d=d)
    return out.astype(dtype) if dtype is not None else out


def fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)
