"""Linear-algebra ops (parity surface: upstream python/paddle/tensor/linalg.py).

Wrappers over jnp.linalg.  Most decompositions have XLA lowerings on every
backend (eigh/lu/lstsq/qr/svd/cholesky/solve/householder_product all compile
on TPU), but general non-symmetric ``eig``/``eigvals`` exist only as a CPU
kernel — on device backends XLA raises ``NotImplementedError: MLIR
translation rule for primitive 'eig' not found`` (reproduced on the real
chip, round-3 verdict weak #1).  Those two are dispatched to the host
explicitly below; like upstream paddle, which also computes general eig on
CPU, they are eager host ops — not traceable inside a device ``jit``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "norm", "t", "transpose", "dist", "cond", "det", "slogdet", "inv",
    "pinv", "matrix_power", "matrix_rank", "cholesky", "cholesky_solve",
    "lu", "qr", "svd", "eig",
    "eigh", "eigvals", "eigvalsh", "solve", "triangular_solve", "lstsq",
    "multi_dot", "matrix_transpose", "householder_product",
    # round-4 additions
    "matrix_exp", "corrcoef",
]


def norm(x, p=None, axis=None, keepdim: bool = False):
    if p is None:
        p = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2
    if axis is None and p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    if isinstance(axis, (list, tuple)):
        return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)
    if p == jnp.inf:
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == -jnp.inf:
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis,
                   keepdims=keepdim) ** (1.0 / p)


def t(x):
    if x.ndim > 2:
        raise ValueError("paddle.t only supports ndim <= 2")
    return x.T


def transpose(x, perm):
    return jnp.transpose(x, axes=perm)


def matrix_transpose(x):
    return jnp.swapaxes(x, -1, -2)


def dist(x, y, p=2):
    return norm(jnp.ravel(x - y), p=p)


def cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


def det(x):
    return jnp.linalg.det(x)


def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return sign, logabs


def inv(x):
    return jnp.linalg.inv(x)


def pinv(x, rcond=1e-15, hermitian: bool = False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def matrix_power(x, n: int):
    return jnp.linalg.matrix_power(x, n)


def matrix_rank(x, tol=None, hermitian: bool = False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def cholesky(x, upper: bool = False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


def qr(x, mode: str = "reduced"):
    return jnp.linalg.qr(x, mode=mode)


def svd(x, full_matrices: bool = False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def _host_eig(fn, x):
    """Run ``fn`` on the host CPU device — eig's only XLA kernel.

    The complex64 results stay on the host: TPU backends cannot hold
    complex arrays (device_put of the result raises UNIMPLEMENTED on the
    real chip), and upstream paddle's GPU eig likewise computes and returns
    via the CPU path.  Downstream jnp ops accept host arrays transparently.
    """
    if jax.default_backend() == "cpu":
        return fn(x)
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        return fn(jax.device_put(x, cpu))


def eig(x):
    return _host_eig(jnp.linalg.eig, x)


def eigh(x, UPLO: str = "L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigvals(x):
    return _host_eig(jnp.linalg.eigvals, x)


def eigvalsh(x, UPLO: str = "L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def solve(x, y):
    return jnp.linalg.solve(x, y)


def triangular_solve(x, y, upper: bool = True, transpose: bool = False,
                     unitriangular: bool = False):
    a = jnp.swapaxes(x, -1, -2) if transpose else x
    return jax.scipy.linalg.solve_triangular(
        a, y, lower=not upper if not transpose else upper,
        unit_diagonal=unitriangular)


def lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


def multi_dot(arrays):
    return jnp.linalg.multi_dot(arrays)


def householder_product(x, tau):
    return jax.lax.linalg.householder_product(x, tau)


def cholesky_solve(x, y, upper: bool = False):
    """Solve A @ out = x given the Cholesky factor ``y`` of A."""
    L = jnp.swapaxes(y, -1, -2).conj() if upper else y
    z = jax.scipy.linalg.solve_triangular(L, x, lower=True)
    return jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(L, -1, -2).conj(), z, lower=False)


def lu(x, pivot: bool = True, get_infos: bool = False):
    """LU factorisation; pivots are 1-indexed (paddle/torch convention)."""
    if not pivot:
        raise NotImplementedError("pivot=False is not supported (XLA's LU "
                                  "is always partial-pivoted)")
    lu_mat, piv, _ = jax.lax.linalg.lu(x)
    piv = piv.astype(jnp.int32) + 1
    if get_infos:
        info = jnp.zeros(x.shape[:-2], jnp.int32)
        return lu_mat, piv, info
    return lu_mat, piv


# -- round-4 additions -------------------------------------------------------

def matrix_exp(x):
    """Matrix exponential (parity: paddle.linalg.matrix_exp) — XLA's
    scaling-and-squaring Padé path via jax.scipy."""
    return jax.scipy.linalg.expm(x)


def corrcoef(x, rowvar: bool = True):
    """Correlation matrix (parity: paddle.linalg.corrcoef)."""
    return jnp.corrcoef(x, rowvar=rowvar)
