"""Comparison / logical ops (parity surface: upstream
python/paddle/tensor/logic.py)."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "allclose", "isclose", "logical_and",
    "logical_or", "logical_not", "logical_xor", "bitwise_and", "bitwise_or",
    "bitwise_not", "bitwise_xor", "isnan", "isinf", "isfinite", "is_empty",
    "where",
    # breadth (round 4)
    "bitwise_left_shift", "bitwise_right_shift", "isposinf", "isneginf",
    "isreal", "is_complex", "is_floating_point", "is_integer",
]


def equal(x, y):
    return jnp.equal(x, y)


def not_equal(x, y):
    return jnp.not_equal(x, y)


def greater_than(x, y):
    return jnp.greater(x, y)


def greater_equal(x, y):
    return jnp.greater_equal(x, y)


def less_than(x, y):
    return jnp.less(x, y)


def less_equal(x, y):
    return jnp.less_equal(x, y)


def equal_all(x, y):
    return jnp.array_equal(x, y)


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def logical_and(x, y):
    return jnp.logical_and(x, y)


def logical_or(x, y):
    return jnp.logical_or(x, y)


def logical_not(x):
    return jnp.logical_not(x)


def logical_xor(x, y):
    return jnp.logical_xor(x, y)


def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


def bitwise_not(x):
    return jnp.bitwise_not(x)


def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


def isnan(x):
    return jnp.isnan(x)


def isinf(x):
    return jnp.isinf(x)


def isfinite(x):
    return jnp.isfinite(x)


def is_empty(x):
    return jnp.asarray(x).size == 0


def where(condition, x=None, y=None):
    if x is None and y is None:
        # data-dependent shape → eager only
        import numpy as np
        return tuple(jnp.asarray(i)
                     for i in np.where(np.asarray(condition)))
    return jnp.where(condition, x, y)


# -- breadth (round 4) -------------------------------------------------------

def bitwise_left_shift(x, y):
    return jnp.left_shift(x, y)


def bitwise_right_shift(x, y):
    return jnp.right_shift(x, y)


def isposinf(x):
    return jnp.isposinf(x)


def isneginf(x):
    return jnp.isneginf(x)


def isreal(x):
    return jnp.isreal(x)


def is_complex(x):
    return jnp.iscomplexobj(x)


def is_floating_point(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer)
