"""Tensor manipulation ops (parity surface: upstream
python/paddle/tensor/manipulation.py).

Paddle calling conventions over jnp/lax.  Ops whose output shape depends on
data (``masked_select``, ``nonzero``-driven paths) are eager-only unless a
static ``size`` style escape hatch exists — data-dependent shapes cannot
live under ``jax.jit`` (XLA static-shape semantics); each such op documents
it.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "concat", "stack", "split", "chunk", "squeeze", "unsqueeze", "reshape",
    "flatten", "transpose", "moveaxis", "roll", "flip", "rot90", "tile",
    "expand", "expand_as", "broadcast_to", "gather", "gather_nd", "scatter",
    "scatter_nd_add", "index_select", "masked_select", "take_along_axis",
    "put_along_axis", "repeat_interleave", "unbind", "unstack", "unique",
    "cast", "slice", "strided_slice", "as_strided", "view", "masked_fill",
]


def concat(x: Sequence, axis: int = 0):
    return jnp.concatenate(list(x), axis=axis)


def stack(x: Sequence, axis: int = 0):
    return jnp.stack(list(x), axis=axis)


def split(x, num_or_sections, axis: int = 0):
    """paddle.split: int = equal parts; list = sizes (-1 = remainder)."""
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    sizes = list(num_or_sections)
    if -1 in sizes:
        known = sum(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = x.shape[axis] - known
    offsets = []
    acc = 0
    for s in sizes[:-1]:
        acc += s
        offsets.append(acc)
    return jnp.split(x, offsets, axis=axis)


def chunk(x, chunks: int, axis: int = 0):
    return jnp.array_split(x, chunks, axis=axis)


def squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis)


def unsqueeze(x, axis):
    return jnp.expand_dims(x, axis)


def reshape(x, shape):
    return jnp.reshape(x, shape)


def view(x, shape_or_dtype):
    if isinstance(shape_or_dtype, (list, tuple)):
        return jnp.reshape(x, shape_or_dtype)
    return x.view(shape_or_dtype)


def flatten(x, start_axis: int = 0, stop_axis: int = -1):
    nd = x.ndim
    start = start_axis % nd
    stop = stop_axis % nd
    flat = 1
    for d in x.shape[start:stop + 1]:
        flat *= d
    return jnp.reshape(x, x.shape[:start] + (flat,) + x.shape[stop + 1:])


def transpose(x, perm):
    """paddle.transpose takes an explicit permutation."""
    return jnp.transpose(x, axes=perm)


def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


def flip(x, axis):
    return jnp.flip(x, axis=axis)


def rot90(x, k: int = 1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


def tile(x, repeat_times):
    return jnp.tile(x, repeat_times)


def expand(x, shape):
    """paddle.expand: -1 keeps the existing dim."""
    tgt = list(shape)
    src = (1,) * (len(tgt) - x.ndim) + x.shape
    for i, s in enumerate(tgt):
        if s == -1:
            tgt[i] = src[i]
    return jnp.broadcast_to(x, tuple(tgt))


def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


def broadcast_to(x, shape):
    return jnp.broadcast_to(x, shape)


def gather(x, index, axis: int = 0):
    """paddle.gather: select rows of ``axis`` by a 1-D index."""
    return jnp.take(x, index, axis=axis)


def gather_nd(x, index):
    """Index with the last dim of ``index`` addressing leading dims of x."""
    index = jnp.asarray(index)
    return x[tuple(jnp.moveaxis(index, -1, 0))]


def scatter(x, index, updates, overwrite: bool = True):
    """paddle.scatter along dim 0 (functional: returns a new array)."""
    x = jnp.asarray(x)
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def scatter_nd_add(x, index, updates):
    x = jnp.asarray(x)
    index = jnp.asarray(index)
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def index_select(x, index, axis: int = 0):
    return jnp.take(x, index, axis=axis)


def masked_select(x, mask):
    """Data-dependent output shape → eager only (not jittable)."""
    import numpy as np
    return jnp.asarray(np.asarray(x)[np.asarray(mask)])


def take_along_axis(arr, indices, axis, broadcast: bool = True):
    if broadcast:
        shape = list(arr.shape)
        shape[axis] = indices.shape[axis]
        indices = jnp.broadcast_to(indices, shape)
    return jnp.take_along_axis(arr, indices, axis=axis)


def put_along_axis(arr, indices, values, axis, reduce: str = "assign"):
    arr = jnp.asarray(arr)
    values = jnp.broadcast_to(jnp.asarray(values, arr.dtype), indices.shape)
    if reduce == "assign":
        return jnp.put_along_axis(arr, indices, values, axis=axis,
                                  inplace=False)
    dims = list(range(arr.ndim))
    del dims[axis]
    idx = jnp.indices(indices.shape)
    full = [idx[d] for d in range(arr.ndim)]
    full[axis] = indices
    if reduce == "add":
        return arr.at[tuple(full)].add(values)
    if reduce == "multiply":
        return arr.at[tuple(full)].multiply(values)
    raise ValueError(f"unknown reduce {reduce!r}")


def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


def unbind(x, axis: int = 0):
    return [jnp.squeeze(s, axis)
            for s in jnp.split(x, x.shape[axis], axis=axis)]


unstack = unbind


def unique(x, return_index=False, return_inverse=False,
           return_counts=False, axis=None):
    """Data-dependent output shape → eager only (not jittable)."""
    import numpy as np
    out = np.unique(np.asarray(x), return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if isinstance(out, tuple):
        return tuple(jnp.asarray(o) for o in out)
    return jnp.asarray(out)


def cast(x, dtype):
    from ..framework.dtype import to_jax_dtype
    return x.astype(to_jax_dtype(dtype))


def slice(x, axes, starts, ends):
    idx = [jnp.s_[:]] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = jnp.s_[st:en]
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides):
    idx = [jnp.s_[:]] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = jnp.s_[st:en:sd]
    return x[tuple(idx)]


def as_strided(x, shape, stride, offset: int = 0):
    """Reference semantics over flat memory; implemented by explicit gather
    (XLA has no aliasing views)."""
    flat = jnp.ravel(x)
    idx = jnp.full(tuple(shape), offset)
    for d, (s, st) in enumerate(zip(shape, stride)):
        r = jnp.arange(s) * st
        idx = idx + jnp.expand_dims(
            r, tuple(i for i in range(len(shape)) if i != d))
    return flat[idx]


def masked_fill(x, mask, value):
    return jnp.where(mask, jnp.asarray(value, dtype=x.dtype), x)


# -- breadth (round 4): remaining documented manipulation surface ------------

def atleast_1d(*xs):
    out = [jnp.atleast_1d(jnp.asarray(x)) for x in xs]
    return out[0] if len(out) == 1 else out


def atleast_2d(*xs):
    out = [jnp.atleast_2d(jnp.asarray(x)) for x in xs]
    return out[0] if len(out) == 1 else out


def atleast_3d(*xs):
    out = [jnp.atleast_3d(jnp.asarray(x)) for x in xs]
    return out[0] if len(out) == 1 else out


def as_complex(x):
    """(..., 2) real pairs → (...) complex."""
    return jax.lax.complex(x[..., 0], x[..., 1])


def as_real(x):
    """(...) complex → (..., 2) real pairs."""
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def block_diag(inputs):
    return jax.scipy.linalg.block_diag(*inputs)


def column_stack(x):
    return jnp.column_stack(x)


def row_stack(x):
    return jnp.vstack(x)


def hstack(x):
    return jnp.hstack(x)


def vstack(x):
    return jnp.vstack(x)


def dstack(x):
    return jnp.dstack(x)


def crop(x, shape, offsets=None):
    offsets = [0] * x.ndim if offsets is None else list(offsets)
    # paddle semantics: -1/None = "from offset to the end of the dim"
    shape = [x.shape[i] - offsets[i] if s in (-1, None) else s
             for i, s in enumerate(shape)]
    for i, (o, s) in enumerate(zip(offsets, shape)):
        if o < 0 or s < 0 or o + s > x.shape[i]:
            # dynamic_slice would silently clamp; surface the bad crop
            raise ValueError(
                f"crop dim {i}: offset {o} + size {s} out of range for "
                f"input extent {x.shape[i]}")
    return jax.lax.dynamic_slice(x, offsets, shape)


def tensor_split(x, num_or_indices, axis: int = 0):
    return jnp.array_split(x, num_or_indices, axis=axis)


def hsplit(x, num_or_indices):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices):
    return tensor_split(x, num_or_indices, axis=2)


def unflatten(x, axis, shape):
    axis = axis % x.ndim
    new = list(x.shape[:axis]) + list(shape) + list(x.shape[axis + 1:])
    # one -1 wildcard allowed, as in paddle
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        new[new.index(-1)] = x.shape[axis] // known
    return jnp.reshape(x, new)


def unique_consecutive(x, return_inverse: bool = False,
                       return_counts: bool = False, axis=None):
    """Deduplicate consecutive runs (host-eager: output shape is data-
    dependent, same constraint as paddle's dynamic-shape op on XLA)."""
    import numpy as np
    xn = np.asarray(x)
    if axis is None:
        xn = xn.ravel()
        axis = 0
    moved = np.moveaxis(xn, axis, 0)
    keep = np.ones(moved.shape[0], dtype=bool)
    if moved.shape[0] > 1:
        keep[1:] = np.any(
            moved[1:].reshape(moved.shape[0] - 1, -1)
            != moved[:-1].reshape(moved.shape[0] - 1, -1), axis=1)
    out = jnp.asarray(np.moveaxis(moved[keep], 0, axis))
    results = [out]
    if return_inverse:
        results.append(jnp.asarray(np.cumsum(keep) - 1))
    if return_counts:
        starts = np.flatnonzero(keep)
        counts = np.diff(np.append(starts, moved.shape[0]))
        results.append(jnp.asarray(counts))
    return results[0] if len(results) == 1 else tuple(results)


def masked_scatter(x, mask, value):
    """Fill mask positions from value's leading elements, row-major.

    Static-shape formulation: position k in the flattened output takes
    value[rank(k)] where rank = cumsum(mask) - 1; non-mask slots keep x.
    """
    mask = jnp.broadcast_to(jnp.asarray(mask), x.shape)
    flat_mask = mask.ravel()
    ranks = jnp.cumsum(flat_mask) - 1
    vals = jnp.ravel(value)[jnp.clip(ranks, 0, None)]
    out = jnp.where(flat_mask, vals.astype(x.dtype), x.ravel())
    return out.reshape(x.shape)
