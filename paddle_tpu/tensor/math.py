"""Tensor math ops (parity surface: upstream python/paddle/tensor/math.py).

Paddle calling conventions (``x``/``y``, ``axis``, ``keepdim``) over jnp.
XLA fuses these elementwise chains into surrounding matmuls — no hand-fused
kernels needed at this layer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    # binary
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "pow", "maximum", "minimum", "fmax", "fmin", "atan2",
    "heaviside", "lerp", "outer", "inner", "cross", "dot", "matmul", "mm",
    "bmm", "mv", "add_n", "einsum",
    # unary
    "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt", "rsqrt",
    "square", "reciprocal", "abs", "neg", "sign", "floor", "ceil", "round",
    "trunc", "frac", "sin", "cos", "tan", "asin", "acos", "atan", "sinh",
    "cosh", "asinh", "acosh", "atanh", "erf", "erfinv", "sigmoid", "tanh",
    "deg2rad", "rad2deg", "angle", "conj", "real", "imag", "digamma",
    "lgamma", "logit", "nan_to_num",
    # clip / reductions
    "clip", "sum", "nansum", "mean", "nanmean", "prod", "max", "min",
    "amax", "amin", "cumsum", "cumprod", "cummax", "cummin", "logsumexp",
    "logcumsumexp", "count_nonzero", "all", "any", "diff", "trace",
    "stanh", "trapezoid", "vander",
    # breadth (round 4): the rest of the documented paddle math surface
    "addmm", "bincount", "cdist", "combinations", "copysign",
    "cumulative_trapezoid", "diag_embed", "diagonal", "frexp", "gammainc",
    "gammaincc", "gammaln", "gcd", "hypot", "i0", "i0e", "i1", "i1e",
    "index_add", "index_fill", "index_put", "kron", "lcm", "ldexp",
    "logaddexp", "multigammaln", "nextafter", "polygamma", "renorm", "sgn",
    "sinc", "take", "tensordot",
]


# -- binary ------------------------------------------------------------------

def add(x, y):
    return jnp.add(x, y)


def subtract(x, y):
    return jnp.subtract(x, y)


def multiply(x, y):
    return jnp.multiply(x, y)


def divide(x, y):
    return jnp.divide(x, y)


def floor_divide(x, y):
    return jnp.floor_divide(x, y)


def mod(x, y):
    return jnp.mod(x, y)


remainder = mod


def pow(x, y):
    return jnp.power(x, y)


def maximum(x, y):
    return jnp.maximum(x, y)


def minimum(x, y):
    return jnp.minimum(x, y)


def fmax(x, y):
    return jnp.fmax(x, y)


def fmin(x, y):
    return jnp.fmin(x, y)


def atan2(x, y):
    return jnp.arctan2(x, y)


def heaviside(x, y):
    return jnp.heaviside(x, y)


def lerp(x, y, weight):
    return x + weight * (y - x)


def outer(x, y):
    return jnp.outer(x, y)


def inner(x, y):
    return jnp.inner(x, y)


def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


def dot(x, y):
    """paddle.dot: 1-D (or batched row-wise) inner product."""
    return jnp.sum(x * y, axis=-1)


def matmul(x, y, transpose_x: bool = False, transpose_y: bool = False):
    """AMP-aware matmul: under an ``amp.auto_cast`` O1 policy the operands
    are cast to the policy dtype (the reference's white-list dispatch in
    eager amp_utils; models route their projections through here so O1 is
    real, not decorative)."""
    from .. import amp as _amp
    x, y = _amp.cast_inputs("matmul", x, y)
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


def einsum(equation, *operands):
    """AMP-aware einsum (white-listed: it is the MoE dispatch/combine and
    attention workhorse)."""
    from .. import amp as _amp
    operands = _amp.cast_inputs("einsum", *operands)
    return jnp.einsum(equation, *operands)


def mm(x, y):
    return jnp.matmul(x, y)


def bmm(x, y):
    return jnp.matmul(x, y)


def mv(x, vec):
    return jnp.matmul(x, vec)


def add_n(inputs):
    out = inputs[0]
    for t in inputs[1:]:
        out = out + t
    return out


# -- unary -------------------------------------------------------------------

def exp(x):
    return jnp.exp(x)


def expm1(x):
    return jnp.expm1(x)


def log(x):
    return jnp.log(x)


def log2(x):
    return jnp.log2(x)


def log10(x):
    return jnp.log10(x)


def log1p(x):
    return jnp.log1p(x)


def sqrt(x):
    return jnp.sqrt(x)


def rsqrt(x):
    return jax.lax.rsqrt(x)


def square(x):
    return jnp.square(x)


def reciprocal(x):
    return jnp.reciprocal(x)


def abs(x):
    return jnp.abs(x)


def neg(x):
    return jnp.negative(x)


def sign(x):
    return jnp.sign(x)


def floor(x):
    return jnp.floor(x)


def ceil(x):
    return jnp.ceil(x)


def round(x):
    return jnp.round(x)


def trunc(x):
    return jnp.trunc(x)


def frac(x):
    return x - jnp.trunc(x)


def sin(x):
    return jnp.sin(x)


def cos(x):
    return jnp.cos(x)


def tan(x):
    return jnp.tan(x)


def asin(x):
    return jnp.arcsin(x)


def acos(x):
    return jnp.arccos(x)


def atan(x):
    return jnp.arctan(x)


def sinh(x):
    return jnp.sinh(x)


def cosh(x):
    return jnp.cosh(x)


def asinh(x):
    return jnp.arcsinh(x)


def acosh(x):
    return jnp.arccosh(x)


def atanh(x):
    return jnp.arctanh(x)


def erf(x):
    return jax.scipy.special.erf(x)


def erfinv(x):
    return jax.scipy.special.erfinv(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def tanh(x):
    return jnp.tanh(x)


def deg2rad(x):
    return jnp.deg2rad(x)


def rad2deg(x):
    return jnp.rad2deg(x)


def angle(x):
    return jnp.angle(x)


def conj(x):
    return jnp.conj(x)


def real(x):
    return jnp.real(x)


def imag(x):
    return jnp.imag(x)


def digamma(x):
    return jax.scipy.special.digamma(x)


def lgamma(x):
    return jax.scipy.special.gammaln(x)


def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


# -- clip / reductions -------------------------------------------------------

def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


def sum(x, axis=None, dtype=None, keepdim: bool = False):
    return jnp.sum(x, axis=axis, dtype=dtype, keepdims=keepdim)


def nansum(x, axis=None, dtype=None, keepdim: bool = False):
    return jnp.nansum(x, axis=axis, dtype=dtype, keepdims=keepdim)


def mean(x, axis=None, keepdim: bool = False):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def nanmean(x, axis=None, keepdim: bool = False):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


def prod(x, axis=None, keepdim: bool = False, dtype=None):
    return jnp.prod(x, axis=axis, dtype=dtype, keepdims=keepdim)


def max(x, axis=None, keepdim: bool = False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim: bool = False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


amax = max
amin = min


def cumsum(x, axis=None, dtype=None):
    if axis is None:
        x = jnp.ravel(x)
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=dtype)


def cumprod(x, dim=None, dtype=None):
    if dim is None:
        x = jnp.ravel(x)
        dim = 0
    return jnp.cumprod(x, axis=dim, dtype=dtype)


def cummax(x, axis=None):
    if axis is None:
        x = jnp.ravel(x)
        axis = 0
    values = jax.lax.associative_scan(jnp.maximum, x, axis=axis)
    # index of the running max = first position attaining the running value
    eq = jnp.equal(jnp.moveaxis(values, axis, -1)[..., :, None],
                   jnp.moveaxis(x, axis, -1)[..., None, :])
    n = x.shape[axis]
    causal = jnp.tril(jnp.ones((n, n), bool))
    idx = jnp.argmax(eq & causal, axis=-1)
    indices = jnp.moveaxis(idx, -1, axis)
    return values, indices


def cummin(x, axis=None):
    values, indices = cummax(-x, axis=axis)
    return -values, indices


def logsumexp(x, axis=None, keepdim: bool = False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def logcumsumexp(x, axis=None):
    if axis is None:
        x = jnp.ravel(x)
        axis = 0
    # logaddexp is associative → a single XLA scan, numerically stable
    return jax.lax.associative_scan(jnp.logaddexp, x, axis=axis)


def count_nonzero(x, axis=None, keepdim: bool = False):
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdim)


def all(x, axis=None, keepdim: bool = False):
    return jnp.all(x, axis=axis, keepdims=keepdim)


def any(x, axis=None, keepdim: bool = False):
    return jnp.any(x, axis=axis, keepdims=keepdim)


def diff(x, n: int = 1, axis: int = -1):
    return jnp.diff(x, n=n, axis=axis)


def trace(x, offset: int = 0, axis1: int = 0, axis2: int = 1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def stanh(x, scale_a: float = 0.67, scale_b: float = 1.7159):
    return scale_b * jnp.tanh(scale_a * x)


def trapezoid(y, x=None, dx=None, axis: int = -1):
    if x is not None and dx is not None:
        raise ValueError("pass either x or dx, not both")
    y = jnp.asarray(y)
    y0 = jnp.take(y, jnp.arange(y.shape[axis] - 1), axis=axis)
    y1 = jnp.take(y, jnp.arange(1, y.shape[axis]), axis=axis)
    if x is not None:
        x = jnp.asarray(x)
        if x.ndim == 1:
            shape = [1] * y.ndim
            shape[axis] = x.shape[0]
            x = x.reshape(shape)
        d = (jnp.take(x, jnp.arange(1, x.shape[axis]), axis=axis)
             - jnp.take(x, jnp.arange(x.shape[axis] - 1), axis=axis))
    else:
        d = 1.0 if dx is None else dx
    return (0.5 * d * (y0 + y1)).sum(axis=axis)


def vander(x, n=None, increasing: bool = False):
    n = x.shape[0] if n is None else n
    powers = jnp.arange(n) if increasing else jnp.arange(n - 1, -1, -1)
    return x[:, None] ** powers[None, :]


# -- breadth (round 4): remaining documented math surface --------------------
# (upstream python/paddle/tensor/math.py; jnp/lax give the math directly,
# the work here is paddle's calling conventions.)

def addmm(input, x, y, beta: float = 1.0, alpha: float = 1.0):
    return beta * input + alpha * jnp.matmul(x, y)


def bincount(x, weights=None, minlength: int = 0):
    # jnp.bincount needs a static length; paddle's output length is
    # max(minlength, max(x)+1), resolved eagerly (host sync).  Inside jit
    # the max is a tracer, so minlength alone sizes the output — pass a
    # large-enough minlength there (values above it are DROPPED by the
    # static-shape clip, the documented jit caveat).
    import jax.core as _core
    length = minlength
    if not isinstance(x, _core.Tracer):
        m = int(jnp.max(x)) + 1 if x.size else 0
        length = m if m > minlength else minlength   # builtin max is shadowed
    return jnp.bincount(x, weights=weights, minlength=length,
                        length=length)


def cdist(x, y, p: float = 2.0):
    diff = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(diff * diff, axis=-1))
    if p == float("inf"):
        return jnp.max(jnp.abs(diff), axis=-1)
    if p == 0.0:
        return jnp.sum((diff != 0).astype(x.dtype), axis=-1)
    return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)


def combinations(x, r: int = 2, with_replacement: bool = False):
    import itertools
    n = x.shape[0]
    gen = (itertools.combinations_with_replacement(range(n), r)
           if with_replacement else itertools.combinations(range(n), r))
    idx = jnp.asarray(list(gen), dtype=jnp.int32).reshape(-1, r)
    return x[idx]


def copysign(x, y):
    return jnp.copysign(x, y)


def cumulative_trapezoid(y, x=None, dx=None, axis: int = -1):
    y = jnp.asarray(y)
    y0 = jnp.take(y, jnp.arange(y.shape[axis] - 1), axis=axis)
    y1 = jnp.take(y, jnp.arange(1, y.shape[axis]), axis=axis)
    if x is not None:
        x = jnp.asarray(x)
        if x.ndim == 1:
            shape = [1] * y.ndim
            shape[axis] = x.shape[0]
            x = x.reshape(shape)
        d = (jnp.take(x, jnp.arange(1, x.shape[axis]), axis=axis)
             - jnp.take(x, jnp.arange(x.shape[axis] - 1), axis=axis))
    else:
        d = 1.0 if dx is None else dx
    return jnp.cumsum(0.5 * d * (y0 + y1), axis=axis)


def diag_embed(x, offset: int = 0, dim1: int = -2, dim2: int = -1):
    n = x.shape[-1] + (offset if offset >= 0 else -offset)
    k = x.shape[-1]
    out = jnp.zeros(x.shape[:-1] + (n, n), dtype=x.dtype)
    rows = jnp.arange(k) + (0 if offset >= 0 else -offset)
    cols = jnp.arange(k) + (offset if offset >= 0 else 0)
    out = out.at[..., rows, cols].set(x)
    # move the two new axes to dim1/dim2
    nd = out.ndim
    dim1 = dim1 % nd
    dim2 = dim2 % nd
    if (dim1, dim2) != (nd - 2, nd - 1):
        out = jnp.moveaxis(out, (nd - 2, nd - 1), (dim1, dim2))
    return out


def diagonal(x, offset: int = 0, axis1: int = 0, axis2: int = 1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def frexp(x):
    return jnp.frexp(x)


def gammainc(x, y):
    return jax.scipy.special.gammainc(x, y)


def gammaincc(x, y):
    return jax.scipy.special.gammaincc(x, y)


def gammaln(x):
    return jax.scipy.special.gammaln(x)


def gcd(x, y):
    return jnp.gcd(x, y)


def hypot(x, y):
    return jnp.hypot(x, y)


def i0(x):
    return jax.scipy.special.i0(x)


def i0e(x):
    return jax.scipy.special.i0e(x)


def i1(x):
    return jax.scipy.special.i1(x)


def i1e(x):
    return jax.scipy.special.i1e(x)


def index_add(x, index, axis, value):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].add(value)


def index_fill(x, index, axis, value):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(value)


def index_put(x, indices, value, accumulate: bool = False):
    indices = tuple(indices)
    return (x.at[indices].add(value) if accumulate
            else x.at[indices].set(value))


def kron(x, y):
    return jnp.kron(x, y)


def lcm(x, y):
    return jnp.lcm(x, y)


def ldexp(x, y):
    return jnp.ldexp(x, y)


def logaddexp(x, y):
    return jnp.logaddexp(x, y)


def multigammaln(x, p: int):
    return jax.scipy.special.multigammaln(x, p)


def nextafter(x, y):
    return jnp.nextafter(x, y)


def polygamma(x, n: int):
    # paddle's argument order is (x, n); jax's is (n, x)
    return jax.scipy.special.polygamma(n, x)


def renorm(x, p: float, axis: int, max_norm: float):
    moved = jnp.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    out = flat * scale[:, None]
    return jnp.moveaxis(out.reshape(moved.shape), 0, axis)


def sgn(x):
    if jnp.iscomplexobj(x):
        # x * 0 builds the complex zero from an ARRAY — an eager Python
        # complex-scalar constant poisons the tunnel chip's backend
        # (tensor/fft.py chip notes)
        mag = jnp.abs(x)
        return jnp.where(mag == 0, x * 0, x / jnp.where(mag == 0, 1.0, mag))
    return jnp.sign(x)


def sinc(x):
    return jnp.sinc(x)


def take(x, index, mode: str = "raise"):
    flat = jnp.ravel(x)
    index = jnp.asarray(index)
    if mode == "wrap":
        index = jnp.mod(index, flat.shape[0])
    else:  # 'raise' can't raise inside jit; clip matches XLA gather semantics
        index = jnp.clip(index, -flat.shape[0], flat.shape[0] - 1)
    return flat[index]


def tensordot(x, y, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a
                     for a in axes)
    return jnp.tensordot(x, y, axes=axes)
