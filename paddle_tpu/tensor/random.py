"""Random tensor ops (parity surface: upstream python/paddle/tensor/random.py).

Stateful-looking API (``paddle.rand`` etc.) over jax's functional PRNG: each
call draws the next key from the framework's global key chain
(``paddle_tpu.seed`` / ``framework.random.next_key``), so results are
reproducible from ``seed()`` like the reference's global generator.
Inside ``jit``, pass an explicit ``key=`` instead (the global chain is a
host-side effect).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.dtype import to_jax_dtype
from ..framework.random import next_key

__all__ = [
    "rand", "randn", "randint", "randperm", "uniform", "normal",
    "standard_normal", "bernoulli", "multinomial", "poisson", "exponential",
    "shuffle",
    # breadth (round 4)
    "log_normal", "binomial", "standard_gamma",
]


def _key(key):
    return key if key is not None else next_key()


def _dt(dtype, default=jnp.float32):
    return to_jax_dtype(dtype) if dtype is not None else default


def rand(shape, dtype=None, key=None):
    return jax.random.uniform(_key(key), tuple(shape), _dt(dtype))


def randn(shape, dtype=None, key=None):
    return jax.random.normal(_key(key), tuple(shape), _dt(dtype))


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype="int64", key=None):
    if high is None:
        low, high = 0, low
    return jax.random.randint(_key(key), tuple(shape), low, high,
                              _dt(dtype, jnp.int32))


def randperm(n: int, dtype="int64", key=None):
    return jax.random.permutation(_key(key), n).astype(_dt(dtype, jnp.int32))


def uniform(shape, dtype=None, min=-1.0, max=1.0, key=None):
    return jax.random.uniform(_key(key), tuple(shape), _dt(dtype),
                              minval=min, maxval=max)


def normal(mean=0.0, std=1.0, shape=(1,), key=None):
    return mean + std * jax.random.normal(_key(key), tuple(shape))


def bernoulli(x, key=None):
    return (jax.random.uniform(_key(key), x.shape) < x).astype(x.dtype)


def multinomial(x, num_samples: int = 1, replacement: bool = False,
                key=None):
    """Sample category indices ∝ x along the last axis (Gumbel trick:
    argmax with replacement, top-k without)."""
    x = jnp.asarray(x)
    logits = jnp.log(x)
    k = _key(key)
    if replacement:
        g = jax.random.gumbel(k, (num_samples,) + x.shape)
        idx = jnp.argmax(logits + g, axis=-1)       # (num_samples, *batch)
        return jnp.moveaxis(idx, 0, -1)             # (*batch, num_samples)
    g = jax.random.gumbel(k, x.shape)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return idx


def poisson(x, key=None):
    return jax.random.poisson(_key(key), jnp.asarray(x)).astype(jnp.float32)


def exponential(x, key=None):
    return jax.random.exponential(_key(key), jnp.shape(x)).astype(
        jnp.asarray(x).dtype)


def shuffle(x, axis: int = 0, key=None):
    return jax.random.permutation(_key(key), x, axis=axis,
                                  independent=False)


# -- breadth (round 4) -------------------------------------------------------

def log_normal(mean=1.0, std=2.0, shape=(1,), key=None):
    """paddle.log_normal: exp of a Normal(mean, std) draw."""
    return jnp.exp(mean + std * jax.random.normal(_key(key), tuple(shape)))


def binomial(count, prob, key=None):
    count = jnp.asarray(count)
    prob = jnp.asarray(prob)
    shape = jnp.broadcast_shapes(count.shape, prob.shape)
    return jax.random.binomial(_key(key), count, prob, shape=shape).astype(
        jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)


def standard_gamma(x, key=None):
    return jax.random.gamma(_key(key), jnp.asarray(x))
