"""Search / sort ops (parity surface: upstream python/paddle/tensor/search.py).

``topk``/``sort`` lower to XLA's sort/top-k HLOs — no custom kernels.  Ops
with data-dependent output shapes (``nonzero``) are eager-only.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "nonzero", "searchsorted",
    "index_sample", "kthvalue", "mode", "median", "quantile", "histogram",
    "bucketize",
]


def argmax(x, axis=None, keepdim: bool = False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim)
    return out.astype(jnp.dtype(dtype) if dtype != "int64" else out.dtype)


def argmin(x, axis=None, keepdim: bool = False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim)
    return out.astype(jnp.dtype(dtype) if dtype != "int64" else out.dtype)


def argsort(x, axis: int = -1, descending: bool = False, stable: bool = True):
    out = jnp.argsort(x, axis=axis, stable=stable, descending=descending)
    return out


def sort(x, axis: int = -1, descending: bool = False, stable: bool = True):
    out = jnp.sort(x, axis=axis, stable=stable, descending=descending)
    return out


def topk(x, k: int, axis: int = -1, largest: bool = True,
         sorted: bool = True):
    """XLA top-k on the requested axis; ``largest=False`` via negation
    (the reference dispatches a dedicated bottom-k kernel)."""
    del sorted  # XLA top_k is always sorted
    x_moved = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = jax.lax.top_k(x_moved, k)
    else:
        vals, idx = jax.lax.top_k(-x_moved, k)
        vals = -vals
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis)


def nonzero(x, as_tuple: bool = False):
    """Data-dependent output shape → eager only (not jittable)."""
    import numpy as np
    idx = np.nonzero(np.asarray(x))
    if as_tuple:
        return tuple(jnp.asarray(i) for i in idx)
    return jnp.asarray(np.stack(idx, axis=1))


def searchsorted(sorted_sequence, values, out_int32: bool = False,
                 right: bool = False):
    out = jnp.searchsorted(sorted_sequence, values,
                           side="right" if right else "left")
    return out.astype(jnp.int32) if out_int32 else out


def index_sample(x, index):
    """Per-row gather: out[i, j] = x[i, index[i, j]]."""
    return jnp.take_along_axis(x, index, axis=1)


def kthvalue(x, k: int, axis: int = -1, keepdim: bool = False):
    vals = jnp.sort(x, axis=axis)
    idxs = jnp.argsort(x, axis=axis, stable=True)
    sel = jnp.take(vals, k - 1, axis=axis)
    sel_i = jnp.take(idxs, k - 1, axis=axis)
    if keepdim:
        sel = jnp.expand_dims(sel, axis)
        sel_i = jnp.expand_dims(sel_i, axis)
    return sel, sel_i


def mode(x, axis: int = -1, keepdim: bool = False):
    """Most frequent value (ties → smallest value), index of its last
    occurrence.  O(n²) pairwise count — fine for the op-parity surface;
    heavy histogramming belongs in user code."""
    xm = jnp.moveaxis(x, axis, -1)
    n = xm.shape[-1]
    cnt = jnp.sum(xm[..., :, None] == xm[..., None, :], axis=-1)
    maxc = jnp.max(cnt, axis=-1, keepdims=True)
    # min over max-count candidates; fill others with the row max (any mode
    # candidate is <= it, so fills never win the min)
    fill = jnp.max(xm, axis=-1, keepdims=True)
    val = jnp.min(jnp.where(cnt == maxc, xm, fill), axis=-1)
    eq = xm == val[..., None]
    idx = (n - 1) - jnp.argmax(jnp.flip(eq, axis=-1), axis=-1)
    if keepdim:
        val = jnp.expand_dims(val, axis)
        idx = jnp.expand_dims(idx, axis)
    return val, idx


def median(x, axis=None, keepdim: bool = False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim: bool = False):
    return jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim)


def histogram(x, bins: int = 100, min: float = 0.0, max: float = 0.0):
    rng = None if (min == 0.0 and max == 0.0) else (min, max)
    hist, _ = jnp.histogram(jnp.ravel(x), bins=bins, range=rng)
    return hist


def bucketize(x, sorted_sequence, out_int32: bool = False,
              right: bool = False):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)
