"""Tensor facade — paddle.Tensor method surface over jax.Array.

Design stance (vs the reference's ~200k-LoC ``python/paddle/tensor/`` +
C++ ``eager_method.cc`` method table): on TPU the array type IS
``jax.Array`` — it already carries the numpy-style method surface
(``.sum``, ``.reshape``, ``.astype``, arithmetic operators) and flows
through jit/grad/sharding natively, so the framework does NOT wrap arrays
by default.  This module adds the *paddle-specific* method names as an
opt-in facade:

  * ``Tensor(x)`` wraps any array-like; it is a registered pytree node, so
    wrapped values pass through ``jax.jit``/``jax.grad`` unchanged;
  * every public function in ``paddle_tpu.tensor`` is exposed as a method
    (``t.matmul(y)``, ``t.cast('float32')``, ``t.unsqueeze(0)``, ...) via
    dispatch-by-name — one source of truth, no 400-method class body;
  * arithmetic/comparison dunders, ``.numpy()``, ``.item()``, ``.clone()``,
    ``.T``, indexing, and ``__jax_array__`` (so wrapped tensors feed any
    jnp function directly).

Methods return plain jax.Arrays (unwrap-on-return): the facade is an entry
convenience, not a parallel type system.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["Tensor"]

_OPS = None


def _ops():
    global _OPS
    if _OPS is None:
        from .. import tensor as _t
        _OPS = _t
    return _OPS


def _unwrap(v):
    return v.value if isinstance(v, Tensor) else v


class Tensor:
    """Opt-in paddle.Tensor-method facade over a jax.Array."""

    __slots__ = ("value",)
    __array_priority__ = 100  # win binary ops vs numpy arrays

    def __init__(self, value):
        if isinstance(value, Tensor):
            value = value.value
        object.__setattr__(self, "value", jnp.asarray(value))

    # -- interop ------------------------------------------------------------
    def __jax_array__(self):
        return self.value

    def numpy(self):
        import numpy as np
        return np.asarray(self.value)

    def item(self):
        return self.value.item()

    def clone(self):
        return Tensor(jnp.array(self.value, copy=True))

    def detach(self):
        return Tensor(jax.lax.stop_gradient(self.value))

    def tolist(self):
        return self.value.tolist()

    def numel(self):
        return self.value.size

    def dim(self):
        return self.value.ndim

    ndimension = dim

    def element_size(self):
        return self.value.dtype.itemsize

    def astype(self, dtype):
        from ..framework.dtype import to_jax_dtype
        return Tensor(self.value.astype(to_jax_dtype(dtype)))

    def cpu(self):
        return Tensor(jax.device_put(
            self.value, jax.devices("cpu")[0]))

    def value_counts(self, sort: bool = True, ascending: bool = False):
        """(unique values, counts) — host-eager, like paddle's dynamic-
        shape op on XLA."""
        import numpy as np
        vals, counts = np.unique(np.asarray(self.value), return_counts=True)
        if sort:
            order = np.argsort(counts if ascending else -counts,
                               kind="stable")
            vals, counts = vals[order], counts[order]
        return Tensor(vals), Tensor(counts)

    def to_dense(self):
        from jax.experimental import sparse as jsparse
        if isinstance(self.value, (jsparse.BCOO, jsparse.BCSR)):
            return Tensor(self.value.todense())
        return Tensor(self.value)

    def to_sparse_coo(self, sparse_dim: Optional[int] = None):
        """Dense → sparse COO (host-eager: nse is data-dependent).
        ``sparse_dim`` < ndim gives paddle's hybrid layout: leading dims
        sparse, trailing dims dense (BCOO n_dense)."""
        from jax.experimental import sparse as jsparse
        ndim = self.value.ndim
        n_dense = 0 if sparse_dim is None else ndim - sparse_dim
        if n_dense < 0 or (sparse_dim is not None and sparse_dim < 1):
            raise ValueError(f"sparse_dim must be in [1, {ndim}], "
                             f"got {sparse_dim}")
        return jsparse.BCOO.fromdense(self.value, n_dense=n_dense)

    def to(self, *args, **kwargs):
        """paddle.Tensor.to(dtype) / .to(device): dtype strings cast;
        device strings re-place via jax.device_put."""
        out = self.value
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu"):
                kind = "cpu" if a == "cpu" else None
                devs = jax.devices(kind) if kind else jax.devices()
                out = jax.device_put(out, devs[0])
            else:
                from ..framework.dtype import to_jax_dtype
                out = out.astype(to_jax_dtype(a))
        return Tensor(out)

    # -- shape/dtype --------------------------------------------------------
    @property
    def shape(self):
        return list(self.value.shape)

    @property
    def ndim(self):
        return self.value.ndim

    @property
    def size(self):
        return self.value.size

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def T(self):
        return Tensor(self.value.T)

    def __len__(self):
        return len(self.value)

    # -- dispatch-by-name to paddle_tpu.tensor ------------------------------
    def __getattr__(self, name):
        ops = _ops()
        fn = getattr(ops, name, None)
        if fn is None or not callable(fn):
            # fall back to the jax.Array method surface (.mean, .astype, ...)
            attr = getattr(self.value, name)
            if callable(attr):
                return lambda *a, **k: attr(*[_unwrap(x) for x in a], **k)
            return attr

        def method(*args, **kwargs):
            args = [_unwrap(a) for a in args]
            kwargs = {k: _unwrap(v) for k, v in kwargs.items()}
            return fn(self.value, *args, **kwargs)
        return method

    # -- operators ----------------------------------------------------------
    def __getitem__(self, idx):
        return Tensor(self.value[_unwrap(idx)])

    def __repr__(self):
        return f"Tensor({self.value!r})"

    def __format__(self, spec):
        return format(self.value, spec)

    def __bool__(self):
        return bool(self.value)

    def __int__(self):
        return int(self.value)

    def __float__(self):
        return float(self.value)

    def __iter__(self):
        return (Tensor(v) for v in self.value)


def _binop(name, jnp_fn, reflected=False):
    if reflected:
        def op(self, other):
            return Tensor(jnp_fn(_unwrap(other), self.value))
    else:
        def op(self, other):
            return Tensor(jnp_fn(self.value, _unwrap(other)))
    op.__name__ = name
    setattr(Tensor, name, op)


for _name, _fn in [("__add__", jnp.add), ("__sub__", jnp.subtract),
                   ("__mul__", jnp.multiply), ("__truediv__", jnp.divide),
                   ("__floordiv__", jnp.floor_divide), ("__mod__", jnp.mod),
                   ("__pow__", jnp.power), ("__matmul__", jnp.matmul),
                   ("__eq__", jnp.equal), ("__ne__", jnp.not_equal),
                   ("__lt__", jnp.less), ("__le__", jnp.less_equal),
                   ("__gt__", jnp.greater), ("__ge__", jnp.greater_equal),
                   ("__and__", jnp.bitwise_and), ("__or__", jnp.bitwise_or),
                   ("__xor__", jnp.bitwise_xor)]:
    _binop(_name, _fn)
for _name, _fn in [("__radd__", jnp.add), ("__rsub__", jnp.subtract),
                   ("__rmul__", jnp.multiply), ("__rtruediv__", jnp.divide),
                   ("__rmatmul__", jnp.matmul), ("__rpow__", jnp.power)]:
    _binop(_name, _fn, reflected=True)
Tensor.__neg__ = lambda self: Tensor(jnp.negative(self.value))
Tensor.__abs__ = lambda self: Tensor(jnp.abs(self.value))
Tensor.__invert__ = lambda self: Tensor(jnp.bitwise_not(self.value))
Tensor.__hash__ = lambda self: id(self)

jax.tree_util.register_pytree_node(
    Tensor,
    lambda t: ((t.value,), None),
    lambda _, children: Tensor(children[0]))
