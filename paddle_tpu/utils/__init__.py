"""paddle_tpu.utils — logging + small shared helpers."""

from .logging import VLOG, get_logger, vlog_level

__all__ = ["get_logger", "VLOG", "vlog_level"]
