"""VLOG-style logging.

Parity: the reference's glog verbosity convention (``VLOG(n)`` in C++,
gated by the ``GLOG_v`` env var; Python logger at paddle/utils — upstream
layout).  ``VLOG(level, msg)`` emits only when ``level <= GLOG_v`` (or the
``glog_v`` flag); the standard logger carries framework warnings.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_LOGGER: Optional[logging.Logger] = None


def get_logger(name: str = "paddle_tpu", level: Optional[int] = None
               ) -> logging.Logger:
    global _LOGGER
    if _LOGGER is None or _LOGGER.name != name:
        logger = logging.getLogger(name)
        if not logger.handlers:
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(logging.Formatter(
                "%(levelname).1s %(asctime)s %(name)s] %(message)s",
                datefmt="%m%d %H:%M:%S"))
            logger.addHandler(h)
            logger.propagate = False
        logger.setLevel(level if level is not None else logging.INFO)
        _LOGGER = logger
    return _LOGGER


def vlog_level() -> int:
    """Active verbosity: GLOG_v env var (reference convention), else 0."""
    try:
        return int(os.environ.get("GLOG_v", "0"))
    except ValueError:
        return 0


def VLOG(level: int, msg: str, *args) -> None:
    """Emit ``msg`` when ``level <= GLOG_v`` — the reference's VLOG(n)."""
    if level <= vlog_level():
        get_logger().info("[v%d] " + msg, level, *args)


_vlog_once_seen: set = set()


def vlog_once(level: int, key: str, msg: str) -> None:
    """VLOG that fires at most once per distinct ``key`` per process —
    for fallback/perf-cliff warnings that would otherwise spam every call
    site (the reference's LOG_FIRST_N(1) convention)."""
    if key not in _vlog_once_seen:
        _vlog_once_seen.add(key)
        VLOG(level, msg)
