"""paddle.vision parity namespace (detection ops live in .ops)."""

from . import ops  # noqa: F401

__all__ = ["ops"]
