"""Detection ops (parity surface: upstream python/paddle/vision/ops.py).

The reference implements these as CUDA kernels (upstream layout:
paddle/phi/kernels/gpu/{nms,roi_align,roi_pool,...}_kernel.cu). On TPU the
dynamic-shape idioms those kernels rely on (variable box counts, per-bin
loops) don't map: everything here is re-expressed with static shapes —
masked O(N²) IoU matrices, gather-based bilinear sampling, masked-max
pooling — so the whole op stays one fused XLA program, jittable and
vmappable. Box counts are padding-tolerant: callers pad with zero-area
boxes and mask on the returned keep/score arrays, the standard TPU
detection recipe.

Not yet implemented (visible in the op registry's absent list):
distribute_fpn_proposals, generate_proposals, yolo_loss — see
framework/op_registry.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["nms", "roi_align", "roi_pool", "box_coder", "prior_box",
           "yolo_box", "matrix_nms", "psroi_pool", "deform_conv2d"]


def _iou_matrix(boxes):
    """Pairwise IoU for (N, 4) [x1, y1, x2, y2] boxes."""
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
        jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)
    lt = jnp.maximum(boxes[:, None, :2], boxes[None, :, :2])
    rb = jnp.minimum(boxes[:, None, 2:], boxes[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def nms(boxes, iou_threshold: float = 0.3, scores=None,
        category_idxs=None, categories=None, top_k=None):
    """Greedy NMS. Returns indices of kept boxes, highest score first.

    Static-shape formulation: one (N, N) IoU matrix + a fori_loop over the
    score-sorted order maintaining a keep mask — N iterations of O(N)
    vector work instead of the reference's dynamic output list. With
    category_idxs, suppression only applies within a category (the IoU
    matrix is masked by category equality), matching paddle's batched NMS.
    """
    n = boxes.shape[0]
    if scores is None:
        order = jnp.arange(n)
    else:
        order = jnp.argsort(-scores)
    iou = _iou_matrix(boxes)
    if category_idxs is not None:
        same = category_idxs[:, None] == category_idxs[None, :]
        iou = jnp.where(same, iou, 0.0)

    def body(i, keep):
        cand = order[i]
        # suppressed if any earlier-kept box overlaps above threshold
        earlier = jnp.arange(n) < i
        sup = jnp.any(keep[order] & earlier & (iou[cand, order] > iou_threshold))
        return keep.at[cand].set(~sup)

    keep = jax.lax.fori_loop(0, n, body, jnp.zeros((n,), dtype=bool))
    kept_sorted = order[keep[order]]       # data-dependent: host/eager only
    if top_k is not None:
        kept_sorted = kept_sorted[:top_k]
    return kept_sorted


def roi_align(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0,
              sampling_ratio: int = -1, aligned: bool = True):
    """RoIAlign (Mask R-CNN). x: (N, C, H, W); boxes: (R, 4) in input coords.

    Bilinear sampling is a gather of the four neighbours per sample point,
    batched over (roi, channel, bin, sample) in one take — no per-bin loop.
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    n, c, h, w = x.shape
    ratio = 4 if sampling_ratio <= 0 else sampling_ratio
    offset = 0.5 if aligned else 0.0

    # map each roi to its batch image from boxes_num (static counts)
    import numpy as np
    counts = np.asarray(boxes_num)
    batch_idx = jnp.asarray(np.repeat(np.arange(len(counts)), counts))

    bx = boxes * spatial_scale - offset
    x1, y1, x2, y2 = bx[:, 0], bx[:, 1], bx[:, 2], bx[:, 3]
    if not aligned:
        x2 = jnp.maximum(x2, x1 + 1.0)
        y2 = jnp.maximum(y2, y1 + 1.0)
    bin_h = (y2 - y1) / ph
    bin_w = (x2 - x1) / pw

    # sample-point grids: (R, ph*ratio), (R, pw*ratio)
    gy = (y1[:, None] + (jnp.arange(ph * ratio) + 0.5)[None, :]
          * (bin_h / ratio)[:, None])
    gx = (x1[:, None] + (jnp.arange(pw * ratio) + 0.5)[None, :]
          * (bin_w / ratio)[:, None])

    def sample(img, ys, xs):
        """img: (C, H, W); ys: (Sy,), xs: (Sx,) → (C, Sy, Sx) bilinear."""
        y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        wy = jnp.clip(ys - y0, 0.0, 1.0)
        wx = jnp.clip(xs - x0, 0.0, 1.0)
        y0 = y0.astype(jnp.int32)
        x0 = x0.astype(jnp.int32)
        v00 = img[:, y0][:, :, x0]
        v01 = img[:, y0][:, :, x1i]
        v10 = img[:, y1i][:, :, x0]
        v11 = img[:, y1i][:, :, x1i]
        return (v00 * (1 - wy)[:, None] * (1 - wx)[None, :]
                + v01 * (1 - wy)[:, None] * wx[None, :]
                + v10 * wy[:, None] * (1 - wx)[None, :]
                + v11 * wy[:, None] * wx[None, :])

    vals = jax.vmap(sample)(x[batch_idx], gy, gx)     # (R, C, ph*r, pw*r)
    vals = vals.reshape(vals.shape[0], c, ph, ratio, pw, ratio)
    return vals.mean(axis=(3, 5))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale: float = 1.0):
    """RoIPool (Fast R-CNN): max over integer bins.

    Variable bin extents under static shapes: a (ph, pw, H, W) membership
    mask per roi and a masked max — O(ph·pw·H·W) vector work that XLA
    fuses, versus the reference's dynamic per-bin CUDA loop.
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    n, c, h, w = x.shape
    import numpy as np
    counts = np.asarray(boxes_num)
    batch_idx = jnp.asarray(np.repeat(np.arange(len(counts)), counts))

    bx = jnp.round(boxes * spatial_scale)
    x1, y1 = bx[:, 0], bx[:, 1]
    x2, y2 = jnp.maximum(bx[:, 2], x1 + 1), jnp.maximum(bx[:, 3], y1 + 1)
    bin_h = (y2 - y1) / ph
    bin_w = (x2 - x1) / pw

    def pool_one(img, bx1, by1, bw, bh):
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        i = jnp.arange(ph, dtype=jnp.float32)
        j = jnp.arange(pw, dtype=jnp.float32)
        y_lo = jnp.floor(by1 + i * bh)[:, None]          # (ph, 1)
        y_hi = jnp.ceil(by1 + (i + 1) * bh)[:, None]
        x_lo = jnp.floor(bx1 + j * bw)[:, None]          # (pw, 1)
        x_hi = jnp.ceil(bx1 + (j + 1) * bw)[:, None]
        ymask = (ys >= y_lo) & (ys < y_hi)               # (ph, H)
        xmask = (xs >= x_lo) & (xs < x_hi)               # (pw, W)
        mask = ymask[:, None, :, None] & xmask[None, :, None, :]
        masked = jnp.where(mask[None], img[:, None, None, :, :], -jnp.inf)
        out = masked.max(axis=(-1, -2))                  # (C, ph, pw)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(pool_one)(x[batch_idx], x1, y1, bin_w, bin_h)


def box_coder(prior_box, prior_box_var, target_box,
              code_type: str = "encode_center_size", box_normalized: bool = True,
              axis: int = 0):
    """Encode boxes to deltas / decode deltas to boxes (SSD-style)."""
    norm = 0.0 if box_normalized else 1.0
    pw = prior_box[:, 2] - prior_box[:, 0] + norm
    ph = prior_box[:, 3] - prior_box[:, 1] + norm
    px = prior_box[:, 0] + pw * 0.5
    py = prior_box[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((4,), dtype=target_box.dtype)
        vx, vy, vw, vh = var
    else:
        pv = jnp.asarray(prior_box_var)
        if pv.ndim == 1:
            vx, vy, vw, vh = pv
        else:
            vx, vy, vw, vh = pv[:, 0], pv[:, 1], pv[:, 2], pv[:, 3]

    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0] + norm
        th = target_box[:, 3] - target_box[:, 1] + norm
        tx = target_box[:, 0] + tw * 0.5
        ty = target_box[:, 1] + th * 0.5
        return jnp.stack([(tx - px) / pw / vx, (ty - py) / ph / vy,
                          jnp.log(tw / pw) / vw, jnp.log(th / ph) / vh],
                         axis=1)
    elif code_type == "decode_center_size":
        if target_box.ndim == 2:
            target_box = target_box[:, None, :]
        dx, dy = target_box[..., 0], target_box[..., 1]
        dw, dh = target_box[..., 2], target_box[..., 3]
        if axis == 0:
            px_, py_, pw_, ph_ = px[:, None], py[:, None], pw[:, None], ph[:, None]
        else:
            px_, py_, pw_, ph_ = px[None, :], py[None, :], pw[None, :], ph[None, :]
        ox = dx * vx * pw_ + px_
        oy = dy * vy * ph_ + py_
        ow = jnp.exp(dw * vw) * pw_
        oh = jnp.exp(dh * vh) * ph_
        out = jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                         ox + ow * 0.5 - norm, oy + oh * 0.5 - norm], axis=-1)
        return out.squeeze(1) if out.shape[1] == 1 else out
    raise ValueError(f"unknown code_type {code_type!r}")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip: bool = False,
              clip: bool = False, steps=(0.0, 0.0), offset: float = 0.5,
              min_max_aspect_ratios_order: bool = False):
    """SSD prior (anchor) boxes for one feature map. Pure index math."""
    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_h = steps[1] if steps[1] > 0 else ih / fh
    step_w = steps[0] if steps[0] > 0 else iw / fw

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    whs = []
    for ms in min_sizes:
        whs.append((ms, ms))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            whs.append(((ms * mx) ** 0.5, (ms * mx) ** 0.5))
        for ar in ars:
            if abs(ar - 1.0) < 1e-6:
                continue
            whs.append((ms * ar ** 0.5, ms / ar ** 0.5))
    whs = jnp.asarray(whs)                      # (P, 2)

    cy = (jnp.arange(fh) + offset) * step_h
    cx = (jnp.arange(fw) + offset) * step_w
    cxg, cyg = jnp.meshgrid(cx, cy)             # (fh, fw)
    centers = jnp.stack([cxg, cyg], axis=-1)[:, :, None, :]     # (fh,fw,1,2)
    half = (whs * 0.5)[None, None, :, :]
    boxes = jnp.concatenate([centers - half, centers + half], axis=-1)
    boxes = boxes / jnp.asarray([iw, ih, iw, ih], boxes.dtype)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance), boxes.shape)
    return boxes, var


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox: bool = True, scale_x_y: float = 1.0,
             iou_aware: bool = False, iou_aware_factor: float = 0.5):
    """Decode YOLOv3 head output to boxes + scores.

    x: (N, A*(5+C), H, W); returns (boxes (N, A*H*W, 4), scores (N, A*H*W, C)).
    """
    if iou_aware:
        raise NotImplementedError(
            "iou_aware yolo_box (extra per-anchor IoU channel blended into "
            "conf) is not implemented — registry work queue")
    n, _, h, w = x.shape
    na = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    x = x.reshape(n, na, 5 + class_num, h, w)

    gx = jnp.arange(w, dtype=jnp.float32)
    gy = jnp.arange(h, dtype=jnp.float32)
    bias = (scale_x_y - 1.0) * 0.5
    px = (jax.nn.sigmoid(x[:, :, 0]) * scale_x_y - bias + gx[None, None, None, :]) / w
    py = (jax.nn.sigmoid(x[:, :, 1]) * scale_x_y - bias + gy[None, None, :, None]) / h
    input_w = downsample_ratio * w
    input_h = downsample_ratio * h
    pw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / input_w
    ph = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / input_h

    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:])
    scores = conf[:, :, None] * probs                # (N, A, C, H, W)
    scores = jnp.where(conf[:, :, None] >= conf_thresh, scores, 0.0)

    imh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (px - pw * 0.5) * imw
    y1 = (py - ph * 0.5) * imh
    x2 = (px + pw * 0.5) * imw
    y2 = (py + ph * 0.5) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, imw - 1)
        y1 = jnp.clip(y1, 0.0, imh - 1)
        x2 = jnp.clip(x2, 0.0, imw - 1)
        y2 = jnp.clip(y2, 0.0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)     # (N, A, H, W, 4)
    boxes = boxes.reshape(n, na * h * w, 4)
    scores = jnp.moveaxis(scores, 2, -1).reshape(n, na * h * w, class_num)
    return boxes, scores


# -- round-4 queue shrink -----------------------------------------------------

def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k: int = 400, keep_top_k: int = 200,
               use_gaussian: bool = False, gaussian_sigma: float = 2.0,
               background_label: int = 0, normalized: bool = True,
               return_index: bool = False, return_rois_num: bool = True):
    """Matrix NMS (SOLOv2): fully-parallel soft suppression — no greedy
    loop.  For each candidate the decay is min over higher-scored
    same-class boxes j of f(iou_ij)/f(iou_max_j); scores decay instead of
    boxes dying, then a single threshold keeps survivors.  This is the
    one NMS variant whose reference CUDA kernel is already matrix-shaped,
    so the TPU expression is the natural one.

    bboxes: (N, M, 4); scores: (N, C, M).  Returns (out (K, 6)
    [label, score, x1, y1, x2, y2], [index], rois_num) with host-side
    selection (data-dependent K, like the reference's dynamic output).
    """
    import numpy as np

    def np_iou(bx):
        area = (np.maximum(bx[:, 2] - bx[:, 0], 0)
                * np.maximum(bx[:, 3] - bx[:, 1], 0))
        lt = np.maximum(bx[:, None, :2], bx[None, :, :2])
        rb = np.minimum(bx[:, None, 2:], bx[None, :, 2:])
        wh = np.maximum(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        union = area[:, None] + area[None, :] - inter
        return np.where(union > 0, inter / union, 0.0)

    outs, idxs, nums = [], [], []
    bboxes_np = np.asarray(bboxes)     # one device sync; loops stay host-side
    scores_np = np.asarray(scores)
    n, c, m = scores_np.shape
    for b in range(n):
        cand = []
        for cls in range(c):
            if cls == background_label:
                continue
            sc = scores_np[b, cls]
            keep = np.nonzero(sc > score_threshold)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[keep])][:nms_top_k]
            iou = np_iou(bboxes_np[b][order])
            s = sc[order]
            k = len(order)
            upper = np.triu(iou, 1)      # upper[i, j]: iou, i higher-scored
            iou_max = upper.max(axis=0)  # box i's max iou w/ its suppressors
            # decay[i, j] = f(iou_ij) / f(iou_max_i): suppressor i's own
            # suppression compensates the denominator (SOLOv2 eq. 5)
            if use_gaussian:
                decay = np.exp(-(upper ** 2 - iou_max[:, None] ** 2)
                               / gaussian_sigma)
            else:
                decay = (1.0 - upper) / np.maximum(1.0 - iou_max[:, None],
                                                   1e-10)
            decay = np.where(np.triu(np.ones((k, k), bool), 1), decay, 1.0)
            decayed = s * decay.min(axis=0)
            for i in range(k):
                if decayed[i] > post_threshold:
                    cand.append((cls, decayed[i], order[i]))
        cand.sort(key=lambda t: -t[1])
        cand = cand[:keep_top_k]
        rows = np.asarray(
            [[cls, s, *bboxes_np[b][i]] for cls, s, i in cand],
            np.float32).reshape(-1, 6)
        outs.append(rows)
        idxs.extend(b * m + i for _, _, i in cand)
        nums.append(len(cand))
    out = jnp.asarray(np.concatenate(outs, axis=0) if outs
                      else np.zeros((0, 6), np.float32))
    res = [out]
    if return_index:
        res.append(jnp.asarray(np.asarray(idxs, np.int64).reshape(-1, 1)))
    if return_rois_num:
        res.append(jnp.asarray(np.asarray(nums, np.int32)))
    return res[0] if len(res) == 1 else tuple(res)


def psroi_pool(x, boxes, boxes_num, output_channels: int,
               spatial_scale: float = 1.0, pooled_height: int = 1,
               pooled_width: int = 1):
    """Position-sensitive RoI pooling (R-FCN): output channel c at bin
    (i, j) AVERAGE-pools input channel c·ph·pw + i·pw + j over the bin —
    same masked-reduction formulation as roi_pool, with the channel
    gather expressed as one reshape."""
    import numpy as np

    ph, pw = pooled_height, pooled_width
    n, cin, h, w = x.shape
    if cin != output_channels * ph * pw:
        raise ValueError(f"psroi_pool: in_channels {cin} != "
                         f"output_channels*ph*pw {output_channels*ph*pw}")
    counts = np.asarray(boxes_num)
    batch_idx = jnp.asarray(np.repeat(np.arange(len(counts)), counts))

    bx = boxes * spatial_scale
    x1, y1 = jnp.round(bx[:, 0]), jnp.round(bx[:, 1])
    x2 = jnp.maximum(jnp.round(bx[:, 2]), x1 + 1)
    y2 = jnp.maximum(jnp.round(bx[:, 3]), y1 + 1)
    bin_h = (y2 - y1) / ph
    bin_w = (x2 - x1) / pw
    # (R, C, ph, pw, H, W) masked mean, with C mapped per (i, j)
    feat = x.reshape(n, output_channels, ph, pw, h, w)

    def pool_one(img, bx1, by1, bw, bh):
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        i = jnp.arange(ph, dtype=jnp.float32)
        j = jnp.arange(pw, dtype=jnp.float32)
        y_lo = jnp.floor(by1 + i * bh)[:, None]
        y_hi = jnp.ceil(by1 + (i + 1) * bh)[:, None]
        x_lo = jnp.floor(bx1 + j * bw)[:, None]
        x_hi = jnp.ceil(bx1 + (j + 1) * bw)[:, None]
        ymask = (ys >= y_lo) & (ys < y_hi)               # (ph, H)
        xmask = (xs >= x_lo) & (xs < x_hi)               # (pw, W)
        mask = (ymask[:, None, :, None]
                & xmask[None, :, None, :]).astype(jnp.float32)
        # img: (C, ph, pw, H, W) — bin (i,j) pools its own channel slice
        num = jnp.einsum("cijhw,ijhw->cij", img, mask)
        den = jnp.maximum(mask.sum(axis=(-1, -2)), 1.0)
        return num / den[None]

    return jax.vmap(pool_one)(feat[batch_idx], x1, y1, bin_w, bin_h)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups: int = 1, groups: int = 1,
                  mask=None):
    """Deformable convolution v1/v2 (parity: paddle.vision.ops.
    deform_conv2d; reference kernel paddle/phi/kernels/gpu/
    deformable_conv_kernel.cu).

    TPU formulation: per kernel tap k the sampling locations are the
    regular grid + the learned offsets; sampling is one batched bilinear
    gather (grid_sample's math), giving (N, Cin, K, Ho, Wo) columns that a
    single einsum contracts with the weights — im2col with learned
    coordinates, MXU-friendly, no per-pixel loop.

    x: (N, Cin, H, W); offset: (N, 2·dg·kh·kw, Ho, Wo) ordered (y, x) per
    tap; mask (v2): (N, dg·kh·kw, Ho, Wo); weight: (Cout, Cin/groups, kh,
    kw).
    """
    n, cin, h, w = x.shape
    cout, cpg, kh, kw = weight.shape
    k = kh * kw
    dg = deformable_groups
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p_h, p_w = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dh, dw = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    ho = (h + 2 * p_h - (dh * (kh - 1) + 1)) // sh + 1
    wo = (w + 2 * p_w - (dw * (kw - 1) + 1)) // sw + 1

    # base sampling grid per tap: (K, Ho, Wo)
    oy = jnp.arange(ho) * sh - p_h
    ox = jnp.arange(wo) * sw - p_w
    ky, kx = jnp.meshgrid(jnp.arange(kh) * dh, jnp.arange(kw) * dw,
                          indexing="ij")
    base_y = ky.reshape(k, 1, 1) + oy[None, :, None]
    base_x = kx.reshape(k, 1, 1) + ox[None, None, :]

    off = offset.reshape(n, dg, k, 2, ho, wo)
    sy = base_y[None, None] + off[:, :, :, 0]            # (N, dg, K, Ho, Wo)
    sx = base_x[None, None] + off[:, :, :, 1]

    def sample_chan_group(img, gy, gx):
        """img: (C', H, W); gy/gx: (K, Ho, Wo) → (C', K, Ho, Wo)."""
        y0 = jnp.floor(gy)
        x0 = jnp.floor(gx)
        wy = gy - y0
        wx = gx - x0
        out = 0.0
        for ddy, ddx, wgt in [(0, 0, (1 - wy) * (1 - wx)),
                              (0, 1, (1 - wy) * wx),
                              (1, 0, wy * (1 - wx)),
                              (1, 1, wy * wx)]:
            yi = (y0 + ddy).astype(jnp.int32)
            xi = (x0 + ddx).astype(jnp.int32)
            valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
            vals = img[:, jnp.clip(yi, 0, h - 1), jnp.clip(xi, 0, w - 1)]
            out = out + jnp.where(valid[None], vals * wgt[None], 0.0)
        return out

    # split channels over deformable groups, sample, stack back
    xg = x.reshape(n, dg, cin // dg, h, w)
    cols = jax.vmap(jax.vmap(sample_chan_group))(
        xg, sy, sx)                                     # (N, dg, C/dg, K, Ho, Wo)
    cols = cols.reshape(n, cin, k, ho, wo)
    if mask is not None:
        m = mask.reshape(n, dg, 1, k, ho, wo)
        cols = (cols.reshape(n, dg, cin // dg, k, ho, wo) * m
                ).reshape(n, cin, k, ho, wo)

    wmat = weight.reshape(groups, cout // groups, cpg, k)
    colsg = cols.reshape(n, groups, cpg, k, ho, wo)
    out = jnp.einsum("ngckhw,gock->ngohw", colsg, wmat)
    out = out.reshape(n, cout, ho, wo)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out
